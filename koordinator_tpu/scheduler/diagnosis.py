"""Schedule diagnosis: structured "why unschedulable" explanations.

Equivalent of ``frameworkext/schedule_diagnosis.go:44-108`` — when a pod fails
to place, report how many nodes each filter stage eliminated, so operators see
"0/128 nodes available: 96 insufficient cpu, 30 usage over threshold, 2
affinity mismatch" instead of a bare failure.

The stage masks are recomputed per failed pod (failures are rare relative to
the hot path, and the per-stage breakdown is exactly what score_pods fuses
away for speed).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops import filtering, scoring
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch


@dataclasses.dataclass
class PodDiagnosis:
    """Counts of nodes eliminated per stage (a node counts once, first-fail)."""

    total_nodes: int
    feasible_nodes: int
    insufficient_resources: int
    usage_over_threshold: int
    affinity_mismatch: int
    quota_rejected: bool
    invalid: int
    #: PostFilter outcome: nominated node + victims when preemption helps
    #: (schedule_diagnosis.go records the same on the explanation)
    preempt_node: str | None = None
    preempt_victims: list[str] = dataclasses.field(default_factory=list)
    #: fine-grained reject-reason counts keyed by ops/explain.REASON_NAMES
    #: (per-dim fit, threshold, affinity, plus host-filled pod-level
    #: gates); None when the explain accounting was disabled
    reason_counts: dict[str, int] | None = None

    def message(self) -> str:
        msg = self._base_message()
        if self.preempt_node is not None:
            victims = ", ".join(self.preempt_victims)
            msg += (f"; fits on {self.preempt_node} after preempting "
                    f"[{victims}]")
        return msg

    def _base_message(self) -> str:
        if self.quota_rejected:
            return "pod rejected by elastic quota admission"
        parts = []
        if self.insufficient_resources:
            parts.append(f"{self.insufficient_resources} insufficient resources")
        if self.usage_over_threshold:
            parts.append(f"{self.usage_over_threshold} usage over threshold")
        if self.affinity_mismatch:
            parts.append(f"{self.affinity_mismatch} didn't match node selector")
        detail = ", ".join(parts) if parts else "no failure recorded"
        return (f"{self.feasible_nodes}/{self.total_nodes} nodes available: "
                f"{detail}")


def explain_pod(
    state: ClusterState,
    pods: PodBatch,
    cfg: ScoringConfig,
    pod_idx: int,
    quota_admitted: bool = True,
) -> PodDiagnosis:
    """Stage-by-stage elimination breakdown for one pod of the batch."""
    req = pods.requests[pod_idx][None, :]
    pod_est = scoring.estimate_pod_usage_by_band(
        req, cfg.estimator_factors, cfg.estimator_defaults
    )
    valid = np.asarray(state.node_valid)
    total = int(valid.sum())

    fit = np.asarray(filtering.fit_mask(state.free, req)[0]) & valid
    inst = filtering.usage_threshold_mask(
        state.node_usage, state.node_allocatable, cfg.usage_thresholds, pod_est
    )
    agg = filtering.usage_threshold_mask(
        state.node_agg_usage, state.node_allocatable,
        cfg.agg_usage_thresholds, pod_est,
    )
    agg_enabled = bool(jnp.any(cfg.agg_usage_thresholds > 0))
    thr = np.asarray((agg if agg_enabled else inst)[0]) & valid
    aff = np.asarray(pods.feasible_row(state, pod_idx)) & valid

    feasible = fit & thr & aff
    # first-fail attribution, in filter order: fit -> thresholds -> affinity
    fail_fit = valid & ~fit
    fail_thr = valid & fit & ~thr
    fail_aff = valid & fit & thr & ~aff

    # per-dim first-fail fit counts: the NumPy oracle the device kernel
    # (ops/explain.explain_counts) is tested against
    from koordinator_tpu.ops import explain as ex

    free = np.asarray(state.free)
    r = np.asarray(req)[0]
    dim_ok = (r[None, :] <= free) | (r[None, :] == 0)        # (N, R)
    fails = ~dim_ok
    prior = np.cumsum(fails, axis=-1) - fails
    ff = fails & (prior == 0)                                # (N, R)
    counts = {name: 0 for name in ex.REASON_NAMES}
    counts["node_invalid"] = int((~valid).sum())
    for d in range(ff.shape[1]):
        counts[ex.REASON_NAMES[ex.REASON_FIT_FIRST + d]] = int(
            (fail_fit & ff[:, d]).sum())
    counts["usage_threshold"] = int(fail_thr.sum())
    counts["affinity"] = int(fail_aff.sum())

    return PodDiagnosis(
        total_nodes=total,
        feasible_nodes=int(feasible.sum()) if quota_admitted else 0,
        insufficient_resources=int(fail_fit.sum()),
        usage_over_threshold=int(fail_thr.sum()),
        affinity_mismatch=int(fail_aff.sum()),
        quota_rejected=not quota_admitted,
        invalid=int((~valid).sum()),
        reason_counts=counts,
    )


def diagnosis_from_counts(
    counts: np.ndarray,      # (NUM_REASONS,) int — one pod's kernel row
    feasible: int,
    total_nodes: int,
    quota_admitted: bool = True,
) -> PodDiagnosis:
    """Build a :class:`PodDiagnosis` from one row of the device kernel's
    reduction (``ops/explain.explain_counts``) — the batched replacement
    for recomputing :func:`explain_pod` per failed pod on host."""
    from koordinator_tpu.ops import explain as ex

    counts = np.asarray(counts)
    reason_counts = {
        name: int(counts[i]) for i, name in enumerate(ex.REASON_NAMES)
    }
    fit_total = int(
        counts[ex.REASON_FIT_FIRST:ex.REASON_USAGE_THRESHOLD].sum())
    return PodDiagnosis(
        total_nodes=total_nodes,
        feasible_nodes=int(feasible) if quota_admitted else 0,
        insufficient_resources=fit_total,
        usage_over_threshold=int(counts[ex.REASON_USAGE_THRESHOLD]),
        affinity_mismatch=int(counts[ex.REASON_AFFINITY]),
        quota_rejected=not quota_admitted,
        invalid=int(counts[ex.REASON_NODE_INVALID]),
        reason_counts=reason_counts,
    )
