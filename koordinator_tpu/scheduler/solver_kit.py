"""Shared jitted solver entries: ONE compiled solver for N tenants.

Before the tenancy subsystem (ISSUE 11) every :class:`Scheduler` built
its own ``jax.jit`` wrappers in ``__init__``, so two schedulers in one
process compiled two identical copies of every solve program.  A
multi-tenant front-end (``scheduler/tenancy.py``) runs one ``Scheduler``
per cluster — T tenants must multiplex onto ONE solver, sharing the jit
caches, the recompile accounting, and the mesh, exactly the way the
paper's shared-capacity argument sizes one pool to aggregate demand.

The kit owns:

- the solve mesh (``parallel/mesh.resolve_solver_mesh`` — sharded by
  default, ``KOORD_SOLVER_MESH``/``KOORD_SOLVER_MESH_MIN_NODES``
  overrides);
- every instrumented jitted entry point of the batch solver (full
  gang_assign, candidate selection/refresh/scatter, the propose/accept
  passes and their sharded twins, the reservation pre-pass solve, the
  preemption kernels, explain/slack reductions).

Shape buckets in the recompile accounting derive the ``@Nshard`` suffix
from the ARGUMENTS (state capacity vs the mesh floor), not from any one
scheduler's snapshot, so a shared kit labels each tenant's compiles
correctly even when tenants straddle the sharding floor.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


class SolverKit:
    """The device half's toolbox, shareable across Scheduler instances.

    Construction is cheap (wrapping, not compiling); compilation happens
    per (entry, shape bucket) on first use and is shared by every
    scheduler holding the kit.
    """

    def __init__(self, mesh="auto", shard_min_nodes: int = 1024):
        from koordinator_tpu.ops import batch_assign as _ba
        from koordinator_tpu.ops import explain as _ex
        from koordinator_tpu.ops import introspection as insp
        from koordinator_tpu.ops.gang import gang_assign
        from koordinator_tpu.ops.preemption import preempt_chain, preempt_one
        from koordinator_tpu.ops.reservation import reservation_greedy_assign
        from koordinator_tpu.parallel import mesh as pmesh
        from koordinator_tpu.parallel import sharded as psharded
        from koordinator_tpu.quality.lp_pack import lp_pack_assign
        from koordinator_tpu.quality.topo_gang import gang_topo_diameter

        # -- sharded-by-default solve mesh (ISSUE 10, 2-D since ISSUE 14) --
        # the node axis of the batch solve shards over every visible
        # device (a pods axis splits off via KOORD_SOLVER_MESH=PxN /
        # KOORD_SOLVER_MESH_PODS); tiny clusters stay single-device —
        # sharding a 64-node problem is pure collective overhead — via
        # the min-nodes floor.
        self.mesh = pmesh.resolve_solver_mesh(mesh)
        self.shard_min_nodes = int(os.environ.get(
            "KOORD_SOLVER_MESH_MIN_NODES", shard_min_nodes))
        self.shards = pmesh.nodes_shard_count(self.mesh)
        self.pod_shards = pmesh.pods_shard_count(self.mesh)
        self.node_sharding = (pmesh.node_sharding(self.mesh)
                              if self.mesh is not None else None)
        self.pod_sharding = (pmesh.pod_sharding(self.mesh)
                             if self.mesh is not None else None)

        def _active(n_cap: int) -> bool:
            """Does THIS capacity solve on the sharded path?  The same
            predicate ``ClusterSnapshot.solver_sharding_active`` applies
            to its own capacity — derived from the args so a shared kit
            labels each tenant correctly."""
            return (self.mesh is not None
                    and n_cap % self.shards == 0
                    and n_cap >= self.shard_min_nodes)

        self.sharding_active_for = _active

        def _pods_shardable(p_cap: int) -> bool:
            """Does THIS pod-batch capacity split over the pods axis?
            Power-of-two batch bucketing guarantees it for power-of-two
            pods_axis sizes; an odd env-forced axis just falls back."""
            return self.mesh is not None and p_cap % self.pod_shards == 0

        self.pods_shardable = _pods_shardable

        def _sfx(n_cap: int) -> str:
            if not _active(n_cap):
                return ""
            # the pods=1 form keeps the historical label so recompile
            # dashboards don't fork a new shape bucket on upgrade
            if self.pod_shards > 1:
                return f"@{self.pod_shards}x{self.shards}shard"
            return f"@{self.shards}shard"

        def _pn(args, kwargs):
            return (f"P{args[1].capacity}xN{args[0].capacity}"
                    f"{_sfx(args[0].capacity)}")

        # solve-state donation: the caller's snapshot.state is dead the
        # moment the call starts (XLA updates the (N, R) accounting in
        # place) and must be replaced wholesale by the returned state.
        # Every jitted entry point is wrapped for recompile accounting
        # (ops/introspection): a cache miss lands in
        # solver_recompiles_total{fn, shape}.
        self.solve = insp.instrument(
            jax.jit(gang_assign,
                    static_argnames=("passes", "solver"),
                    donate_argnums=(0,)),
            "gang_assign", shape_of=_pn)
        # explicit shard_map twin of the gang/greedy solve (ISSUE 14):
        # same signature prefix as gang_assign, so the scheduler swaps
        # entries without re-plumbing; the GSPMD-placed self.solve stays
        # the fallback for dense-feasibility (hinted) batches and
        # capacities the mesh doesn't divide
        self.solve_sh = None
        if self.mesh is not None:
            from functools import partial as _gpartial

            # koordlint: shape[arg0: NxR i32 nodes]
            self.solve_sh = insp.instrument(
                jax.jit(_gpartial(psharded.sharded_gang_assign, self.mesh),
                        static_argnames=("passes", "solver", "k",
                                         "rounds", "spread_bits"),
                        donate_argnums=(0,)),
                "gang_assign", shape_of=_pn)

        self.select_scored = insp.instrument(
            jax.jit(_ba.select_candidates,
                    static_argnames=("k", "spread_bits", "method",
                                     "with_scores")),
            "select_candidates", shape_of=_pn)
        self.align_cands = insp.instrument(
            jax.jit(_ba.align_candidate_cache),
            "align_candidate_cache",
            shape_of=lambda a, k: (f"P{a[1].shape[0]}xN{a[3].shape[0]}"))
        self.refresh_cands = insp.instrument(
            jax.jit(_ba.refresh_candidates,
                    static_argnames=("k", "spread_bits"),
                    donate_argnums=(3,)),
            "refresh_candidates",
            shape_of=lambda a, k: (f"P{a[1].capacity}xN{a[0].capacity}"
                                   f"xD{a[4].shape[0]}"))
        self.scatter_cands = insp.instrument(
            jax.jit(_ba.scatter_candidate_rows, donate_argnums=(0,)),
            "scatter_candidate_rows",
            shape_of=lambda a, k: (f"P{a[0].cand_key.shape[0]}"
                                   f"xS{a[1].shape[0]}"))
        # the shape annotations on the pass entries are specflow seed
        # contracts (tools/koordlint/specflow): arg0 is ONE tenant's
        # (N, R) state — a tenant-stacked (T, N, R) tensor reaching
        # these bindings is a tenant-axis finding, not a solve
        # koordlint: shape[arg0: NxR i32 nodes]
        self.pass1 = insp.instrument(
            jax.jit(_ba.assign_round_pass,
                    static_argnames=("rounds",),
                    donate_argnums=(0,)),
            "assign_round_pass", shape_of=_pn)
        # koordlint: shape[arg0: NxR i32 nodes, arg1: NxR i32 nodes]
        self.pass2 = insp.instrument(
            jax.jit(_ba.assign_followup_pass,
                    static_argnames=("k", "rounds", "spread_bits",
                                     "method"),
                    donate_argnums=(0, 1)),
            "assign_followup_pass",
            shape_of=lambda a, k: f"P{a[2].capacity}xN{a[0].capacity}")

        # sharded twins (selection recall-exact on the mesh; acceptance
        # bit-identical — parallel/sharded.py).  Donation mirrors the
        # unsharded bindings: the state (and the refresh's cache)
        # updates in place under its NamedSharding placement.
        self.select_scored_sh = self.refresh_cands_sh = None
        self.pass1_sh = self.pass2_sh = None
        if self.mesh is not None:
            from functools import partial as _partial

            self.select_scored_sh = insp.instrument(
                jax.jit(_partial(psharded.sharded_select_candidates,
                                 self.mesh),
                        static_argnames=("k", "spread_bits",
                                         "with_scores")),
                "select_candidates", shape_of=_pn)
            self.refresh_cands_sh = insp.instrument(
                jax.jit(_partial(psharded.sharded_refresh_candidates,
                                 self.mesh),
                        static_argnames=("k", "spread_bits"),
                        donate_argnums=(3,)),
                "refresh_candidates",
                shape_of=lambda a, k: (
                    f"P{a[1].capacity}xN{a[0].capacity}"
                    f"xD{a[4].shape[0]}{_sfx(a[0].capacity)}"))
            # koordlint: shape[arg0: NxR i32 nodes]
            self.pass1_sh = insp.instrument(
                jax.jit(_partial(psharded.sharded_assign_round_pass,
                                 self.mesh),
                        static_argnames=("rounds",),
                        donate_argnums=(0,)),
                "assign_round_pass", shape_of=_pn)
            # koordlint: shape[arg0: NxR i32 nodes, arg1: NxR i32 nodes]
            self.pass2_sh = insp.instrument(
                jax.jit(_partial(psharded.sharded_assign_followup_pass,
                                 self.mesh),
                        static_argnames=("k", "rounds", "spread_bits"),
                        donate_argnums=(0, 1)),
                "assign_followup_pass",
                shape_of=lambda a, k: (
                    f"P{a[2].capacity}"
                    f"xN{a[0].capacity}{_sfx(a[0].capacity)}"))

        # -- quality mode (ISSUE 13): the LP-relaxation packing solve,
        # the second solver backend behind the kit.  Same donation
        # contract as the greedy entries: arg0 (the snapshot state) is
        # consumed and must be replaced by the blessed swap.
        # koordlint: shape[arg0: NxR i32 nodes]
        self.quality_solve = insp.instrument(
            jax.jit(lp_pack_assign,
                    static_argnames=("ascent_iters", "rounding_iters"),
                    donate_argnums=(0,)),
            "lp_pack_assign", shape_of=_pn)
        self.quality_solve_sh = None
        if self.mesh is not None:
            from functools import partial as _qpartial

            # koordlint: shape[arg0: NxR i32 nodes]
            self.quality_solve_sh = insp.instrument(
                jax.jit(_qpartial(psharded.sharded_lp_pack_assign,
                                  self.mesh),
                        static_argnames=("ascent_iters",
                                         "rounding_iters"),
                        donate_argnums=(0,)),
                "lp_pack_assign", shape_of=_pn)
        #: topology diameter of a placed slot set (quality/topo_gang) —
        #: the rank-aware gang observable bench_recall and the quality
        #: planner report
        self.topo_diameter = jax.jit(gang_topo_diameter)

        # -- forecast plane (ISSUE 15): predictive admission — the
        # gang/greedy solve with the forecast-headroom reserve charged
        # for the round (charge -> solve -> release inside ONE jitted
        # program; forecast/kernels).  Donation mirrors gang_assign:
        # arg0 (the snapshot state) is consumed and replaced by the
        # blessed swap; the (N, R) reserve at arg1 stays live for the
        # host half's rescue pass.
        from koordinator_tpu.forecast.kernels import forecast_gang_assign

        def _fpn(args, kwargs):
            return (f"P{args[2].capacity}xN{args[0].capacity}"
                    f"{_sfx(args[0].capacity)}")

        # koordlint: shape[arg0: NxR i32 nodes, arg1: NxR i32 nodes]
        self.forecast_solve = insp.instrument(
            jax.jit(forecast_gang_assign,
                    static_argnames=("passes", "solver"),
                    donate_argnums=(0,)),
            "forecast_gang_assign", shape_of=_fpn)
        self.forecast_solve_sh = None
        if self.mesh is not None:
            from functools import partial as _fpartial

            # koordlint: shape[arg0: NxR i32 nodes, arg1: NxR i32 nodes]
            self.forecast_solve_sh = insp.instrument(
                jax.jit(_fpartial(psharded.sharded_forecast_gang_assign,
                                  self.mesh),
                        static_argnames=("passes", "solver", "k",
                                         "rounds", "spread_bits"),
                        donate_argnums=(0,)),
                "forecast_gang_assign", shape_of=_fpn)

        self.rsv_solve = insp.instrument(
            jax.jit(reservation_greedy_assign, donate_argnums=(0,)),
            "reservation_greedy_assign", shape_of=_pn)

        self.preempt = jax.jit(
            preempt_one, static_argnames=("same_quota_only", "nominate"))
        self.preempt_chain = jax.jit(preempt_chain)

        #: device-side reject-reason reduction over a round's COMPACTED
        #: failed rows — O(F·NUM_REASONS) host transfer, never (P, N)
        self.explain_counts = insp.instrument(
            jax.jit(_ex.explain_counts), "explain_counts", shape_of=_pn)
        #: per-dim capacity-slack reduction ((N, R) -> two (R,) sums);
        #: float32 accumulation — a 10k-node cluster's summed int32
        #: quantities overflow int32, and a ratio gauge doesn't need
        #: integer exactness
        self.slack_sums = insp.instrument(
            jax.jit(lambda st: (
                jnp.sum(jnp.where(
                    st.node_valid[:, None],
                    st.node_allocatable - st.node_requested, 0
                ).astype(jnp.float32), axis=0),
                jnp.sum(jnp.where(
                    st.node_valid[:, None], st.node_allocatable, 0
                ).astype(jnp.float32), axis=0))),
            "capacity_slack",
            shape_of=lambda a, k: f"N{a[0].capacity}")
