"""ScheduleExplanation persistence + workload audit trail.

The reference turns per-cycle Diagnosis state into durable artifacts two
ways: an async diagnosis dump queue that renders ScheduleExplanation CRs
(``frameworkext/schedule_diagnosis.go:44-108`` — DumpDiagnosis enqueues to
``diagnosisQueue`` with worker fan-out, blocking mode for tests), and the
workload auditor ring that records every scheduling attempt per pod/gang
(``frameworkext/workloadauditor/workload_auditor.go``). Here the queue
feeds an :class:`ExplanationStore` (the CR registry stand-in) and
:class:`WorkloadAuditor` keeps bounded per-workload event rings.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from koordinator_tpu.api.crds import ScheduleExplanation
from koordinator_tpu.scheduler.diagnosis import PodDiagnosis


# ---- placement explanations (device-side reject-reason accounting) --------


@dataclasses.dataclass
class PlacementExplanation:
    """One pod's reject-reason breakdown from a scheduling round.

    Counts come from the device-side reduction
    (``ops/explain.explain_counts``) plus the host-attributed pod-level
    gates (quota, gang barrier, degraded suspension); ``trace_id`` joins
    the explanation to the pod's trace and ``round`` to its flight
    record (``/debug/rounds``)."""

    pod: str
    round: int
    total_nodes: int
    feasible_nodes: int
    #: reason name -> node count, keyed by ops/explain.REASON_NAMES;
    #: only nonzero reasons are retained
    reasons: dict[str, int]
    trace_id: Optional[str] = None
    quota: Optional[str] = None
    gang: Optional[str] = None
    update_time: float = 0.0

    #: pod-level gates outrank node-count reasons in top_reason(): when
    #: quota admission (or the gang barrier / degraded suspension) held a
    #: pod back, it IS the attributed cause — the node-level counts are
    #: context, not the verdict
    _GATE_REASONS = ("quota", "gang_barrier", "degraded_suspended")

    def top_reason(self) -> Optional[str]:
        """The attributed cause: a pod-level gate when one fired, else
        the reason that eliminated the most nodes (None if none)."""
        if not self.reasons:
            return None
        for gate in self._GATE_REASONS:
            if self.reasons.get(gate, 0) > 0:
                return gate
        return max(self.reasons.items(), key=lambda kv: (kv[1], kv[0]))[0]

    def summary(self) -> str:
        """"0/10240 nodes feasible: 9812 fit_gpu, 401 quota, 27 ..."."""
        head = f"{self.feasible_nodes}/{self.total_nodes} nodes feasible"
        parts = [f"{count} {name}" for name, count in
                 sorted(self.reasons.items(), key=lambda kv: (-kv[1], kv[0]))
                 if count > 0]
        return head + (": " + ", ".join(parts) if parts else "")

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["summary"] = self.summary()
        doc["top_reason"] = self.top_reason()
        return doc


class ExplanationRing:
    """Bounded pod-keyed ring of the latest :class:`PlacementExplanation`
    per pod — the retention layer behind ``/debug/explain/<pod>``.

    Re-recording a pod refreshes its recency; the oldest pods fall off
    once ``capacity`` distinct pods are held (a years-long scheduler must
    not leak one entry per pod name ever seen)."""

    def __init__(self, capacity: int = 4096, clock=time.time):
        self.capacity = capacity
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: OrderedDict[str, PlacementExplanation] = OrderedDict()

    def record(self, explanation: PlacementExplanation) -> None:
        if not explanation.update_time:
            explanation.update_time = self.clock()
        with self._lock:
            self._ring.pop(explanation.pod, None)
            self._ring[explanation.pod] = explanation
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)

    def get(self, pod: str) -> Optional[PlacementExplanation]:
        with self._lock:
            return self._ring.get(pod)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class ExplanationStore:
    """Persists diagnosis results as ScheduleExplanation objects.

    ``blocking=False`` mirrors the reference's default async dump: record()
    enqueues and a drain (the worker) writes CRs; ``blocking=True`` writes
    through immediately (dumpDiagnosisBlocking). Capacity-bounded both in
    queue depth (diagnosisQueueSize=1000) and retained CRs.
    """

    def __init__(self, capacity: int = 1024, queue_size: int = 1000,
                 blocking: bool = False, clock=time.time):
        self.capacity = capacity
        self.queue_size = queue_size
        self.blocking = blocking
        self.clock = clock
        self._lock = threading.Lock()
        self._queue: deque[ScheduleExplanation] = deque()
        self._store: OrderedDict[str, ScheduleExplanation] = OrderedDict()
        self.dropped = 0

    # -- producer side (scheduler Diagnose phase) ---------------------------

    def record(self, pod_name: str, diagnosis: PodDiagnosis,
               namespace: str = "default", uid: str = "") -> None:
        offers = {}
        if diagnosis.preempt_node is not None:
            offers[diagnosis.preempt_node] = (
                "fits after preempting ["
                + ", ".join(diagnosis.preempt_victims) + "]")
        explanation = ScheduleExplanation(
            pod_uid=uid or pod_name,
            pod_namespace=namespace,
            pod_name=pod_name,
            reasons=(diagnosis.message(),),
            node_offers=offers,
            update_time=self.clock(),
        )
        with self._lock:
            if self.blocking:
                self._write(explanation)
                return
            if len(self._queue) >= self.queue_size:
                self.dropped += 1  # queue full: drop, never block scheduling
                return
            self._queue.append(explanation)

    def delete(self, pod_name: str) -> None:
        """Pod scheduled (or removed): its explanation is stale — purge the
        store AND any queued-but-undrained entry, or a later drain would
        resurrect a failure explanation for a bound pod."""
        with self._lock:
            self._store.pop(pod_name, None)
            if any(e.pod_name == pod_name for e in self._queue):
                self._queue = deque(
                    e for e in self._queue if e.pod_name != pod_name)

    # -- worker side --------------------------------------------------------

    def drain(self, max_items: int | None = None) -> int:
        """Apply queued explanations to the store (the async worker)."""
        n = 0
        with self._lock:
            while self._queue and (max_items is None or n < max_items):
                self._write(self._queue.popleft())
                n += 1
        return n

    def _write(self, explanation: ScheduleExplanation) -> None:
        self._store.pop(explanation.pod_name, None)
        self._store[explanation.pod_name] = explanation
        while len(self._store) > self.capacity:
            self._store.popitem(last=False)

    # -- query side ---------------------------------------------------------

    def get(self, pod_name: str) -> Optional[ScheduleExplanation]:
        with self._lock:
            return self._store.get(pod_name)

    def list(self) -> list[ScheduleExplanation]:
        with self._lock:
            return list(self._store.values())


# ---- workload auditor ------------------------------------------------------

RECORD_SCHEDULE_FAILED = "ScheduleFailed"
RECORD_SCHEDULE_SUCCESS = "ScheduleSuccess"
RECORD_GATED = "Gated"
RECORD_ATTEMPT = "Attempt"


@dataclasses.dataclass(frozen=True)
class AuditEvent:
    timestamp: float
    record_type: str
    message: str = ""


class WorkloadAuditor:
    """Bounded per-workload (pod or gang group) scheduling-lifecycle rings
    (workloadauditor.workloadAuditorImpl: per-record locking, attempts
    counter, gating transitions)."""

    def __init__(self, enabled: bool = True, ring_size: int = 32,
                 clock=time.time):
        self.enabled = enabled
        self.ring_size = ring_size
        self.clock = clock
        self._lock = threading.Lock()
        self._records: dict[str, deque[AuditEvent]] = {}
        self._attempts: dict[str, int] = {}
        self._gated: dict[str, bool] = {}

    def _ring(self, key: str) -> deque[AuditEvent]:
        return self._records.setdefault(key, deque(maxlen=self.ring_size))

    def record(self, key: str, record_type: str, message: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring(key).append(
                AuditEvent(self.clock(), record_type, message))

    def record_attempt(self, key: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._attempts[key] = self._attempts.get(key, 0) + 1
            self._ring(key).append(AuditEvent(self.clock(), RECORD_ATTEMPT))

    def record_gating(self, key: str, gated: bool) -> None:
        """Only gating *transitions* are recorded (RecordPodGating)."""
        if not self.enabled:
            return
        with self._lock:
            if self._gated.get(key) == gated:
                return
            self._gated[key] = gated
            self._ring(key).append(AuditEvent(
                self.clock(), RECORD_GATED, "gated" if gated else "ungated"))

    def delete(self, key: str) -> None:
        with self._lock:
            self._records.pop(key, None)
            self._attempts.pop(key, None)
            self._gated.pop(key, None)

    def attempts(self, key: str) -> int:
        with self._lock:
            return self._attempts.get(key, 0)

    def events(self, key: str) -> list[AuditEvent]:
        with self._lock:
            return list(self._records.get(key, ()))
