"""ScheduleExplanation persistence + workload audit trail.

The reference turns per-cycle Diagnosis state into durable artifacts two
ways: an async diagnosis dump queue that renders ScheduleExplanation CRs
(``frameworkext/schedule_diagnosis.go:44-108`` — DumpDiagnosis enqueues to
``diagnosisQueue`` with worker fan-out, blocking mode for tests), and the
workload auditor ring that records every scheduling attempt per pod/gang
(``frameworkext/workloadauditor/workload_auditor.go``). Here the queue
feeds an :class:`ExplanationStore` (the CR registry stand-in) and
:class:`WorkloadAuditor` keeps bounded per-workload event rings.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from koordinator_tpu.api.crds import ScheduleExplanation
from koordinator_tpu.scheduler.diagnosis import PodDiagnosis


class ExplanationStore:
    """Persists diagnosis results as ScheduleExplanation objects.

    ``blocking=False`` mirrors the reference's default async dump: record()
    enqueues and a drain (the worker) writes CRs; ``blocking=True`` writes
    through immediately (dumpDiagnosisBlocking). Capacity-bounded both in
    queue depth (diagnosisQueueSize=1000) and retained CRs.
    """

    def __init__(self, capacity: int = 1024, queue_size: int = 1000,
                 blocking: bool = False, clock=time.time):
        self.capacity = capacity
        self.queue_size = queue_size
        self.blocking = blocking
        self.clock = clock
        self._lock = threading.Lock()
        self._queue: deque[ScheduleExplanation] = deque()
        self._store: OrderedDict[str, ScheduleExplanation] = OrderedDict()
        self.dropped = 0

    # -- producer side (scheduler Diagnose phase) ---------------------------

    def record(self, pod_name: str, diagnosis: PodDiagnosis,
               namespace: str = "default", uid: str = "") -> None:
        offers = {}
        if diagnosis.preempt_node is not None:
            offers[diagnosis.preempt_node] = (
                "fits after preempting ["
                + ", ".join(diagnosis.preempt_victims) + "]")
        explanation = ScheduleExplanation(
            pod_uid=uid or pod_name,
            pod_namespace=namespace,
            pod_name=pod_name,
            reasons=(diagnosis.message(),),
            node_offers=offers,
            update_time=self.clock(),
        )
        with self._lock:
            if self.blocking:
                self._write(explanation)
                return
            if len(self._queue) >= self.queue_size:
                self.dropped += 1  # queue full: drop, never block scheduling
                return
            self._queue.append(explanation)

    def delete(self, pod_name: str) -> None:
        """Pod scheduled (or removed): its explanation is stale — purge the
        store AND any queued-but-undrained entry, or a later drain would
        resurrect a failure explanation for a bound pod."""
        with self._lock:
            self._store.pop(pod_name, None)
            if any(e.pod_name == pod_name for e in self._queue):
                self._queue = deque(
                    e for e in self._queue if e.pod_name != pod_name)

    # -- worker side --------------------------------------------------------

    def drain(self, max_items: int | None = None) -> int:
        """Apply queued explanations to the store (the async worker)."""
        n = 0
        with self._lock:
            while self._queue and (max_items is None or n < max_items):
                self._write(self._queue.popleft())
                n += 1
        return n

    def _write(self, explanation: ScheduleExplanation) -> None:
        self._store.pop(explanation.pod_name, None)
        self._store[explanation.pod_name] = explanation
        while len(self._store) > self.capacity:
            self._store.popitem(last=False)

    # -- query side ---------------------------------------------------------

    def get(self, pod_name: str) -> Optional[ScheduleExplanation]:
        with self._lock:
            return self._store.get(pod_name)

    def list(self) -> list[ScheduleExplanation]:
        with self._lock:
            return list(self._store.values())


# ---- workload auditor ------------------------------------------------------

RECORD_SCHEDULE_FAILED = "ScheduleFailed"
RECORD_SCHEDULE_SUCCESS = "ScheduleSuccess"
RECORD_GATED = "Gated"
RECORD_ATTEMPT = "Attempt"


@dataclasses.dataclass(frozen=True)
class AuditEvent:
    timestamp: float
    record_type: str
    message: str = ""


class WorkloadAuditor:
    """Bounded per-workload (pod or gang group) scheduling-lifecycle rings
    (workloadauditor.workloadAuditorImpl: per-record locking, attempts
    counter, gating transitions)."""

    def __init__(self, enabled: bool = True, ring_size: int = 32,
                 clock=time.time):
        self.enabled = enabled
        self.ring_size = ring_size
        self.clock = clock
        self._lock = threading.Lock()
        self._records: dict[str, deque[AuditEvent]] = {}
        self._attempts: dict[str, int] = {}
        self._gated: dict[str, bool] = {}

    def _ring(self, key: str) -> deque[AuditEvent]:
        return self._records.setdefault(key, deque(maxlen=self.ring_size))

    def record(self, key: str, record_type: str, message: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring(key).append(
                AuditEvent(self.clock(), record_type, message))

    def record_attempt(self, key: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._attempts[key] = self._attempts.get(key, 0) + 1
            self._ring(key).append(AuditEvent(self.clock(), RECORD_ATTEMPT))

    def record_gating(self, key: str, gated: bool) -> None:
        """Only gating *transitions* are recorded (RecordPodGating)."""
        if not self.enabled:
            return
        with self._lock:
            if self._gated.get(key) == gated:
                return
            self._gated[key] = gated
            self._ring(key).append(AuditEvent(
                self.clock(), RECORD_GATED, "gated" if gated else "ungated"))

    def delete(self, key: str) -> None:
        with self._lock:
            self._records.pop(key, None)
            self._attempts.pop(key, None)
            self._gated.pop(key, None)

    def attempts(self, key: str) -> int:
        with self._lock:
            return self._attempts.get(key, 0)

    def events(self, key: str) -> list[AuditEvent]:
        with self._lock:
            return list(self._records.get(key, ()))
