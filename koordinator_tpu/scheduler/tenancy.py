"""Multi-tenant round pipeline: N clusters on one mesh (ISSUE 11).

"Millions of users" is many clusters, not one.  This module multiplexes
several clusters — *tenants* — onto one scheduler process and one solve
mesh.  Each tenant keeps its OWN control-plane state: a
:class:`~koordinator_tpu.scheduler.snapshot.ClusterSnapshot`, candidate
cache, quota tree, staleness watchdog and degraded mode — isolation is
structural, one tenant's stale sync feed cannot suspend another's
admission — while all tenants share ONE
:class:`~koordinator_tpu.scheduler.solver_kit.SolverKit` (one jit cache,
one mesh, one recompile ledger).

Three dispatch modes, best first:

- **batched** — when every tenant's round is shape-aligned (same node
  capacity, same pod bucket, gangless, selector-mask path, compatible
  quota shapes, single-device), the cycle solves as ONE tensor program
  with a leading tenant axis: per-tenant states/batches stack to
  (T, N, R)/(T, P, ...) pytrees and a ``jax.vmap`` of candidate
  selection + the first propose/accept pass runs in one dispatch.
  Per-tenant slices are bit-identical to the serial solves (integer
  ranking keys; a finished tenant's extra ``while_loop`` iterations are
  no-ops), proven in tests/test_tenancy.py.
- **pipelined** — otherwise, per-tenant rounds ride the host/device
  split (``Scheduler.round_device``/``round_host``): tenant B's device
  solve is DISPATCHED before tenant A's host commit runs, so the mesh
  executes B's solve while the host binds A's pods, serves A's debug
  traffic, and applies deltas — round N+1's solve overlaps round N's
  commit, which is what deletes the host-commit device idle gap.
- **serial** — ``pipeline=False`` fallback: plain ``schedule_round``
  per tenant (the before-baseline bench_stages measures against).

Admission is **weighted deficit-round-robin**: each cycle distributes
``cycle_pod_budget`` credits in proportion to tenant weights (unused
share redistributes to backlogged tenants), every tenant's round admits
at most its credit, and admitted pods are charged back — under
sustained overload admitted shares converge to weight fractions
(Priority Matters' per-tenant fairness inside one batched solve, not
per-cluster silos).

The double-buffered hand-off and its donation argument are documented
on ``Scheduler._round_device`` and in docs/multitenancy.md; koordlint's
donation-safety corpus seeds both the blessed swap and the
stash-the-in-flight-buffer anti-idiom.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from functools import partial

import numpy as np

from koordinator_tpu import metrics, timeline

# JAX is imported lazily inside methods where possible, but the batched
# path is core to this module; the scheduler stack already pulls JAX in.
import jax
import jax.numpy as jnp


@dataclasses.dataclass
class TenantSpec:
    """One cluster's identity and share of the mesh."""

    name: str
    #: weighted-fair admission share (relative; DRR credits accrue
    #: proportionally to this each cycle)
    weight: float = 1.0
    #: the tenant's ClusterSnapshot row capacity at creation (grows by
    #: power-of-two buckets like any snapshot)
    node_capacity: int = 64


class Tenant:
    """A tenant's scheduler plus its fair-admission ledger."""

    def __init__(self, spec: TenantSpec, scheduler):
        self.spec = spec
        self.scheduler = scheduler
        #: DRR deficit credit, in pods; topped up each cycle by
        #: weight share, drawn down by admitted pods
        self.credit = 0.0
        self.admitted_total = 0
        self.last_admitted = 0
        self.rounds = 0

    @property
    def name(self) -> str:
        return self.spec.name


class TenantScheduler:
    """Front-end multiplexing N tenants onto one shared solver kit.

    Duck-type compatible with the single-tenant ``Scheduler`` where the
    transport layer needs it (``lock`` + ``schedule_round`` for
    SolveService; ``stop`` for the binary assembly), so one listen
    socket can drive multi-tenant cycles.
    """

    def __init__(self, cycle_pod_budget: int = 4096,
                 pipeline: bool = True,
                 batch_tenant_axis: bool = True,
                 mesh="auto", shard_min_nodes: int = 1024,
                 scheduler_defaults: dict | None = None,
                 solver_kit=None):
        from koordinator_tpu.scheduler.solver_kit import SolverKit

        #: pods admitted per cycle across ALL tenants (the DRR quantum)
        self.cycle_pod_budget = cycle_pod_budget
        self.pipeline = pipeline
        self.batch_tenant_axis = batch_tenant_axis
        #: ctor kwargs applied to every tenant's Scheduler (e.g.
        #: batch_solver_threshold, incremental_solve) unless overridden
        #: per add_tenant call
        self.scheduler_defaults = dict(scheduler_defaults or {})
        #: a passed kit is SHARED (e.g. bench_stages times serial vs
        #: pipelined fronts on one jit cache); otherwise build our own
        self.kit = (solver_kit if solver_kit is not None
                    else SolverKit(mesh=mesh,
                                   shard_min_nodes=shard_min_nodes))
        #: front-end lock: serializes cycles (SolveService acquires it
        #: the way it acquires a Scheduler's round lock)
        self.lock = threading.RLock()
        self._tenants: dict[str, Tenant] = {}
        self.cycle_seq = 0
        self.last_mode = "none"
        self.last_cycle_s = 0.0
        self.last_host_wait_fraction = 0.0
        #: the last cycle's reconstructed timeline doc (ISSUE 18) —
        #: None until a cycle ran with the recorder armed
        self.last_timeline = None
        #: jit cache for the tenant-axis batched programs, keyed by the
        #: static solve knobs (shapes retrace inside jax.jit as usual)
        self._batched_fns: dict[tuple, object] = {}
        #: jit cache for the QUALITY tenant-axis program (ISSUE 19):
        #: vmap of lp_pack_assign, keyed by has_quota
        self._quality_fns: dict[bool, object] = {}
        #: ONE shared ScoringConfig handed to tenants that don't bring
        #: their own: the batched program broadcasts a single config
        #: over the tenant axis, and _batched_eligible requires config
        #: IDENTITY — per-tenant default instances would silently
        #: disqualify every cycle
        self._default_config = None
        #: demand snapshot of the current cycle (tenant -> pending
        #: count), taken once by _admission_limits under each tenant's
        #: lock and reused by _batch_floor so the floor and the limits
        #: describe the SAME queue state
        self._cycle_demand: dict[str, int] = {}
        #: SLO monitor / trend engine attached by the binary assembly —
        #: same attachment points a single-tenant Scheduler exposes
        self.slo_monitor = None
        self.trend_engine = None
        #: ha.LeaderElector — leadership gates the WHOLE cycle here (a
        #: standby front must not decide for ANY tenant); per-tenant
        #: schedulers run ungated under the front
        self.elector = None
        #: per-tenant StateSyncServices (binary assembly) and teardown
        #: hooks for the extra per-tenant listen sockets
        self.tenant_syncs: dict = {}
        self.closers: list = []

    # -- tenant lifecycle ----------------------------------------------------

    def add_tenant(self, spec: TenantSpec, **scheduler_kwargs) -> Tenant:
        """Create a tenant: its own snapshot/quota/degraded state, the
        SHARED solver kit."""
        from koordinator_tpu.scheduler.scheduler import Scheduler
        from koordinator_tpu.scheduler.snapshot import ClusterSnapshot

        with self.lock:
            if spec.name in self._tenants:
                raise ValueError(f"tenant {spec.name!r} already exists")
            kwargs = {**self.scheduler_defaults, **scheduler_kwargs}
            if kwargs.get("config") is None:
                if self._default_config is None:
                    from koordinator_tpu.ops.assignment import ScoringConfig

                    self._default_config = ScoringConfig.default()
                kwargs["config"] = self._default_config
            snapshot = kwargs.pop("snapshot", None) or ClusterSnapshot(
                capacity=spec.node_capacity)
            sched = Scheduler(snapshot, tenant=spec.name,
                              solver_kit=self.kit, **kwargs)
            sched.tenant_front = self
            tenant = Tenant(spec, sched)
            self._tenants[spec.name] = tenant
            metrics.tenant_count.set(float(len(self._tenants)))
            return tenant

    def tenant(self, name: str) -> Tenant:
        return self._tenants[name]

    def tenants(self) -> list[Tenant]:
        return list(self._tenants.values())

    @property
    def primary(self):
        """The first tenant's scheduler — the attachment point for
        surfaces that expect one Scheduler (flight dumps on SLO
        breach, per-tenant DebugService instances serve their own)."""
        first = next(iter(self._tenants.values()), None)
        return first.scheduler if first is not None else None

    def stop(self) -> None:
        if self.slo_monitor is not None:
            self.slo_monitor.stop()
        for tenant in self._tenants.values():
            tenant.scheduler.stop()
        for closer in reversed(self.closers):
            try:
                closer()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self.closers.clear()

    # -- weighted-fair admission (deficit round robin) -----------------------

    def _admission_limits(self) -> dict[str, int]:
        """Top up each tenant's DRR credit by its weight share of the
        cycle budget, redistribute share no backlog can use, and return
        per-tenant admission limits for this cycle's rounds."""
        tenants = list(self._tenants.values())
        if not tenants:
            return {}
        demand: dict[str, int] = {}
        for t in tenants:
            with t.scheduler.lock:
                demand[t.name] = len(t.scheduler.pending)
        # one demand snapshot per cycle: _batch_floor reuses it so the
        # common pod bucket describes the same queue state as the limits
        self._cycle_demand = dict(demand)
        wsum = sum(max(t.spec.weight, 0.0) for t in tenants) or 1.0
        budget = float(self.cycle_pod_budget)
        # waterfill: hand out weight-proportional share, move share no
        # backlog can consume to still-hungry tenants (bounded passes —
        # each pass either satisfies someone or terminates)
        share = {t.name: budget * max(t.spec.weight, 0.0) / wsum
                 for t in tenants}
        for _ in range(len(tenants)):
            surplus = 0.0
            hungry: list[Tenant] = []
            for t in tenants:
                # credit is invariantly >= 0 (admission never exceeds
                # int(credit)), so a tenant's useful share is its backlog
                room = float(demand[t.name])
                if share[t.name] > room:
                    surplus += share[t.name] - room
                    share[t.name] = room
                elif demand[t.name] > share[t.name]:
                    hungry.append(t)
            if surplus <= 0.0 or not hungry:
                break
            hsum = sum(max(t.spec.weight, 0.0) for t in hungry) or 1.0
            for t in hungry:
                share[t.name] += surplus * max(t.spec.weight, 0.0) / hsum
        limits: dict[str, int] = {}
        for t in tenants:
            # credit carries fractional share across cycles (classic
            # DRR) but is clamped to one budget so an idle tenant
            # cannot bank unbounded burst rights
            t.credit = min(t.credit + share[t.name], budget)
            limits[t.name] = max(int(t.credit), 0)
        return limits

    # -- the cycle -----------------------------------------------------------

    def schedule_round(self):
        """SolveService-compatible entry: run one full cycle and merge
        the per-tenant results under ``tenant/pod`` keys."""
        from koordinator_tpu.scheduler.scheduler import SchedulingResult

        results = self.schedule_cycle()
        merged = SchedulingResult({}, {}, 0)
        for name, result in results.items():
            merged.round_pods += result.round_pods
            for pod, node in result.assignments.items():
                merged.assignments[f"{name}/{pod}"] = node
            for pod, diag in result.failures.items():
                merged.failures[f"{name}/{pod}"] = diag
            for pod, nom in result.nominations.items():
                merged.nominations[f"{name}/{pod}"] = nom
        return merged

    def schedule_cycle(self) -> dict:
        """One multi-tenant scheduling cycle: weighted-fair admission,
        then every tenant's round — batched on the tenant axis when
        shape-aligned, pipelined otherwise (round N+1's device solve
        overlaps round N's host commit), serial as the opt-out."""
        with self.lock:
            if self.elector is not None and not self.elector.tick():
                # standby front: keep syncing every tenant's state,
                # decide nothing for anyone
                return {}
            self.cycle_seq += 1
            t0 = time.perf_counter()
            with timeline.RECORDER.section("host_other",
                                           "tenancy.admission"):
                limits = self._admission_limits()
            order = [t for t in self._tenants.values()]
            results: dict = {}
            if not order:
                return results
            # pipeline=False is the full opt-out: plain serial rounds,
            # whatever batch_tenant_axis says (the batched path's
            # misalignment fallback is itself pipelined)
            if not self.pipeline:
                mode = self._cycle_serial(order, limits, results)
            elif self.batch_tenant_axis:
                mode = self._cycle_batched(order, limits, results)
            else:
                mode = self._cycle_pipelined(order, limits, results)
            wall = time.perf_counter() - t0
            device_wait = sum(t.scheduler._solve_device_s for t in order)
            self.last_mode = mode
            self.last_cycle_s = wall
            self.last_host_wait_fraction = (
                min(device_wait / wall, 1.0) if wall > 0 else 0.0)
            metrics.tenant_cycles.inc(labels={"mode": mode})
            metrics.tenant_cycle_latency.observe(wall)
            metrics.pipeline_host_wait_fraction.set(
                self.last_host_wait_fraction)
            admitted_cycle = sum(t.last_admitted for t in order) or 1
            for t in order:
                metrics.tenant_admission_share.set(
                    t.last_admitted / admitted_cycle,
                    labels={"tenant": t.name})
            if timeline.RECORDER.enabled:
                # timeline observatory (ISSUE 18): reconstruct the
                # cycle's gantt, attribute its wall, publish the
                # host_wait_attribution family, and back-annotate every
                # tenant's flight records with the critical-path verdict
                doc = timeline.RECORDER.finish_cycle(
                    self.cycle_seq, t0, t0 + wall, mode=mode)
                if doc is not None:
                    self.last_timeline = doc
                    for t in order:
                        t.scheduler.flight_recorder.annotate_round(
                            t.scheduler.round_seq, t.name,
                            cycle_seq=doc["cycle"],
                            cycle_critical_cause=doc["critical_cause"],
                            cycle_critical_seconds=doc[
                                "critical_seconds"])
            return results

    def _begin_round(self, tenant: Tenant, limits: dict[str, int]):
        """Acquire the tenant's round lock and apply its admission cap.
        Caller owns releasing via :meth:`_end_round`."""
        sched = tenant.scheduler
        tl_armed = timeline.RECORDER.enabled
        t0 = time.perf_counter() if tl_armed else 0.0
        sched.lock.acquire()
        if tl_armed:
            # contention with the sync reader threads (deltasync
            # applies hold the same lock): the lock_wait slice of the
            # host-wait attribution
            timeline.RECORDER.add(t0, time.perf_counter(), "lock_wait",
                                  "round_lock.acquire", tenant.name)
        sched.round_pod_limit = limits.get(tenant.name)

    def _end_round(self, tenant: Tenant) -> None:
        sched = tenant.scheduler
        sched.round_pod_limit = None
        sched.lock.release()

    def _account_round(self, tenant: Tenant, handle) -> None:
        admitted = len(handle.pods)
        tenant.last_admitted = admitted
        tenant.admitted_total += admitted
        tenant.rounds += 1
        tenant.credit -= admitted
        if admitted:
            metrics.tenant_admitted.inc(admitted,
                                        labels={"tenant": tenant.name})

    def _cycle_serial(self, order, limits, results) -> str:
        for t in order:
            self._begin_round(t, limits)
            try:
                with t.scheduler.lock:
                    handle = t.scheduler.round_device()
                    self._account_round(t, handle)
                    results[t.name] = t.scheduler.round_host(handle)
            finally:
                self._end_round(t)
        return "serial"

    def _cycle_pipelined(self, order, limits, results) -> str:
        """Depth-1 software pipeline over tenants: dispatch tenant i+1's
        device solve BEFORE committing tenant i, so the device executes
        one tenant's solve while the host binds another's pods.  Locks
        are acquired in cycle order and each is held exactly across its
        tenant's two halves (RLock self-edges are exempt from the
        lock-discipline order graph; distinct tenants' locks are only
        ever taken in the fixed cycle order)."""
        pending: collections.deque = collections.deque()

        def commit(entry) -> None:
            t, handle = entry
            try:
                results[t.name] = t.scheduler.round_host(handle)
            finally:
                self._end_round(t)

        try:
            for t in order:
                self._begin_round(t, limits)
                try:
                    handle = t.scheduler.round_device()
                    self._account_round(t, handle)
                except Exception:
                    self._end_round(t)
                    raise
                pending.append((t, handle))
                # depth 1: the previous tenant commits while this
                # tenant's solve executes on device
                while len(pending) > 1:
                    commit(pending.popleft())
            while pending:                  # the cycle's last commit
                commit(pending.popleft())
        finally:
            # exception drain — every dispatched round still COMMITS
            # (its solve already charged the device-side accounting;
            # dropping it would strand phantom placements).  A commit
            # failing while we are already unwinding must not leak the
            # remaining tenants' locks, so failures here are swallowed
            # (commit's own finally released that tenant's lock).
            while pending:
                try:
                    commit(pending.popleft())
                except Exception:  # noqa: BLE001 — already unwinding
                    pass
        return "pipelined"

    # -- tenant-axis batched dispatch ---------------------------------------

    @staticmethod
    def _stack(trees):
        """Stack a list of congruent pytrees on a new leading tenant
        axis (None leaves stay None)."""
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    @staticmethod
    def _unstack(tree, i: int):
        return jax.tree.map(lambda x: x[i], tree)

    def _batched_fn(self, key: tuple):
        """The jitted tenant-axis program for one (k, spread, method,
        rounds, has_quota) signature: vmap of candidate selection + the
        first propose/accept pass.  The stacked state is donated (it is
        a stacking COPY — the per-tenant originals stay live until each
        scheduler's blessed swap in round_adopt_batched)."""
        fn = self._batched_fns.get(key)
        if fn is not None:
            return fn
        k, spread, method, rounds, has_quota = key
        from koordinator_tpu.ops import batch_assign as ba

        def one_tenant(state, batch, quota, cfg):
            ck, cn, cs = ba.select_candidates(
                state, batch, cfg, k=k, spread_bits=spread,
                method=method, with_scores=True)
            a, st, q, est = ba.assign_round_pass(
                state, batch, quota, ck, cn, cfg, rounds=rounds)
            return a, st, q, est, ck, cn, cs

        # koordlint: shape[state: TxNxR i32, batch: TxP i32, quota: TxQ i32]
        def program(state, batch, quota, cfg):
            # cfg broadcasts over the tenant axis (in_axes=None) — one
            # shared ScoringConfig, exactly the serial entries' shape
            return jax.vmap(
                one_tenant,
                in_axes=(0, 0, 0 if has_quota else None, None))(
                    state, batch, quota, cfg)

        fn = jax.jit(program, donate_argnums=(0,))
        self._batched_fns[key] = fn
        return fn

    def _batch_floor(self, limits: dict[str, int]) -> int:
        """Common PodBatch capacity for this cycle: the bucket of the
        largest per-tenant admission (stacking needs equal pod axes).
        Reads the demand snapshot _admission_limits took under the
        tenant locks, so the floor and the limits describe the same
        queue state."""
        from koordinator_tpu.state.cluster_state import _bucket

        worst = 1
        for t in self._tenants.values():
            demand = self._cycle_demand.get(t.name, 0)
            limit = limits.get(t.name)
            worst = max(worst,
                        demand if limit is None else min(demand, limit))
        return _bucket(max(worst, 1), minimum=16)

    def _batched_eligible(self, pairs) -> bool:
        """Shape-alignment gate for the tenant-axis program.  Any miss
        falls back to the pipelined path — a correctness-neutral choice
        (both paths are bit-identical per tenant)."""
        live = [(t, h) for t, h in pairs if not h.done]
        if len(live) < 2:
            return False
        sched0 = live[0][0].scheduler
        caps = set()
        pcaps = set()
        qshapes = set()
        for t, h in live:
            sched = t.scheduler
            if (h.gang_index or h.batch.selector_mask is None
                    or len(sched.reservations)
                    or len(h.pods) < sched.batch_solver_threshold
                    or sched.degraded
                    # the chaos seam fires in _round_dispatch, which the
                    # batched program bypasses — a fault-injected tenant
                    # must keep the per-tenant dispatch path
                    or sched.faults is not None
                    # quality-mode tenants are eligible too (ISSUE 19
                    # closed the PR 13 gap): escalated tenants solve in
                    # their OWN vmapped lp_pack_assign program, the rest
                    # in the select+pass1 program — see
                    # _dispatch_tenant_axis's partition
                    # a forecast-mode tenant charges its admission
                    # reserve in _round_dispatch, which the batched
                    # select+pass1 program bypasses — its cycle keeps
                    # the per-tenant dispatch path (same reasoning as
                    # quality mode)
                    or (sched.forecast_mode != "off"
                        and sched.forecast_plane is not None)
                    or (sched.mesh is not None
                        and sched.snapshot.solver_sharding_active)):
                return False
            # the ONE batched program broadcasts tenant 0's config and
            # solve knobs over the tenant axis: every live tenant must
            # share them (config by IDENTITY — add_tenant hands tenants
            # a shared default), or its slice would be solved with
            # someone else's scoring and break per-tenant bit-identity
            if (sched.config is not sched0.config
                    or sched.cand_k != sched0.cand_k
                    or sched.cand_spread != sched0.cand_spread
                    or sched.cand_method != sched0.cand_method
                    or sched.solve_rounds != sched0.solve_rounds):
                return False
            caps.add(sched.snapshot.capacity)
            pcaps.add(h.batch.capacity)
            qshapes.add(None if h.quota is None
                        else tuple(h.quota.chain.shape))
        return len(caps) == 1 and len(pcaps) == 1 and len(qshapes) == 1

    def _cycle_batched(self, order, limits, results) -> str:
        """Try the tenant-axis batched program; fall back to the
        pipelined dispatch when the cycle isn't shape-aligned."""
        from koordinator_tpu import tracing

        floor = self._batch_floor(limits)
        held: list[Tenant] = []
        for t in order:
            self._begin_round(t, limits)
            held.append(t)
            t.scheduler.batch_capacity_floor = floor

        def commit(t: Tenant, handle) -> None:
            try:
                results[t.name] = t.scheduler.round_host(handle)
            finally:
                self._end_round(t)
                held.remove(t)

        pairs: list = []
        mode = "batched"
        try:
            for t in order:
                sched = t.scheduler
                # same blanket round_device wears on the per-tenant
                # path: typed segments inside win the sweep, the
                # prepare glue stops reading as unattributed
                with timeline.RECORDER.section(
                        "host_other", "round.prepare", t.name):
                    sched._round_begin()
                    handle = sched._round_prepare()
                handle.start_wall = time.time()
                handle.t0 = time.perf_counter()
                pairs.append((t, handle))
            if self._batched_eligible(pairs):
                self._dispatch_tenant_axis(pairs)
                with timeline.RECORDER.section("host_other",
                                               "round.publish"):
                    for t, handle in pairs:
                        self._account_round(t, handle)
                        if (t.scheduler._round_recordable
                                and not handle.done):
                            t.scheduler._round_flight_record(
                                handle.result, "", handle.start_wall,
                                time.perf_counter() - handle.t0,
                                t.scheduler._current_path(), half="solve")
                for t, handle in pairs:
                    commit(t, handle)
            else:
                # dispatch each prepared round individually and commit
                # depth-1 pipelined (same overlap, per-tenant programs)
                mode = "pipelined"
                pending: collections.deque = collections.deque()
                for t, handle in pairs:
                    with tracing.TRACER.span(
                            "scheduler.round.solve", service="scheduler",
                            attributes={"tenant": t.name}) as span:
                        handle = t.scheduler._round_dispatch(handle)
                    self._account_round(t, handle)
                    if (t.scheduler._round_recordable
                            and not handle.done):
                        t.scheduler._round_flight_record(
                            handle.result, span.trace_id,
                            handle.start_wall,
                            time.perf_counter() - handle.t0,
                            t.scheduler._current_path(), half="solve")
                    pending.append((t, handle))
                    while len(pending) > 1:
                        commit(*pending.popleft())
                while pending:
                    commit(*pending.popleft())
        finally:
            # exception cleanup: a DISPATCHED round still commits (its
            # solve already charged device-side accounting — dropping
            # it would strand phantom placements); an undispatched one
            # decided nothing (the stacked program consumed only a
            # stacking COPY) and just releases its lock
            for t in list(held):
                handle = next((h for tt, h in pairs if tt is t), None)
                dispatched = handle is not None and (
                    handle.done or handle.assignments is not None)
                try:
                    if dispatched:
                        commit(t, handle)
                    else:
                        self._end_round(t)
                        held.remove(t)
                except Exception:  # noqa: BLE001 — already unwinding
                    if t in held:
                        held.remove(t)
                        try:
                            self._end_round(t)
                        except RuntimeError:
                            pass
        return mode

    @staticmethod
    def _wants_quality(sched) -> bool:
        """Mirror of _round_dispatch's use_quality predicate for the
        tenant-axis partition (gang rounds and forecast tenants never
        reach here — _batched_eligible already falls back on them)."""
        return (sched.quality_mode == "lp"
                or (sched.quality_mode == "auto"
                    and sched._quality_escalate))

    def _dispatch_tenant_axis(self, pairs) -> None:
        """ONE vmapped select+pass1 dispatch over every live tenant's
        stacked state — the leading tenant axis the issue names.
        Quality-escalated tenants (ISSUE 19) dispatch through their own
        vmapped lp_pack_assign program in the same window, so a mixed-
        quality fleet no longer serializes its host halves."""
        live = [(t, h) for t, h in pairs if not h.done]
        plain = [(t, h) for t, h in live
                 if not self._wants_quality(t.scheduler)]
        quality = [(t, h) for t, h in live
                   if self._wants_quality(t.scheduler)]
        # timeline observatory (ISSUE 18): the stack/trace/unstack walls
        # of the one vmapped program are solver dispatch, exactly like
        # the per-tenant _round_dispatch window, and the async solve
        # starts executing inside it — its start is the device-busy
        # leading edge each tenant's block pairs with
        dispatch_t0 = time.perf_counter()
        try:
            if plain:
                self._dispatch_tenant_axis_inner(plain)
            if quality:
                self._dispatch_quality_axis_inner(quality)
        finally:
            if timeline.RECORDER.enabled:
                timeline.RECORDER.add(
                    dispatch_t0, time.perf_counter(), "dispatch",
                    "tenant_axis.dispatch")
                for t, _ in live:
                    if t.scheduler._tl_device_t0 is None:
                        t.scheduler._tl_device_t0 = dispatch_t0

    def _dispatch_tenant_axis_inner(self, live) -> None:
        from koordinator_tpu.ops import batch_assign as ba

        states = [t.scheduler.snapshot.state for t, _ in live]
        batches = [h.batch for _, h in live]
        quotas = [h.quota for _, h in live]
        has_quota = quotas[0] is not None
        sched0 = live[0][0].scheduler
        n = sched0.snapshot.capacity
        k = min(sched0.cand_k, n)
        spread = sched0.cand_spread
        method = sched0.cand_method
        if method == "auto":
            method = ("approx" if jax.default_backend() == "tpu"
                      else "exact")
        rounds = sched0.solve_rounds
        cfg = sched0.config
        fn = self._batched_fn((k, spread, method, rounds, has_quota))
        stacked_state = self._stack(states)
        stacked_batch = self._stack(batches)
        stacked_quota = self._stack(quotas) if has_quota else None
        a, st, q, est, ck, cn, cs = fn(
            stacked_state, stacked_batch, stacked_quota, cfg)
        for i, (t, handle) in enumerate(live):
            cache = ba.CandidateCache(
                self._unstack(ck, i), self._unstack(cn, i),
                self._unstack(cs, i))
            t.scheduler.round_adopt_batched(
                handle,
                self._unstack(a, i), self._unstack(st, i),
                self._unstack(q, i) if has_quota else None,
                self._unstack(est, i), cache, k, method)

    def _quality_batched_fn(self, has_quota: bool):
        """The jitted quality tenant-axis program: vmap of the full
        lp_pack_assign solve (default static iteration knobs, exactly
        the standalone quality branch's call).  The stacked state is
        donated — a stacking COPY, same contract as _batched_fn."""
        fn = self._quality_fns.get(has_quota)
        if fn is not None:
            return fn
        from koordinator_tpu.quality.lp_pack import lp_pack_assign

        def one_tenant(state, batch, quota, cfg):
            return lp_pack_assign(state, batch, cfg, quota)

        # koordlint: shape[state: TxNxR i32, batch: TxP i32, quota: TxQ i32]
        def program(state, batch, quota, cfg):
            return jax.vmap(
                one_tenant,
                in_axes=(0, 0, 0 if has_quota else None, None))(
                    state, batch, quota, cfg)

        fn = jax.jit(program, donate_argnums=(0,))
        self._quality_fns[has_quota] = fn
        return fn

    def _dispatch_quality_axis_inner(self, live) -> None:
        states = [t.scheduler.snapshot.state for t, _ in live]
        batches = [h.batch for _, h in live]
        quotas = [h.quota for _, h in live]
        has_quota = quotas[0] is not None
        cfg = live[0][0].scheduler.config
        # pre-solve slack per tenant (the quality_slack_recovered
        # baseline), dispatched against the ORIGINAL state buffers
        # before the donating program consumes the stacking copy —
        # the standalone quality branch's ordering
        slacks = [t.scheduler._slack_sums(state)
                  for (t, _), state in zip(live, states)]
        fn = self._quality_batched_fn(has_quota)
        a, st, q, qiters = fn(
            self._stack(states), self._stack(batches),
            self._stack(quotas) if has_quota else None, cfg)
        for i, (t, handle) in enumerate(live):
            t.scheduler.round_adopt_quality_batched(
                handle,
                self._unstack(a, i), self._unstack(st, i),
                self._unstack(q, i) if has_quota else None,
                self._unstack(qiters, i), slacks[i])

    # -- surfaces ------------------------------------------------------------

    def tenants_report(self) -> dict:
        """The /debug/tenants body (served by ``debug_tenants_body`` on
        both HTTP surfaces through any tenant's scheduler)."""
        tenants = []
        wsum = sum(max(t.spec.weight, 0.0)
                   for t in self._tenants.values()) or 1.0
        admitted_cycle = sum(t.last_admitted
                             for t in self._tenants.values())
        for t in self._tenants.values():
            sched = t.scheduler
            with sched.lock:
                doc = {
                    "name": t.name,
                    "weight": t.spec.weight,
                    "share_target": max(t.spec.weight, 0.0) / wsum,
                    "share_observed": (
                        t.last_admitted / admitted_cycle
                        if admitted_cycle else 0.0),
                    "credit": round(t.credit, 3),
                    "admitted_last_cycle": t.last_admitted,
                    "admitted_total": t.admitted_total,
                    "overflow_last_round": sched.last_overflow,
                    "rounds": t.rounds,
                    "pending": len(sched.pending),
                    "bound": len(sched.bound),
                    "degraded": sched.degraded,
                    "suspended": sched.last_suspended,
                    "staleness_s": sched._last_staleness_s,
                    "last_solve_path": sched.last_solve_path,
                    "node_capacity": sched.snapshot.capacity,
                    "nodes": len(sched.snapshot.node_index),
                }
            tenants.append(doc)
        return {
            "tenants": tenants,
            "cycle": {
                "seq": self.cycle_seq,
                "mode": self.last_mode,
                "pod_budget": self.cycle_pod_budget,
                "duration_s": self.last_cycle_s,
                "host_wait_fraction": self.last_host_wait_fraction,
                "pipeline": self.pipeline,
                "batch_tenant_axis": self.batch_tenant_axis,
            },
            "kit": {
                "shards": self.kit.shards,
                "mesh": self.kit.mesh is not None,
            },
        }
