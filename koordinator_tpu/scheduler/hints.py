"""Scheduling hints, cross-scheduler nomination, in-place pod resize
(reference: ``frameworkext/hinter`` + ``plugins/schedulinghint``,
``frameworkext/cross_scheduler_nominator.go``, the ResizePod feature gate and
``RunResizePod``, ``framework_extender.go:837``).

- :class:`SchedulingHints`: per-pod preferred/excluded node sets recorded by
  earlier attempts or external hinters; consumed as a feasibility-mask edit
  plus a score bonus at batch-build time.
- :class:`CrossSchedulerNominator`: nominated (pod -> node, resources) from
  other scheduler instances; their claims are charged into the snapshot so a
  concurrently-deciding scheduler doesn't double-book the capacity.
- :func:`resize_pod`: validate + apply an in-place resource resize of a bound
  pod against its node's free capacity.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from koordinator_tpu.scheduler.snapshot import ClusterSnapshot


@dataclasses.dataclass
class PodHint:
    preferred_nodes: set[str] = dataclasses.field(default_factory=set)
    excluded_nodes: set[str] = dataclasses.field(default_factory=set)
    #: bonus added to preferred nodes' scores (schedulinghint plugin weight)
    preference_bonus: int = 20


class SchedulingHints:
    def __init__(self, snapshot: ClusterSnapshot):
        self.snapshot = snapshot
        self._hints: dict[str, PodHint] = {}

    def set_hint(self, pod_name: str, hint: PodHint) -> None:
        self._hints[pod_name] = hint

    def record_failure(self, pod_name: str, node: str) -> None:
        """A failed placement excludes that node from the next attempt
        (the hinter's negative-cache behavior)."""
        self._hints.setdefault(pod_name, PodHint()).excluded_nodes.add(node)

    def clear(self, pod_name: str) -> None:
        self._hints.pop(pod_name, None)

    def has_hint(self, pod_name: str) -> bool:
        return pod_name in self._hints

    def apply_to_mask(self, pod_name: str, feasible: np.ndarray) -> np.ndarray:
        """Edit one pod's (N,) feasibility row: drop excluded nodes; if any
        preferred node is feasible, restrict to the preferred set (the
        skip/prefer semantics of the schedulinghint plugin)."""
        hint = self._hints.get(pod_name)
        if hint is None:
            return feasible
        out = feasible.copy()
        for node in hint.excluded_nodes:
            row = self.snapshot.node_index.get(node)
            if row is not None:
                out[row] = False
        if hint.preferred_nodes:
            preferred = np.zeros_like(out)
            any_pref = False
            for node in hint.preferred_nodes:
                row = self.snapshot.node_index.get(node)
                if row is not None and out[row]:
                    preferred[row] = True
                    any_pref = True
            if any_pref:
                out = preferred
        return out


class CrossSchedulerNominator:
    """Nominations made by OTHER schedulers: charge their claimed resources
    into the snapshot so this scheduler's solve sees them as used; release
    when the owning scheduler binds or abandons."""

    def __init__(self, snapshot: ClusterSnapshot):
        self.snapshot = snapshot
        #: (node, requests, node_generation) — the release must target
        #: the node INSTANCE the charge was made against
        self._nominations: dict[str, tuple[str, np.ndarray, int]] = {}

    def nominate(self, pod_uid: str, node: str, requests: np.ndarray) -> bool:
        if pod_uid in self._nominations:
            return False
        if node not in self.snapshot.node_index:
            return False
        self.snapshot.reserve(node, requests)
        self._nominations[pod_uid] = (
            node, np.asarray(requests),
            self.snapshot.node_generation.get(node, 0))
        return True

    def release(self, pod_uid: str) -> None:
        entry = self._nominations.pop(pod_uid, None)
        if entry is None:
            return
        node, requests, generation = entry
        self.snapshot.unreserve_instance(node, requests, generation)

    def nominated_node(self, pod_uid: str) -> Optional[str]:
        entry = self._nominations.get(pod_uid)
        return entry[0] if entry else None


def resize_pod(
    snapshot: ClusterSnapshot,
    node: str,
    old_requests: np.ndarray,
    new_requests: np.ndarray,
) -> tuple[bool, str]:
    """In-place resize of a bound pod (ResizePod/RunResizePod): the delta must
    fit the node's remaining free capacity; growth is charged, shrink is
    released. Returns (ok, reason)."""
    row = snapshot.node_index.get(node)
    if row is None:
        return False, f"node {node} not found"
    old = np.asarray(old_requests, np.int64)
    new = np.asarray(new_requests, np.int64)
    delta = new - old
    if np.any(delta > 0):
        snapshot.flush()
        free = np.asarray(snapshot.state.free)[row]
        if np.any(delta > free):
            lacking = int(np.argmax(delta - free))
            return False, f"insufficient free capacity on dim {lacking}"
    grow = np.maximum(delta, 0).astype(np.int32)
    shrink = np.maximum(-delta, 0).astype(np.int32)
    if grow.any():
        snapshot.reserve(node, grow)
    if shrink.any():
        snapshot.unreserve(node, shrink)
    return True, ""
