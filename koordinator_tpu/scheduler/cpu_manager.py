"""Host-side CPU allocation manager: per-node topology registry + commits.

The Reserve-phase counterpart of the reference's nodenumaresource
resource_manager (pkg/scheduler/plugins/nodenumaresource/resource_manager.go:
allocateCPUSet, Update/Release) — tracks per-node per-cpu reference counts and
exclusivity, calls the :mod:`koordinator_tpu.ops.numa` take kernel, and
produces the cpuset annotation payload (apis/extension/numa_aware.go
resource-status) that the node agent's cpuset hook applies.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.numa import (
    BIND_DEFAULT,
    EXCLUSIVE_NONE,
    EXCLUSIVE_NUMA_LEVEL,
    EXCLUSIVE_PCPU_LEVEL,
    STRATEGY_MOST_ALLOCATED,
    CPUTopology,
    take_cpus,
)


@dataclasses.dataclass
class CPUAllocation:
    pod: str
    cpus: list[int]
    exclusive_policy: int = EXCLUSIVE_NONE


@dataclasses.dataclass
class NodeCPUState:
    topology: CPUTopology
    ref_count: np.ndarray                    # (C,) int32
    max_ref: int = 1
    allocations: dict[str, CPUAllocation] = dataclasses.field(default_factory=dict)


class CPUManager:
    """Registry of node CPU topologies + allocation bookkeeping."""

    def __init__(self) -> None:
        self._nodes: dict[str, NodeCPUState] = {}
        #: allocations preserved across a topology disappearance (e.g. a
        #: transient annotation-less node re-upsert removed the node):
        #: restored when the topology re-registers, so exclusive cores
        #: held by still-bound pods cannot be granted twice after the
        #: NRT annotation returns
        self._orphans: dict[str, dict[str, CPUAllocation]] = {}

    def register_node(
        self, name: str, topology: CPUTopology, max_ref: int = 1
    ) -> None:
        """(Re-)register a node's topology.  Node objects re-sync on every
        heartbeat, so a re-registration must carry live allocations over —
        wiping ref counts would let exclusive cores be granted twice."""
        old = self._nodes.get(name)
        if (old is not None and old.max_ref == max_ref
                and old.topology.capacity == topology.capacity
                and bool(np.array_equal(np.asarray(old.topology.core_of),
                                        np.asarray(topology.core_of)))
                and bool(np.array_equal(np.asarray(old.topology.numa_of),
                                        np.asarray(topology.numa_of)))
                and bool(np.array_equal(np.asarray(old.topology.socket_of),
                                        np.asarray(topology.socket_of)))
                and bool(np.array_equal(np.asarray(old.topology.valid),
                                        np.asarray(topology.valid)))):
            return   # unchanged heartbeat: keep state as-is
        st = NodeCPUState(
            topology=topology,
            ref_count=np.zeros(topology.capacity, np.int32),
            max_ref=max_ref,
        )
        valid = np.asarray(topology.valid)
        carried = dict(old.allocations) if old is not None else {}
        # a topology that vanished and returned (remove_node stashed the
        # allocations) restores them too — live records win over orphans
        for pod, alloc in self._orphans.pop(name, {}).items():
            carried.setdefault(pod, alloc)
        for pod, alloc in carried.items():
            cpus = [c for c in alloc.cpus
                    if c < len(valid) and valid[c]]
            if cpus:
                st.ref_count[cpus] += 1
                st.allocations[pod] = CPUAllocation(
                    pod, cpus, alloc.exclusive_policy)
        self._nodes[name] = st

    def node(self, name: str) -> NodeCPUState | None:
        return self._nodes.get(name)

    def clear(self) -> None:
        """Drop all topologies and CPU allocations — snapshot-resync
        restart semantics (SchedulerBinding.reset); the replayed
        snapshot's NRT annotations re-register what still exists and
        the bound-pod replay restores allocations."""
        self._nodes.clear()
        self._orphans.clear()

    def remove_node(self, name: str) -> None:
        """Drop one node's topology — the node's NRT annotation
        disappeared (or the node did): fine-grained CPU placement on it
        is no longer possible, and keeping the stale topology would
        diverge from what a bootstrap replay builds.  Allocations are
        STASHED, not dropped: if the disappearance was transient (an
        annotation-less re-upsert racing the koordlet's NRT report),
        the re-registration restores them — wiping ref counts would let
        exclusive cores be granted twice."""
        st = self._nodes.pop(name, None)
        if st is not None and st.allocations:
            stash = self._orphans.setdefault(name, {})
            for pod, alloc in st.allocations.items():
                stash[pod] = alloc

    def _banned_mask(self, st: NodeCPUState, pod_policy: int) -> np.ndarray:
        """CPUs excluded by other pods' exclusivity or by this pod's own
        exclusivity requirement (isCPUExclusivePCPULevel/NUMANodeLevel)."""
        topo = st.topology
        core_of = np.asarray(topo.core_of)
        numa_of = np.asarray(topo.numa_of)
        banned = np.zeros(topo.capacity, bool)
        for alloc in st.allocations.values():
            if not alloc.cpus:
                continue
            # Other pods' exclusivity claims...
            if alloc.exclusive_policy == EXCLUSIVE_PCPU_LEVEL:
                banned |= np.isin(core_of, core_of[alloc.cpus])
            elif alloc.exclusive_policy == EXCLUSIVE_NUMA_LEVEL:
                banned |= np.isin(numa_of, numa_of[alloc.cpus])
            # ...AND this pod's own requirement apply independently.
            if pod_policy == EXCLUSIVE_PCPU_LEVEL:
                # This pod wants whole cores: cores already referenced by
                # anyone are off limits.
                banned |= np.isin(core_of, core_of[alloc.cpus])
            elif pod_policy == EXCLUSIVE_NUMA_LEVEL:
                # This pod wants whole NUMA nodes to itself.
                banned |= np.isin(numa_of, numa_of[alloc.cpus])
        return banned

    def allocate(
        self,
        node: str,
        pod: str,
        n_cpus: int,
        bind_policy: int = BIND_DEFAULT,
        strategy: int = STRATEGY_MOST_ALLOCATED,
        exclusive_policy: int = EXCLUSIVE_NONE,
    ) -> list[int] | None:
        """Pick and commit a cpuset; returns sorted cpu ids or None."""
        st = self._nodes.get(node)
        if st is None:
            return None
        # Re-allocate: free the old cpuset for the attempt, but restore it if
        # the new selection fails (the pod keeps running on its old cpus).
        old = st.allocations.get(pod)
        if old is not None:
            self.release(node, pod)
        banned = self._banned_mask(st, exclusive_policy)
        selected, ok = take_cpus(
            st.topology,
            jnp.asarray(st.ref_count),
            jnp.int32(st.max_ref),
            jnp.int32(n_cpus),
            bind_policy=bind_policy,
            strategy=strategy,
            banned=jnp.asarray(banned),
        )
        if not bool(ok):
            if old is not None:
                st.ref_count[old.cpus] += 1
                st.allocations[pod] = old
            return None
        cpus = sorted(int(i) for i in np.flatnonzero(np.asarray(selected)))
        st.ref_count[cpus] += 1
        st.allocations[pod] = CPUAllocation(pod, cpus, exclusive_policy)
        return cpus

    def restore(self, node: str, pod: str, cpus: list[int],
                exclusive_policy: int = EXCLUSIVE_NONE) -> bool:
        """Replay a pod's existing cpuset at startup (the reference restores
        allocations from pod resource-status annotations): commits the exact
        cpus without running selection.  Annotation data is external: cpu
        ids outside the registered topology reject the whole restore (the
        pod falls back to unpinned) rather than corrupting ref counts."""
        st = self._nodes.get(node)
        if st is None or not cpus:
            return False
        cpus = sorted({int(c) for c in cpus})
        valid = np.asarray(st.topology.valid)
        # bounds AND the valid mask: topology capacities are padded to a
        # power of two with zeroed core/numa ids — a stale id landing in the
        # padding would ban core 0 for every future exclusive pod
        if cpus[0] < 0 or cpus[-1] >= len(valid) or not valid[cpus].all():
            return False
        self.release(node, pod)   # idempotent replay
        st.ref_count[cpus] += 1
        st.allocations[pod] = CPUAllocation(pod, cpus, exclusive_policy)
        return True

    def release(self, node: str, pod: str) -> None:
        # purge any orphaned record too: a pod deleted while the node's
        # topology was absent must not resurrect on re-registration
        orphans = self._orphans.get(node)
        if orphans is not None:
            orphans.pop(pod, None)
            if not orphans:
                del self._orphans[node]
        st = self._nodes.get(node)
        if st is None:
            return
        alloc = st.allocations.pop(pod, None)
        if alloc is not None:
            st.ref_count[alloc.cpus] -= 1

    def resource_status(self, node: str, pod: str) -> dict | None:
        """The scheduling.koordinator.sh/resource-status annotation payload."""
        st = self._nodes.get(node)
        if st is None or pod not in st.allocations:
            return None
        alloc = st.allocations[pod]
        numa_of = np.asarray(st.topology.numa_of)
        return {
            "cpuset": ",".join(str(c) for c in alloc.cpus),
            "numaNodeResources": sorted(
                {int(numa_of[c]) for c in alloc.cpus}
            ),
        }


def parse_cpuset_bounded(s: str, limit: int = 1024) -> list[int]:
    """Parse a "0-3,8" cpuset string with a hard size bound (annotation
    data is external; the shared procfs parser enforces the limit)."""
    from koordinator_tpu.koordlet.system.procfs import parse_cpu_list

    return parse_cpu_list(str(s), limit=limit)


def register_node_from_annotations(
    mgr: CPUManager, name: str, annotations: dict[str, str]
) -> bool:
    """NRT bridge: parse the koordlet's cpu-topology annotation
    (nodetopo.NodeTopology.to_annotations; the reference's
    nodenumaresource/topology_options.go reads the same payload) and
    register the node's topology with the CPU manager."""
    import json

    raw = annotations.get("node.koordinator.sh/cpu-topology", "")
    if not raw:
        return False
    try:
        detail = json.loads(raw)["detail"]
        if not detail:
            return False
        core_of = np.asarray([d["core"] for d in detail], np.int32)
        numa_of = np.asarray([d["node"] for d in detail], np.int32)
        socket_of = np.asarray([d["socket"] for d in detail], np.int32)
    except (ValueError, KeyError, TypeError):
        # annotation payloads are external data: malformed entries reject
        # the registration instead of crashing node processing
        return False
    mgr.register_node(name, CPUTopology.build(core_of, numa_of, socket_of))
    return True
