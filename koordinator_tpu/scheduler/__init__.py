"""The scheduler shell: queue, snapshot sync, phase pipeline, diagnosis.

The reference wraps the upstream k8s scheduler framework with frameworkext
(SURVEY.md section 2.3); here the "framework" is the host-side orchestration around
the batched TPU solve:

- ``snapshot``  -- incremental host->device cluster-state sync (name->row
                   maps, delta scatter updates, capacity bucketing)
- ``scheduler`` -- the scheduling loop: priority queue, gang manager, batched
                   solve rounds, Reserve accounting, bind callbacks
- ``diagnosis`` -- structured "why unschedulable" explanations
                   (schedule_diagnosis.go equivalent)
- ``monitor``   -- per-round phase timing watchdog (scheduler_monitor.go)
"""

from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, NodeSpec, PodSpec
from koordinator_tpu.scheduler.scheduler import Scheduler, SchedulingResult
from koordinator_tpu.scheduler.diagnosis import explain_pod
from koordinator_tpu.scheduler.monitor import SchedulerMonitor

__all__ = [
    "ClusterSnapshot",
    "NodeSpec",
    "PodSpec",
    "Scheduler",
    "SchedulingResult",
    "explain_pod",
    "SchedulerMonitor",
]
