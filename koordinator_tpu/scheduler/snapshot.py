"""Incremental cluster snapshot: informer deltas -> device tensors.

The reference scheduler snapshots its node cache every cycle (upstream
snapshotting model, SURVEY.md section 5 "race detection"); the TPU rebuild keeps the
cluster resident on device and applies *deltas*: the host maintains
name -> row maps and dirty-row buffers, and ``flush()`` ships only changed rows
(``ClusterState.scatter_update``). Capacity grows by power-of-two buckets so
recompilation is O(log N) over cluster life (SURVEY.md section 7 hard part (a)/(b)).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS
from koordinator_tpu.state.cluster_state import ClusterState, _bucket

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class NodeSpec:
    """Host-side node record (what the Node informer + NodeMetric deliver)."""

    name: str
    allocatable: np.ndarray                 # (R,) int32
    usage: np.ndarray | None = None         # (R,) int32
    agg_usage: np.ndarray | None = None     # (R,) int32
    prod_usage: np.ndarray | None = None    # (R,) int32
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    #: NoSchedule taints as key -> value (a pod needs a matching toleration)
    taints: dict[str, str] = dataclasses.field(default_factory=dict)

    def signature(self) -> tuple:
        """Label/taint equivalence-class signature: nodes with equal
        signatures are interchangeable for selector/toleration filtering."""
        return (
            tuple(sorted(self.labels.items())),
            tuple(sorted(self.taints.items())),
        )


@dataclasses.dataclass
class PodSpec:
    """Host-side pending pod (what the webhook-mutated Pod object carries)."""

    name: str
    requests: np.ndarray                    # (R,) int32
    priority: int = 0
    qos: int = 0
    gang: str | None = None
    quota: str | None = None
    non_preemptible: bool = False
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    #: tolerated NoSchedule taints (key -> value)
    tolerations: dict[str, str] = dataclasses.field(default_factory=dict)
    creation: float = 0.0
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    owner: str | None = None               # controller key for reservation owner match
    #: pod.spec.preemptionPolicy — "Never" opts out of preempting others
    #: (PodEligibleToPreemptOthers, elasticquota/preempt.go:62)
    preemption_policy: str = "PreemptLowerPriority"
    #: manager-side ingest wall-clock (journey ledger, ISSUE 20); 0.0 when
    #: no stamp rode deltasync in.  Never read by solve or the pending
    #: sort key — that is `creation` — so it cannot perturb decisions.
    arrival_ts: float = 0.0


class ClusterSnapshot:
    """Name-indexed view over the device-resident ClusterState."""

    def __init__(self, capacity: int = 64, dims: int = NUM_RESOURCE_DIMS):
        self.dims = dims
        self.state = ClusterState.zeros(capacity, dims)
        self.node_index: dict[str, int] = {}
        self._row_to_name: dict[int, str] = {}
        self.node_specs: dict[str, NodeSpec] = {}
        self._free_rows: list[int] = list(range(capacity - 1, -1, -1))
        self._dirty: set[int] = set()
        #: rows whose solver-visible state changed since the incremental
        #: candidate cache last consumed them (superset of _dirty: spec
        #: upserts AND accounting changes — reserve/unreserve/solve
        #: adoption — land here; _dirty only tracks host-spec rows
        #: pending a device flush).  The scheduler's candidate cache
        #: derives its dirty-node column mask from this set.
        self._cand_dirty: set[int] = set()
        # rows whose solver-accumulated node_requested must be zeroed at next
        # flush (freed by remove_node; a reused row must not inherit the dead
        # node's accounting)
        self._reset_requested: set[int] = set()
        #: per-name INSTANCE counter, bumped each time a name (re)appears
        #: with a fresh row: a pod bound to the previous instance of a
        #: removed-then-readded node must not decrement the new one
        #: (re-add starts clean — see _reset_requested above)
        self.node_generation: dict[str, int] = {}
        # label/taint equivalence classes: signature -> class id. Ids are
        # never recycled (bounded by distinct signatures ever seen); the
        # (P, C) selector masks index them via ClusterState.node_class.
        self._class_index: dict[tuple, int] = {}
        self._class_sigs: list[tuple] = []
        #: clock time of the last applied sync event (delta/heartbeat)
        #: from whatever informer feeds this snapshot; None until the
        #: feed first speaks.  The scheduler's staleness watchdog reads
        #: the AGE of this stamp — a stalled feed means every usage- and
        #: batch-allocatable-derived row here is untrustworthy.
        self.last_sync_time: float | None = None
        #: solver-mesh placement (scheduler-owned): when set, the state's
        #: node tensors live node-axis-sharded over the mesh so the
        #: sharded solve entries donate them IN PLACE instead of
        #: resharding per call.  Applied lazily — only once the capacity
        #: both divides over the shard count and reaches the min-nodes
        #: floor (sharding a tiny cluster is pure collective overhead).
        self._solver_sharding = None
        self._solver_shards = 1
        self._solver_shard_min_nodes = 0

    def set_solver_sharding(self, sharding, shards: int,
                            min_nodes: int = 0) -> None:
        """Install the solver mesh's node-axis placement (see above)."""
        self._solver_sharding = sharding
        self._solver_shards = max(int(shards), 1)
        self._solver_shard_min_nodes = int(min_nodes)
        self._apply_solver_sharding()

    @property
    def solver_sharding_active(self) -> bool:
        """True when the CURRENT capacity solves on the sharded path."""
        return (self._solver_sharding is not None
                and self.capacity % self._solver_shards == 0
                and self.capacity >= self._solver_shard_min_nodes)

    def _apply_solver_sharding(self) -> None:
        if self.solver_sharding_active:
            ns = self._solver_sharding
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, ns), self.state)

    def mark_sync(self, now: float) -> None:
        """Stamp feed liveness (monotonic under the writer's clock)."""
        self.last_sync_time = now

    def staleness(self, now: float) -> float | None:
        """Seconds since the feed last spoke; None before first contact."""
        if self.last_sync_time is None:
            return None
        return max(0.0, now - self.last_sync_time)

    @property
    def class_capacity(self) -> int:
        """Padded equivalence-class count for (P, C) selector masks."""
        return _bucket(max(len(self._class_sigs), 1), minimum=8)

    @property
    def class_count(self) -> int:
        """Registered equivalence classes (monotonic — ids never recycle);
        cache keys use this, not class_capacity, so a new class within the
        same padding bucket still invalidates."""
        return len(self._class_sigs)

    def _class_of(self, spec: NodeSpec) -> int:
        sig = spec.signature()
        cid = self._class_index.get(sig)
        if cid is None:
            cid = len(self._class_sigs)
            self._class_index[sig] = cid
            self._class_sigs.append(sig)
        return cid

    @staticmethod
    def _pod_allows(pod: PodSpec, labels: tuple, taints: tuple) -> bool:
        lbl = dict(labels)
        if any(lbl.get(k) != v for k, v in pod.node_selector.items()):
            return False
        return all(pod.tolerations.get(k) == v for k, v in taints)

    def selector_row_for(self, pod: PodSpec) -> np.ndarray:
        """(class_capacity,) bool: which node equivalence classes the pod's
        nodeSelector + tolerations admit. O(C) per pod — the factored
        replacement for the O(N) feasibility_row walk."""
        row = np.zeros(self.class_capacity, bool)
        for cid, (labels, taints) in enumerate(self._class_sigs):
            row[cid] = self._pod_allows(pod, labels, taints)
        return row

    @property
    def capacity(self) -> int:
        return self.state.capacity

    # -- node lifecycle -----------------------------------------------------

    def upsert_node(self, spec: NodeSpec) -> int:
        row = self.node_index.get(spec.name)
        if row is None:
            if not self._free_rows:
                self._grow()
            row = self._free_rows.pop()
            if row in self._reset_requested:
                # a freed row reused BEFORE the pending flush: zero the
                # dead node's accumulated requested NOW — deferring to
                # flush would also wipe any charge made against the new
                # instance in between (e.g. a pinned reservation's
                # make_available, a cross-scheduler nomination), whose
                # later generation-checked release would then drive
                # node_requested negative
                self._reset_requested.discard(row)
                self.state = self.state.replace(
                    node_requested=self.state.node_requested.at[row].set(0))
            self.node_index[spec.name] = row
            self._row_to_name[row] = spec.name
            self.node_generation[spec.name] = (
                self.node_generation.get(spec.name, -1) + 1)
        self.node_specs[spec.name] = spec
        self._class_of(spec)  # register the equivalence class up front
        self._dirty.add(row)
        self._cand_dirty.add(row)
        return row

    def remove_node(self, name: str) -> None:
        row = self.node_index.pop(name, None)
        if row is None:
            return
        del self.node_specs[name]
        del self._row_to_name[row]
        self._free_rows.append(row)
        self._dirty.add(row)
        self._cand_dirty.add(row)
        self._reset_requested.add(row)

    def _grow(self) -> None:
        old_cap = self.capacity
        new_cap = _bucket(old_cap + 1)
        old = self.state

        def pad(a):
            out = np.zeros((new_cap,) + a.shape[1:], a.dtype)
            out[:old_cap] = np.asarray(a)
            return jnp.asarray(out)

        self.state = ClusterState(
            node_allocatable=pad(old.node_allocatable),
            node_requested=pad(old.node_requested),
            node_usage=pad(old.node_usage),
            node_agg_usage=pad(old.node_agg_usage),
            node_prod_usage=pad(old.node_prod_usage),
            node_valid=pad(old.node_valid),
            node_class=pad(old.node_class),
        )
        self._free_rows = list(range(new_cap - 1, old_cap - 1, -1)) + self._free_rows
        self._apply_solver_sharding()

    # -- delta flush ---------------------------------------------------------

    def flush(self) -> int:
        """Ship dirty rows to device in one scatter. Returns rows shipped."""
        if not self._dirty:
            return 0
        rows = sorted(self._dirty)
        self._dirty.clear()
        if self._reset_requested:
            reset = jnp.asarray(sorted(self._reset_requested), dtype=jnp.int32)
            self._reset_requested.clear()
            self.state = self.state.replace(
                node_requested=self.state.node_requested.at[reset].set(0)
            )
        k = len(rows)
        alloc = np.zeros((k, self.dims), np.int32)
        usage = np.zeros((k, self.dims), np.int32)
        agg = np.zeros((k, self.dims), np.int32)
        prod = np.zeros((k, self.dims), np.int32)
        valid = np.zeros(k, bool)
        nclass = np.zeros(k, np.int32)
        for i, r in enumerate(rows):
            name = self._row_to_name.get(r)
            if name is None:
                continue  # removed node: stays zero/invalid
            spec = self.node_specs[name]
            alloc[i] = spec.allocatable
            if spec.usage is not None:
                usage[i] = spec.usage
            agg[i] = spec.agg_usage if spec.agg_usage is not None else usage[i]
            prod[i] = spec.prod_usage if spec.prod_usage is not None else usage[i]
            valid[i] = True
            nclass[i] = self._class_of(spec)
        idx = jnp.asarray(np.asarray(rows, np.int32))
        # donate=True: the snapshot owns its state exclusively, so the
        # (N, R) tensors update in place instead of reallocating per flush
        self.state = self.state.scatter_update(
            idx,
            donate=True,
            node_allocatable=jnp.asarray(alloc),
            node_usage=jnp.asarray(usage),
            node_agg_usage=jnp.asarray(agg),
            node_prod_usage=jnp.asarray(prod),
            node_valid=jnp.asarray(valid),
            node_class=jnp.asarray(nclass),
        )
        return k

    # -- accounting ---------------------------------------------------------

    def reserve(self, node: str, requests: np.ndarray) -> None:
        """Account a binding onto a node (Reserve)."""
        row = self.node_index[node]
        self._cand_dirty.add(row)
        self.state = self.state.add_pod(
            jnp.asarray(np.int32(row)), jnp.asarray(requests.astype(np.int32))
        )

    def reserve_batch(self, requests_by_node) -> None:
        """Account many bindings in ONE device op (startup informer
        replay, warm-restart checkpoint restore).  Bit-identical to
        sequential :meth:`reserve` — integer adds commute — but the
        scatter cost is paid once instead of per pod, which is what
        makes a checkpoint restore cheaper than re-placing the same
        pods through rounds."""
        if not requests_by_node:
            return
        add = np.zeros(self.state.node_requested.shape, dtype=np.int32)
        for node, requests in requests_by_node.items():
            row = self.node_index[node]
            self._cand_dirty.add(row)
            add[row] += requests.astype(np.int32)
        self.state = self.state.replace(
            node_requested=self.state.node_requested + jnp.asarray(add))

    def unreserve(self, node: str, requests: np.ndarray) -> None:
        row = self.node_index[node]
        self._cand_dirty.add(row)
        self.state = self.state.remove_pod(
            jnp.asarray(np.int32(row)), jnp.asarray(requests.astype(np.int32))
        )

    def unreserve_instance(self, node: str, requests: np.ndarray,
                           generation: int) -> None:
        """Release a charge made against a SPECIFIC node instance: a
        no-op when the node is gone or the name now labels a fresh
        instance (re-add starts clean — decrementing it would drive
        node_requested negative).  Every release whose record can
        outlive the node (bound pods, nominations, reservation
        remainders) must come through here."""
        if node not in self.node_index:
            return
        if self.node_generation.get(node, 0) != generation:
            return
        self.unreserve(node, requests)

    def adopt_state(self, state: ClusterState,
                    changed_rows=None) -> None:
        """Adopt solver-updated accounting (post gang/greedy assign).

        ``changed_rows`` names the node rows whose ``node_requested`` the
        solver touched (the assigned rows) so the candidate cache only
        invalidates those; None is the conservative default — every
        valid row is treated as dirty."""
        if state.capacity != self.capacity:
            raise ValueError("state capacity mismatch")
        if changed_rows is None:
            self._cand_dirty.update(self.node_index.values())
        else:
            self._cand_dirty.update(int(r) for r in changed_rows)
        self.state = state

    def rebuild_conservative(self) -> None:
        """Disaster recovery for a DONATED-then-failed device state: a
        jitted solve that fails at execution time has already consumed
        the old buffers, so the accounting tensor (node_requested) is
        unrecoverable host-side.  Rebuild the spec-side tensors from
        ``node_specs`` and mark every valid node FULLY BOOKED
        (requested = allocatable): the scheduler keeps running and never
        overcommits, but places nothing new on existing nodes until a
        sync resync (SchedulerBinding.reset + bootstrap) or node churn
        restores exact accounting.  Releases stay safe: true bookings
        are always <= allocatable, so subtracting a released pod keeps
        the conservative row >= the true remaining bookings."""
        self.state = ClusterState.zeros(self.capacity, self.dims)
        self._apply_solver_sharding()
        self._reset_requested.clear()
        self._dirty.update(self.node_index.values())
        self._cand_dirty.update(self.node_index.values())
        self.flush()
        self.state = self.state.replace(
            node_requested=jnp.where(self.state.node_valid[:, None],
                                     self.state.node_allocatable,
                                     0))

    def consume_candidate_dirty(self) -> list[int]:
        """Rows dirtied since the last consume (sorted), clearing the set
        — called exactly when the candidate cache is rebuilt/refreshed."""
        rows = sorted(self._cand_dirty)
        self._cand_dirty.clear()
        return rows

    # -- queries ------------------------------------------------------------

    def node_name(self, row: int) -> str | None:
        return self._row_to_name.get(row)

    def feasibility_row(self, pod: PodSpec) -> np.ndarray:
        """(N,) bool host-computed selector/toleration mask for one pod.

        The dense path — used where per-(pod, node) edits are needed
        (scheduling hints, topology pins); the hot path uses
        :meth:`selector_row_for` + ``ClusterState.node_class`` instead.
        """
        mask = np.zeros(self.capacity, bool)
        for name, row in self.node_index.items():
            spec = self.node_specs[name]
            mask[row] = self._pod_allows(
                pod, tuple(spec.labels.items()), tuple(spec.taints.items())
            )
        return mask
