"""Scheduler monitor: per-round phase timing with slow-round logging.

Equivalent of ``frameworkext/scheduler_monitor.go:44-100`` — records how long
each scheduling phase takes, keeps a rolling history, and flags rounds that
exceed the configured timeout (the reference logs pods stuck in a phase).
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict, deque

from koordinator_tpu import metrics

logger = logging.getLogger("koordinator_tpu.scheduler")


class SchedulerMonitor:
    def __init__(self, timeout_sec: float = 1.0, history: int = 256,
                 clock=time.perf_counter):
        self.timeout_sec = timeout_sec
        self.clock = clock
        self.phase_history: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=history)
        )
        self.slow_rounds = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        start = self.clock()
        try:
            yield
        finally:
            elapsed = self.clock() - start
            self.phase_history[name].append(elapsed)
            # feed the prometheus surface too (the reference exports
            # scheduling-cycle latency per phase from the same hook)
            metrics.scheduling_latency.observe(
                elapsed, labels={"phase": name})
            if name == "Solve":
                metrics.solver_batch_latency.observe(elapsed)
            if elapsed > self.timeout_sec:
                self.slow_rounds += 1
                logger.warning(
                    "scheduling phase %s took %.3fs (timeout %.3fs)",
                    name, elapsed, self.timeout_sec,
                )

    def stats(self) -> dict[str, dict[str, float]]:
        out = {}
        for name, hist in self.phase_history.items():
            if not hist:
                continue
            s = sorted(hist)
            out[name] = {
                "count": float(len(s)),
                "mean": sum(s) / len(s),
                "p50": s[len(s) // 2],
                "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                "max": s[-1],
            }
        return out
