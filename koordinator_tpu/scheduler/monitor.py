"""Scheduler monitor: per-round phase timing with slow-round logging.

Equivalent of ``frameworkext/scheduler_monitor.go:44-100`` — records how long
each scheduling phase takes, keeps a rolling history, and flags rounds that
exceed the configured timeout (the reference logs pods stuck in a phase).

Observability duties (PR 3): each phase is also a trace span (child of
the round span the scheduler opens, when one is active) and feeds the
``scheduling_duration_seconds`` histogram WITH a trace-id exemplar, so a
latency outlier on the dashboard links straight to the round trace that
produced it.  ``start_round()``/``round_timings`` expose the CURRENT
round's per-phase wall times for the flight recorder.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict, deque

from koordinator_tpu import metrics, timeline, tracing

logger = logging.getLogger("koordinator_tpu.scheduler")


class SchedulerMonitor:
    def __init__(self, timeout_sec: float = 1.0, history: int = 256,
                 clock=time.perf_counter):
        self.timeout_sec = timeout_sec
        self.clock = clock
        self.phase_history: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=history)
        )
        self.slow_rounds = 0
        #: per-phase wall times of the round in flight (reset by
        #: start_round; the flight recorder snapshots it at round end)
        self.round_timings: dict[str, float] = {}
        #: tenancy identity (ISSUE 11): when set, every phase
        #: observation additionally carries a {tenant=...} label so the
        #: per-tenant p99 SLO and dashboards can slice one histogram
        self.tenant = ""

    def start_round(self) -> None:
        """Reset the per-round phase accumulator (called by the
        scheduler at round start, under the round lock)."""
        self.round_timings = {}

    @contextlib.contextmanager
    def phase(self, name: str, carry_s: float = 0.0):
        """``carry_s`` folds wall time measured OUTSIDE this context
        into the phase's one observation — the pipelined round split
        times the solve dispatch in the device half and carries it into
        the host half's "Solve" phase, so a round still produces exactly
        one Solve observation (the SLO engine's per-observation bad
        fractions must not dilute)."""
        # phase spans only under an active trace (the scheduler's round
        # span): standalone monitor users pay nothing, traced rounds get
        # one child span per phase
        ctx = tracing.current_context()
        span_cm = (tracing.TRACER.span(f"phase.{name}") if ctx is not None
                   else contextlib.nullcontext())
        # the timeline segment is timed on perf_counter directly (not
        # self.clock, which tests may fake): cycle windows clip by real
        # monotonic time and a synthetic clock would mis-place segments
        tl_start = (time.perf_counter() if timeline.RECORDER.enabled
                    else 0.0)
        start = self.clock()
        try:
            with span_cm:
                yield
        finally:
            if timeline.RECORDER.enabled:
                timeline.RECORDER.add(
                    tl_start, time.perf_counter(),
                    timeline.PHASE_CAUSES.get(name, "host_other"),
                    f"phase.{name}", self.tenant)
            elapsed = self.clock() - start + carry_s
            self.phase_history[name].append(elapsed)
            self.round_timings[name] = (
                self.round_timings.get(name, 0.0) + elapsed)
            # feed the prometheus surface too (the reference exports
            # scheduling-cycle latency per phase from the same hook);
            # the exemplar links this observation to the round's trace
            exemplar = ({"trace_id": ctx.trace_id} if ctx is not None
                        else None)
            labels = {"phase": name}
            if self.tenant:
                labels["tenant"] = self.tenant
            metrics.scheduling_latency.observe(
                elapsed, labels=labels, exemplar=exemplar)
            if name == "Solve":
                metrics.solver_batch_latency.observe(
                    elapsed, exemplar=exemplar)
            if elapsed > self.timeout_sec:
                self.slow_rounds += 1
                logger.warning(
                    "scheduling phase %s took %.3fs (timeout %.3fs)",
                    name, elapsed, self.timeout_sec,
                )

    def stats(self) -> dict[str, dict[str, float]]:
        out = {}
        for name, hist in self.phase_history.items():
            if not hist:
                continue
            s = sorted(hist)
            out[name] = {
                "count": float(len(s)),
                "mean": sum(s) / len(s),
                "p50": s[len(s) // 2],
                "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
                "max": s[-1],
            }
        return out
