"""Host-side reservation lifecycle: phases, owner matching, expiration.

Mirrors the reference's reservation cache + controller
(pkg/scheduler/plugins/reservation/cache.go, controller/, and the phase
machine in apis/scheduling/v1alpha1/reservation_types.go: Pending ->
Available -> Succeeded | Failed/Expired). The branchy lifecycle stays on the
host (SURVEY.md section 7 hard part (e)); only the Available set is shipped to
the device as a :class:`~koordinator_tpu.ops.reservation.ReservationSet`.

Owner matching (reservation_types.go OwnerMatchers: label selector and/or
controller reference) is evaluated host-side into a dense (pods x
reservations) boolean matrix consumed by the fit kernels.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from koordinator_tpu.ops.reservation import ReservationSet
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, PodSpec


class ReservationPhase(enum.Enum):
    PENDING = "Pending"        # created, not yet placed on a node
    AVAILABLE = "Available"    # placed; owners may allocate
    SUCCEEDED = "Succeeded"    # allocate-once consumed / all owners bound
    FAILED = "Failed"
    EXPIRED = "Expired"


@dataclasses.dataclass
class OwnerMatcher:
    """One OwnerMatchers entry: pod matches if all selector kv-pairs match
    its labels AND (if set) its controller key equals ``controller``."""

    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    controller: str | None = None

    def matches(self, pod: PodSpec) -> bool:
        pod_labels = getattr(pod, "labels", {}) or {}
        if any(pod_labels.get(k) != v for k, v in self.labels.items()):
            return False
        if self.controller is not None:
            if getattr(pod, "owner", None) != self.controller:
                return False
        return True


@dataclasses.dataclass
class ReservationSpec:
    name: str
    requests: np.ndarray                    # (R,) reserved vector
    owners: list[OwnerMatcher] = dataclasses.field(default_factory=list)
    allocate_once: bool = False
    restricted: bool = False                # AllocatePolicy Restricted vs Aligned
    ttl_sec: float | None = None            # spec.ttl; None = never expires
    node: str | None = None                 # pre-pinned node (spec.template nodeName)
    #: reserve-pod template placement constraints (spec.template
    #: nodeSelector / tolerations) — honored by the placement solve
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: dict[str, str] = dataclasses.field(default_factory=dict)

    # status
    phase: ReservationPhase = ReservationPhase.PENDING
    allocated: np.ndarray | None = None     # (R,)
    owner_pods: list[str] = dataclasses.field(default_factory=list)
    available_at: float = 0.0
    created_at: float = 0.0                 # for Pending-phase TTL expiry
    #: instance identity: a same-named re-created reservation gets a new
    #: generation, so stale bind records can't credit the wrong instance
    generation: int = 0
    #: snapshot.node_generation at placement: the node INSTANCE the
    #: reserved vector was charged to — the remainder must not release
    #: against a re-added same-name node that started clean
    node_generation: int = 0


class ReservationCache:
    """Name-keyed reservation store + device-tensor builder."""

    def __init__(self) -> None:
        self._specs: dict[str, ReservationSpec] = {}
        self._next_generation = 1

    def __len__(self) -> int:
        return len(self._specs)

    def get(self, name: str) -> ReservationSpec | None:
        return self._specs.get(name)

    def upsert(self, spec: ReservationSpec) -> None:
        spec.generation = self._next_generation
        self._next_generation += 1
        self._specs[spec.name] = spec

    def gc(self) -> list[str]:
        """Drop terminal specs (EXPIRED / SUCCEEDED): their accounting is
        settled — an Expired reservation returned its remainder, a Succeeded
        one frees with its consuming pod (return_allocation rejects both by
        phase, so bind records of dead instances free their full vector)."""
        dead = [
            n for n, s in self._specs.items()
            if s.phase in (ReservationPhase.EXPIRED,
                           ReservationPhase.SUCCEEDED,
                           ReservationPhase.FAILED)
        ]
        for n in dead:
            del self._specs[n]
        return dead

    def remove(self, name: str, snapshot: ClusterSnapshot | None = None) -> None:
        spec = self._specs.pop(name, None)
        if spec is None:
            return
        if snapshot is not None and spec.phase is ReservationPhase.AVAILABLE:
            self._return_remainder(spec, snapshot)

    def make_available(
        self, name: str, node: str, snapshot: ClusterSnapshot,
        now: float = 0.0, charge: bool = True,
    ) -> None:
        """The reserve-pod got 'bound': charge the full reserved vector to the
        node (so ordinary pods can't see it) and open the reservation.
        ``charge=False`` is the solve path (_commit_reserve_pod), where
        the batch solve already charged the vector to node_requested —
        the ONE transition implementation serves both paths so a new
        field (as node_generation was) cannot be stamped in only one."""
        spec = self._specs[name]
        spec.node = node
        spec.node_generation = snapshot.node_generation.get(node, 0)
        spec.phase = ReservationPhase.AVAILABLE
        spec.available_at = now
        spec.allocated = np.zeros_like(spec.requests)
        if charge:
            snapshot.reserve(node, spec.requests)

    def fail_stale_instances(self, snapshot: ClusterSnapshot) -> list[str]:
        """Fail Available reservations whose NODE INSTANCE is gone — the
        node was removed (or removed and re-added under the same name;
        the fresh instance started clean and was never charged).  Their
        accounting died with the instance, so no remainder returns, and
        the FAILED phase makes return_allocation reject stale bind
        records (their pods then free their full vector).  Without this
        sweep a stale Available spec would project its reserved vector
        onto a fresh same-name node build_set resolves by NAME —
        oversubscribing it — and a deleted owner pod would leak its
        drawn amount into spec.allocated forever."""
        failed = []
        for spec in self._specs.values():
            if spec.phase is not ReservationPhase.AVAILABLE:
                continue
            if spec.node is None:
                continue
            if (spec.node not in snapshot.node_index
                    or snapshot.node_generation.get(spec.node, 0)
                    != spec.node_generation):
                spec.phase = ReservationPhase.FAILED
                failed.append(spec.name)
        return failed

    def expire_tick(self, now: float, snapshot: ClusterSnapshot) -> list[str]:
        """Expire reservations past their TTL: an Available one returns its
        unallocated remainder to node free capacity (controller/ expiration);
        a still-Pending one (reserve-pod never placed) simply expires —
        nothing was ever charged."""
        expired = []
        for spec in self._specs.values():
            if spec.ttl_sec is None:
                continue
            if (
                spec.phase is ReservationPhase.AVAILABLE
                and now - spec.available_at >= spec.ttl_sec
            ):
                spec.phase = ReservationPhase.EXPIRED
                self._return_remainder(spec, snapshot)
                expired.append(spec.name)
            elif (
                spec.phase is ReservationPhase.PENDING
                and now - spec.created_at >= spec.ttl_sec
            ):
                spec.phase = ReservationPhase.EXPIRED
                expired.append(spec.name)
        return expired

    def specs(self) -> list[ReservationSpec]:
        return list(self._specs.values())

    def pending(self) -> list[ReservationSpec]:
        return [
            s for s in self._specs.values()
            if s.phase is ReservationPhase.PENDING
        ]

    def return_allocation(self, name: str, drawn: np.ndarray,
                          generation: int = 0) -> bool:
        """An owner pod freed: give its drawn vector back to the reservation
        remainder.  Returns True when the SAME reservation instance still
        holds the node charge (caller then unreserves only the pod's spill);
        False when it is gone/consumed/re-created (caller frees the pod's
        full backing)."""
        spec = self._specs.get(name)
        if (
            spec is None
            or spec.allocated is None
            or spec.phase is not ReservationPhase.AVAILABLE
            or (generation and spec.generation != generation)
        ):
            return False
        spec.allocated = np.maximum(
            spec.allocated - drawn.astype(spec.allocated.dtype), 0
        )
        return True

    def _return_remainder(self, spec: ReservationSpec, snapshot: ClusterSnapshot) -> None:
        remainder = spec.requests - (
            spec.allocated if spec.allocated is not None else 0
        )
        # The node may have been deleted since the reservation became
        # Available (its accounting died with the row) or re-added under
        # the same name (the fresh instance started clean) — the
        # instance-checked release covers both.
        if spec.node is not None:
            snapshot.unreserve_instance(
                spec.node, np.maximum(remainder, 0), spec.node_generation)

    # -- device tensor builders ------------------------------------------------

    def available(self) -> list[ReservationSpec]:
        return [
            s for s in self._specs.values() if s.phase is ReservationPhase.AVAILABLE
        ]

    def build_set(
        self, snapshot: ClusterSnapshot, capacity: int | None = None
    ) -> tuple[ReservationSet, list[str]]:
        """(device set, row->name map) over Available reservations."""
        avail = self.available()
        names = [s.name for s in avail]
        if not avail:
            return ReservationSet.zeros(capacity or 16), names
        reserved = np.stack([s.requests for s in avail]).astype(np.int32)
        allocated = np.stack(
            [s.allocated if s.allocated is not None else np.zeros_like(s.requests)
             for s in avail]
        ).astype(np.int32)
        node_idx = np.array(
            # resolve by INSTANCE, not just name: a re-added same-name
            # node was never charged for this reservation (the
            # fail_stale_instances sweep normally catches these first;
            # this guards exotic call orders)
            [snapshot.node_index.get(s.node, -1)
             if s.node and snapshot.node_generation.get(s.node, 0)
             == s.node_generation else -1
             for s in avail],
            np.int32,
        )
        return (
            ReservationSet.build(
                reserved,
                node_idx,
                allocated=allocated,
                allocate_once=np.array([s.allocate_once for s in avail]),
                restricted=np.array([s.restricted for s in avail]),
                capacity=capacity,
            ),
            names,
        )

    def match_matrix(self, pods: list[PodSpec], pod_capacity: int,
                     rsv_capacity: int) -> np.ndarray:
        """(P, V) bool owner-match matrix for the Available set."""
        avail = self.available()
        out = np.zeros((pod_capacity, rsv_capacity), bool)
        for j, spec in enumerate(avail[:rsv_capacity]):
            for i, pod in enumerate(pods[:pod_capacity]):
                out[i, j] = any(m.matches(pod) for m in spec.owners)
        return out

    def commit_allocations(
        self,
        names: list[str],
        pods: list[PodSpec],
        assignments: np.ndarray,     # (P,) node rows
        rsv_choice: np.ndarray,      # (P,) reservation rows, -1 = none
    ) -> list[np.ndarray | None]:
        """Mirror the device-side allocation back into host specs (Reserve).

        Returns the per-pod vector drawn from its reservation (None for pods
        that didn't allocate through one) so bind records can return it when
        the pod is later freed."""
        drawn: list[np.ndarray | None] = [None] * len(pods)
        for i, pod in enumerate(pods):
            r = int(rsv_choice[i])
            if r < 0 or r >= len(names) or int(assignments[i]) < 0:
                continue
            spec = self._specs.get(names[r])
            if (
                spec is None
                or spec.allocated is None
                or spec.phase is not ReservationPhase.AVAILABLE
                or not np.any(spec.requests > spec.allocated)
            ):
                continue
            remainder = np.maximum(spec.requests - spec.allocated, 0)
            take = np.minimum(pod.requests.astype(np.int64), remainder)
            spec.allocated = spec.allocated + take.astype(spec.allocated.dtype)
            spec.owner_pods.append(pod.name)
            drawn[i] = take
            if spec.allocate_once:
                # the whole remainder is consumed on the pod's behalf; it
                # must free with the pod, not leak when the pod dies
                drawn[i] = remainder
                spec.allocated = spec.requests.copy()
                spec.phase = ReservationPhase.SUCCEEDED
        return drawn
