"""The scheduling loop: batched solve rounds over the pending queue.

Where the reference's scheduleOne loop (SURVEY.md section 3.1) takes one pod per
cycle through PreFilter->Filter->Score->Reserve->Permit->PreBind->Bind, this
scheduler drains the whole pending queue through one batched TPU solve per
round:

  round():
    PreEnqueue   gang readiness + backoff gating (host)
    BatchBuild   pad pods to a power-of-two bucket, host affinity masks
    Solve        gang_assign (filter+score+assign+quota+gang) on device
    Reserve      adopt the solver's node accounting, charge quotas
    Bind         callback per placed pod
    Diagnose     structured reasons for every unplaced pod

Gang Permit semantics map to solve-and-rollback (ops/gang.py); the WaitTime
state machine survives here: a gang that keeps failing past its wait_time is
rejected and its pods surface failures (coscheduling core/gang.go WaitTime).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.gang import GangInfo, gang_assign
from koordinator_tpu.ops.network_topology import (
    TopologyArrays,
    TopologyRequirements,
    plan_gang_placement,
)
from koordinator_tpu.quota.admission import QuotaDeviceState
from koordinator_tpu.quota.tree import QuotaTree
from koordinator_tpu.scheduler.diagnosis import PodDiagnosis, explain_pod
from koordinator_tpu.scheduler.monitor import SchedulerMonitor
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, PodSpec
from koordinator_tpu.state.cluster_state import PodBatch, _bucket


@dataclasses.dataclass
class GangRecord:
    """Host-side gang state (PodGroup + gang annotations)."""

    name: str
    min_member: int
    group: str | None = None
    wait_time_sec: float = 600.0
    first_failure: float | None = None
    rejected: bool = False
    #: network-topology gather requirements; needs Scheduler.topology_tree
    topology: TopologyRequirements | None = None


@dataclasses.dataclass
class SchedulingResult:
    assignments: dict[str, str]              # pod -> node
    failures: dict[str, PodDiagnosis]        # pod -> why
    round_pods: int = 0


class Scheduler:
    """Batched scheduler over a ClusterSnapshot."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        config: ScoringConfig | None = None,
        quota_tree: QuotaTree | None = None,
        bind_fn=None,
        monitor: SchedulerMonitor | None = None,
        gang_passes: int = 2,
        clock=time.monotonic,
        topology_tree: TopologyArrays | None = None,
        barrier=None,
        debug_service=None,
        hints=None,
    ):
        self.snapshot = snapshot
        self.config = config if config is not None else ScoringConfig.default()
        self.quota_tree = quota_tree
        self.bind_fn = bind_fn
        self.monitor = monitor or SchedulerMonitor()
        self.gang_passes = gang_passes
        self.clock = clock
        self.topology_tree = topology_tree

        #: startup sync barrier (barrier.SyncBarrier) — rounds no-op until
        #: the informer replays past it
        self.barrier = barrier
        #: debug service for top-N score dumps (services.DebugService)
        self.debug_service = debug_service
        #: scheduling hints (hints.SchedulingHints) — mask edits per pod
        self.hints = hints
        self.last_result = SchedulingResult({}, {}, 0)
        self.pending: dict[str, PodSpec] = {}
        self.gangs: dict[str, GangRecord] = {}
        self._solve = jax.jit(gang_assign, static_argnames=("passes",))

    # -- registration -------------------------------------------------------

    def register_gang(self, record: GangRecord) -> None:
        self.gangs[record.name] = record

    def enqueue(self, pod: PodSpec) -> None:
        self.pending[pod.name] = pod

    def dequeue(self, pod_name: str) -> None:
        self.pending.pop(pod_name, None)

    # -- the scheduling round ----------------------------------------------

    def _active_pods(self) -> list[PodSpec]:
        """PreEnqueue: skip pods of rejected gangs."""
        out = []
        for pod in self.pending.values():
            if pod.gang is not None:
                gang = self.gangs.get(pod.gang)
                if gang is not None and gang.rejected:
                    continue
            out.append(pod)
        out.sort(key=lambda p: (-p.priority, p.creation, p.name))
        return out

    def _build_batch(self, pods: list[PodSpec], gang_index: dict[str, int],
                     quota_index: dict[str, int]) -> PodBatch:
        p = len(pods)
        cap = _bucket(max(p, 1), minimum=16)
        n_cap = self.snapshot.capacity
        requests = np.zeros((p, self.snapshot.dims), np.int32)
        priority = np.zeros(p, np.int32)
        qos = np.zeros(p, np.int8)
        gang_id = np.full(p, -1, np.int32)
        quota_id = np.full(p, -1, np.int32)
        non_preempt = np.zeros(p, bool)
        feasible = np.zeros((p, n_cap), bool)
        for i, pod in enumerate(pods):
            requests[i] = pod.requests
            priority[i] = pod.priority
            qos[i] = pod.qos
            if pod.gang is not None and pod.gang in gang_index:
                gang_id[i] = gang_index[pod.gang]
            if pod.quota is not None and pod.quota in quota_index:
                quota_id[i] = quota_index[pod.quota]
            non_preempt[i] = pod.non_preemptible
            row = self.snapshot.feasibility_row(pod)
            if self.hints is not None:
                row = self.hints.apply_to_mask(pod.name, row)
            feasible[i] = row
        return PodBatch.build(
            requests, priority=priority, qos=qos, gang_id=gang_id,
            quota_id=quota_id, non_preemptible=non_preempt,
            feasible=feasible, node_capacity=n_cap, capacity=cap,
        )

    def _build_gang_info(self, pods: list[PodSpec]) -> tuple[GangInfo, dict[str, int]]:
        names = sorted({p.gang for p in pods if p.gang is not None})
        index = {n: i for i, n in enumerate(names)}
        groups: dict[str, int] = {}
        min_member = np.zeros(max(len(names), 1), np.int32)
        group_id = np.arange(max(len(names), 1), dtype=np.int32)
        for name, i in index.items():
            gang = self.gangs.get(name)
            min_member[i] = gang.min_member if gang else 0
            if gang and gang.group:
                group_id[i] = groups.setdefault(gang.group, i)
        return (
            GangInfo.build(min_member[: len(names)], group_id[: len(names)])
            if names else GangInfo.build(np.zeros(0, np.int32)),
            index,
        )

    def _build_quota(self) -> tuple[QuotaDeviceState | None, dict[str, int]]:
        if self.quota_tree is None:
            return None, {}
        # GroupQuotaManager duty: a leaf quota's request is what its pods ask
        # for — already-admitted usage plus this round's pending requests.
        pending: dict[str, np.ndarray] = {}
        for pod in self.pending.values():
            if pod.quota is not None and pod.quota in self.quota_tree.nodes:
                cur = pending.setdefault(
                    pod.quota, np.zeros(self.snapshot.dims, np.int64)
                )
                cur += pod.requests.astype(np.int64)
        for name, qnode in self.quota_tree.nodes.items():
            if self.quota_tree.children[name]:
                continue  # parents aggregate from children
            self.quota_tree.set_request(
                name, qnode.used + pending.get(
                    name, np.zeros(self.snapshot.dims, np.int64))
            )
        self.quota_tree.refresh_runtime()
        return QuotaDeviceState.from_tree(self.quota_tree)

    def _apply_topology_plans(
        self, batch: PodBatch, gang_index: dict[str, int]
    ) -> PodBatch:
        """FindOneNode parity (``frameworkext/interface.go:120``,
        ``coscheduling.go:137-144``): a gang with network-topology
        requirements gets a placement plan up front; each member's feasible
        set is pinned to its planned node. A gang whose plan fails is masked
        out of the round entirely (all-or-nothing at plan level)."""
        if self.topology_tree is None:
            return batch
        gang_ids = np.asarray(batch.gang_id)
        feasible = np.array(batch.feasible)
        valid = np.array(batch.valid)
        changed = False
        for name, gi in gang_index.items():
            gang = self.gangs.get(name)
            if gang is None or gang.topology is None:
                continue
            mask = (gang_ids == gi) & valid
            if not mask.any():
                continue
            plan = plan_gang_placement(
                self.snapshot.state, batch, mask, self.topology_tree,
                gang.topology, cfg=self.config,
            )
            changed = True
            desired = gang.topology.desired_slots or int(mask.sum())
            planned = np.flatnonzero(mask & (plan >= 0))
            if len(planned) < min(desired, int(mask.sum())):
                # no gather plan at all -> the whole gang backs off
                valid[mask] = False
                continue
            # pin planned members; surplus members (pending > desired_slots)
            # stay unpinned and schedule freely once the gang is permitted
            feasible[planned] = False
            feasible[planned, plan[planned]] = True
        if not changed:
            return batch
        return batch.replace(
            feasible=jnp.asarray(feasible), valid=jnp.asarray(valid)
        )

    def schedule_round(self) -> SchedulingResult:
        """Solve the current pending queue; reserve, bind, diagnose."""
        if self.barrier is not None and not self.barrier.check():
            # stale cache after restart: refuse to decide until the informer
            # replays past the barrier (sync_barrier.go semantics)
            return SchedulingResult({}, {}, 0)
        now = self.clock()
        with self.monitor.phase("PreEnqueue"):
            pods = self._active_pods()
        if not pods:
            self.last_result = SchedulingResult({}, {}, 0)
            return self.last_result

        with self.monitor.phase("BatchBuild"):
            self.snapshot.flush()
            gangs, gang_index = self._build_gang_info(pods)
            quota, quota_index = self._build_quota()
            batch = self._build_batch(pods, gang_index, quota_index)
            batch = self._apply_topology_plans(batch, gang_index)

        with self.monitor.phase("Solve"):
            assignments, new_state, new_quota = self._solve(
                self.snapshot.state, batch, self.config, gangs, quota,
                passes=self.gang_passes,
            )
            a = np.asarray(assignments)
        if (self.debug_service is not None
                and self.debug_service.dump_top_n_scores > 0):
            # debug-only extra solve: dump per-pod node scores
            from koordinator_tpu.ops.assignment import score_pods

            scores, _ = score_pods(self.snapshot.state, batch, self.config)
            self.debug_service.record_scores(
                pods, np.asarray(scores),
                [self.snapshot.node_name(r) or str(r)
                 for r in range(self.snapshot.state.capacity)],
            )

        result = SchedulingResult({}, {}, round_pods=len(pods))
        self.last_result = result  # debug-API diagnosis surface
        with self.monitor.phase("Reserve"):
            self.snapshot.adopt_state(new_state)

        with self.monitor.phase("Bind"):
            placed_gangs: set[str] = set()
            for i, pod in enumerate(pods):
                node_row = int(a[i])
                if node_row >= 0:
                    node = self.snapshot.node_name(node_row)
                    result.assignments[pod.name] = node
                    del self.pending[pod.name]
                    if pod.gang:
                        placed_gangs.add(pod.gang)
                    if (pod.quota and self.quota_tree is not None
                            and pod.quota in self.quota_tree.nodes):
                        q = self.quota_tree.nodes[pod.quota]
                        q.used = q.used + pod.requests.astype(np.int64)
                        if pod.non_preemptible:
                            q.non_preemptible_used = (
                                q.non_preemptible_used
                                + pod.requests.astype(np.int64)
                            )
                    if self.bind_fn is not None:
                        self.bind_fn(pod.name, node)

        with self.monitor.phase("Diagnose"):
            admitted = None
            if quota is not None:
                from koordinator_tpu.quota.admission import quota_admission_mask

                admitted = np.asarray(quota_admission_mask(
                    quota, batch.requests, batch.quota_id, batch.non_preemptible
                ))
            failed_gangs: set[str] = set()
            for i, pod in enumerate(pods):
                if int(a[i]) >= 0:
                    continue
                result.failures[pod.name] = explain_pod(
                    self.snapshot.state, batch, self.config, i,
                    quota_admitted=bool(admitted[i]) if admitted is not None else True,
                )
                if pod.gang:
                    failed_gangs.add(pod.gang)

            # gang WaitTime state machine (Permit timeout semantics)
            for name in failed_gangs - placed_gangs:
                gang = self.gangs.get(name)
                if gang is None:
                    continue
                if gang.first_failure is None:
                    gang.first_failure = now
                elif now - gang.first_failure > gang.wait_time_sec:
                    gang.rejected = True
            for name in placed_gangs:
                gang = self.gangs.get(name)
                if gang is not None:
                    gang.first_failure = None

        return result
