"""The scheduling loop: batched solve rounds over the pending queue.

Where the reference's scheduleOne loop (SURVEY.md section 3.1) takes one pod per
cycle through PreFilter->Filter->Score->Reserve->Permit->PreBind->Bind, this
scheduler drains the whole pending queue through one batched TPU solve per
round:

  round():
    PreEnqueue   gang readiness + backoff gating (host)
    BatchBuild   pad pods to a power-of-two bucket, host affinity masks
    Solve        gang_assign (filter+score+assign+quota+gang) on device
    Reserve      adopt the solver's node accounting, charge quotas
    Bind         callback per placed pod
    Diagnose     structured reasons for every unplaced pod

Gang Permit semantics map to solve-and-rollback (ops/gang.py); the WaitTime
state machine survives here: a gang that keeps failing past its wait_time is
rejected and its pods surface failures (coscheduling core/gang.go WaitTime).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu import journey, metrics, timeline, tracing
from koordinator_tpu.ops.assignment import ScoringConfig
from koordinator_tpu.ops.gang import GangInfo
from koordinator_tpu.ops.network_topology import (
    TopologyArrays,
    TopologyRequirements,
    plan_gang_placement,
)
from koordinator_tpu.quota.admission import QuotaDeviceState
from koordinator_tpu.quota.tree import QuotaTree
from koordinator_tpu.scheduler.diagnosis import PodDiagnosis, explain_pod
from koordinator_tpu.scheduler.monitor import SchedulerMonitor
from koordinator_tpu.scheduler.snapshot import ClusterSnapshot, PodSpec
from koordinator_tpu.state.cluster_state import PodBatch, _bucket

#: pending-queue key prefix for synthetic reserve-pods (the reference models
#: a Reservation as a pod the scheduler places; reservation_types.go)
RSV_POD_PREFIX = "rsv::"


@dataclasses.dataclass
class PdbRecord:
    """PodDisruptionBudget: selector + remaining disruption budget."""

    name: str
    selector: dict[str, str]
    allowed: int  # status.disruptionsAllowed

    def matches(self, labels: dict[str, str]) -> bool:
        # a PDB with an empty selector matches nothing; a pod with no labels
        # matches no PDB (filterPodsWithPDBViolation, preempt.go:224)
        if not self.selector or not labels:
            return False
        return all(labels.get(k) == v for k, v in self.selector.items())


@dataclasses.dataclass
class BoundPod:
    """Host record of a bound pod — the victim-candidate universe."""

    name: str
    node: str
    requests: np.ndarray
    priority: int = 0
    quota: str | None = None
    non_preemptible: bool = False
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    gang: str | None = None
    #: reservation this pod allocated from, and how much it drew — freeing
    #: the pod returns the drawn part to the reservation remainder (the node
    #: keeps the reservation's original charge), and unreserves only the
    #: spill that was charged to the node at bind time
    reservation: str | None = None
    rsv_drawn: np.ndarray | None = None
    rsv_generation: int = 0
    #: snapshot.node_generation at bind time: the node INSTANCE this
    #: pod's capacity was charged to — a release after the node was
    #: removed and re-added under the same name must not decrement the
    #: fresh instance (it starts clean; the churn suite drove
    #: node_requested negative before this stamp existed)
    node_generation: int = 0


@dataclasses.dataclass
class GangRecord:
    """Host-side gang state (PodGroup + gang annotations)."""

    name: str
    min_member: int
    group: str | None = None
    #: None = inherit the scheduler's default (CoschedulingArgs
    #: DefaultTimeout via the component config; 600s like the reference)
    wait_time_sec: float | None = None
    first_failure: float | None = None
    rejected: bool = False
    #: network-topology gather requirements; needs Scheduler.topology_tree
    topology: TopologyRequirements | None = None


@dataclasses.dataclass
class SchedulingResult:
    assignments: dict[str, str]              # pod -> node
    failures: dict[str, PodDiagnosis]        # pod -> why
    round_pods: int = 0
    #: PostFilter outcomes: preemptor pod -> (nominated node, victim names)
    nominations: dict[str, tuple[str, list[str]]] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class RoundHandle:
    """An in-flight round between its device and host halves (ISSUE 11).

    ``round_device`` returns one after DISPATCHING the solve; nothing in
    it has been blocked on.  ``assignments``/``new_state``/``new_quota``
    are in-flight device arrays — the dispatched solve DONATED the
    previous ``snapshot.state`` buffers and the snapshot was re-pointed
    at ``new_state`` before dispatch returned (the blessed swap), so the
    pre-dispatch buffers are dead and must never be stashed on a handle.
    The handle is only valid under the same ``scheduler.lock`` hold that
    produced it."""

    result: SchedulingResult
    #: the round finished entirely in the device half (elector/barrier
    #: gated, or an empty active queue) — round_host returns immediately
    done: bool = False
    now: float = 0.0
    pods: list = dataclasses.field(default_factory=list)
    batch: PodBatch | None = None
    gangs: GangInfo | None = None
    gang_index: dict = dataclasses.field(default_factory=dict)
    quota: object = None                 # post-prepass device quota
    solver: str = "greedy"
    assignments: object = None           # in-flight device array
    new_state: object = None             # in-flight donated-swap state
    new_quota: object = None
    #: incremental-path finish context (None = full/greedy path)
    inc: dict | None = None
    #: quality-path finish context (ISSUE 13): the LP solve's in-flight
    #: iteration count and pre-solve slack sums (None = not a quality
    #: round)
    quality: dict | None = None
    #: the forecast-headroom reserve charged into this round's solve
    #: (ISSUE 15; None = not a forecast round).  NOT donated — the host
    #: half's rescue pass re-charges the same tensor.
    forecast_reserve: object = None
    start_wall: float = 0.0
    t0: float = 0.0


class Scheduler:
    """Batched scheduler over a ClusterSnapshot."""

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        config: ScoringConfig | None = None,
        quota_tree: QuotaTree | None = None,
        bind_fn=None,
        bind_batch_fn=None,
        monitor: SchedulerMonitor | None = None,
        gang_passes: int = 2,
        gang_default_timeout_sec: float = 600.0,
        batch_solver_threshold: int = 1024,
        clock=time.monotonic,
        topology_tree: TopologyArrays | None = None,
        barrier=None,
        debug_service=None,
        hints=None,
        enable_preemption: bool | None = None,
        preempt_fn=None,
        explanations=None,
        auditor=None,
        cpu_manager=None,
        device_manager=None,
        elector=None,
        incremental_solve: bool = True,
        staleness_threshold_sec: float | None = None,
        staleness_exit_sec: float | None = None,
        trace_pods: bool = False,
        faults=None,
        explain: bool = True,
        flight_ring_size: int = 256,
        mesh="auto",
        shard_min_nodes: int = 1024,
        tenant: str = "",
        solver_kit=None,
        quality_mode: str = "off",
        quality_slack_threshold: float = 0.3,
        forecast_mode: str = "off",
    ):
        self.snapshot = snapshot
        self.config = config if config is not None else ScoringConfig.default()
        self.quota_tree = quota_tree
        self.bind_fn = bind_fn
        #: batched bind sink (ISSUE 19): when set, each round's whole
        #: bind set arrives as ONE call ([(pod, node), ...]) — the seam
        #: for a single deltasync emission per round instead of one
        #: frame per pod.  bind_fn (per-pod) still fires when only it is
        #: set; a round with both set calls bind_batch_fn only.
        self.bind_batch_fn = bind_batch_fn
        #: tenancy identity (ISSUE 11): when set, this scheduler is one
        #: tenant of a TenantScheduler — per-tenant labels ride every
        #: scheduler metric, flight records stamp the tenant, and the
        #: front-end back-reference serves /debug/tenants
        self.tenant = tenant
        #: TenantScheduler back-reference (set by tenancy.add_tenant) so
        #: a per-tenant debug surface can serve the shared rollup
        self.tenant_front = None
        self.monitor = monitor or SchedulerMonitor()
        if tenant:
            self.monitor.tenant = tenant
        self.gang_passes = gang_passes
        #: CoschedulingArgs.DefaultTimeout: WaitTime for gangs that don't
        #: set their own
        self.gang_default_timeout_sec = gang_default_timeout_sec
        #: queues at or above this size solve with the data-parallel
        #: propose/accept engine instead of the exact sequential scan
        #: (ops/gang.py solver param) — exact for interactive queue sizes,
        #: batch-parallel at scale
        self.batch_solver_threshold = batch_solver_threshold
        self.clock = clock
        self.topology_tree = topology_tree

        #: startup sync barrier (barrier.SyncBarrier) — rounds no-op until
        #: the informer replays past it
        self.barrier = barrier
        #: debug service for top-N score dumps (services.DebugService)
        self.debug_service = debug_service
        #: scheduling hints (hints.SchedulingHints) — mask edits per pod
        self.hints = hints
        #: explanation.ExplanationStore — failures persist as
        #: ScheduleExplanation CRs (schedule_diagnosis.go DumpDiagnosis)
        self.explanations = explanations
        #: explanation.WorkloadAuditor — per-pod/gang lifecycle records
        self.auditor = auditor
        self.last_result = SchedulingResult({}, {}, 0)
        #: which solve engine the last round used ("greedy"/"batch")
        self.last_solver = "greedy"
        #: serializes rounds against informer-driven mutations — the
        #: transport layer applies watch pushes from a reader thread while
        #: solve RPCs run rounds (the reference relies on the upstream
        #: single-scheduling-goroutine + informer snapshot model)
        self.lock = threading.RLock()
        self.pending: dict[str, PodSpec] = {}
        self.gangs: dict[str, GangRecord] = {}
        # PodBatch cache: repeated rounds over an unchanged pending queue
        # (pods parked on gangs/quota, failing pods awaiting capacity) reuse
        # the previous device batch instead of rebuilding host-side
        self._pending_rev = 0
        self._batch_cache: tuple[tuple, PodBatch] | None = None
        self.batch_rebuilds = 0
        #: host-side arrays of the last batch build, for row-level reuse
        #: when the queue changes incrementally (see _build_batch)
        self._batch_host: dict | None = None
        # -- the shared solver kit (ISSUE 11) --
        # every jitted entry point lives in a SolverKit (solve mesh
        # included): a standalone scheduler builds its own, a tenant of
        # a TenantScheduler is handed the front-end's shared kit so T
        # tenants multiplex onto ONE compiled solver (one jit cache, one
        # recompile ledger) instead of compiling T copies.
        from koordinator_tpu.scheduler.solver_kit import SolverKit

        self.kit = (solver_kit if solver_kit is not None
                    else SolverKit(mesh=mesh,
                                   shard_min_nodes=shard_min_nodes))
        self.mesh = self.kit.mesh
        self.shard_min_nodes = self.kit.shard_min_nodes
        self.solver_shard_count = self.kit.shards
        if self.mesh is not None:
            self.snapshot.set_solver_sharding(
                self.kit.node_sharding, self.solver_shard_count,
                min_nodes=self.shard_min_nodes)
        self._solve = self.kit.solve
        #: explicit shard_map gang/greedy twin (ISSUE 14): engaged for
        #: factored-feasibility batches whenever the mesh is active and
        #: both capacities divide over their axes; hinted (dense-mask)
        #: rounds keep the GSPMD-placed entry
        self._solve_sh = self.kit.solve_sh

        # -- incremental delta-driven solve (no-gang batch rounds) --
        #: steady-state rounds refresh a device-resident (P, k) candidate
        #: cache against the dirty-node/pod delta instead of re-selecting
        #: over the whole (P, N) problem; falls back to the full pass when
        #: the dirty fraction crosses incremental_dirty_threshold
        self.incremental_solve = incremental_solve
        self.incremental_dirty_threshold = 0.25
        #: candidate-selection knobs — MUST mirror batch_assign's defaults
        #: (gang_assign's full path uses them), or the incremental and
        #: full rounds would solve different problems
        self.cand_k = 32
        self.cand_spread = (5, 15)
        self.cand_method = "auto"
        self.solve_rounds = 12
        self._cand_cache: dict | None = None
        #: which candidate path the last batch round took
        #: (incremental | full_cold | full_fallback | full_gang |
        #: full_dense | disabled)
        self.last_solve_path = "none"
        #: stable per-pod-name rotation ids (PodBatch.rot_id): a pod keeps
        #: its candidate tie-break rotation when the queue shifts around it
        self._rot_ids: dict[str, int] = {}
        self._rot_counter = 0
        self._select_scored = self.kit.select_scored
        self._align_cands = self.kit.align_cands
        self._refresh_cands = self.kit.refresh_cands
        self._scatter_cands = self.kit.scatter_cands
        self._pass1 = self.kit.pass1
        self._pass2 = self.kit.pass2
        self._select_scored_sh = self.kit.select_scored_sh
        self._refresh_cands_sh = self.kit.refresh_cands_sh
        self._pass1_sh = self.kit.pass1_sh
        self._pass2_sh = self.kit.pass2_sh

        # -- solve-quality mode (ISSUE 13) --
        #: "off" = today's greedy path exactly; "lp" = every eligible
        #: round solves with the LP-relaxation packing engine
        #: (quality/lp_pack); "auto" = escalate only rounds whose
        #: preceding result leaves capacity_slack_fraction above the
        #: threshold (free capacity is the win-back opportunity
        #: constraint-based packing exists for)
        from koordinator_tpu.quality import QUALITY_MODES

        if quality_mode not in QUALITY_MODES:
            raise ValueError(f"unknown quality_mode {quality_mode!r}; "
                             f"one of {QUALITY_MODES}")
        self.quality_mode = quality_mode
        self.quality_slack_threshold = quality_slack_threshold
        self._quality_solve = self.kit.quality_solve
        self._quality_solve_sh = self.kit.quality_solve_sh
        #: auto-mode escalation latch, recomputed from every round's
        #: resulting per-dim slack (MIN over provisioned dims vs the
        #: threshold: every dimension must have headroom worth winning
        #: back — see _quality_round_finish)
        self._quality_escalate = False
        self._last_quality_iters = 0
        metrics.solver_quality_mode.set(
            float(QUALITY_MODES.index(quality_mode)),
            labels=self._tl())

        # -- forecast plane (ISSUE 15) --
        #: "off" = today's solve exactly (the forecast entries are never
        #: called — bit-identical acceptance decisions and quota
        #: charges); "admit" = the forecast-headroom reserve charges
        #: into every eligible round's filter/score accounting; "full" =
        #: admission plus the colocation/rebalance drivers armed at
        #: assembly.  The plane itself attaches separately
        #: (attach_forecast_plane) — a mode without a plane is inert.
        from koordinator_tpu.forecast import FORECAST_MODES

        if forecast_mode not in FORECAST_MODES:
            raise ValueError(f"unknown forecast_mode {forecast_mode!r}; "
                             f"one of {FORECAST_MODES}")
        self.forecast_mode = forecast_mode
        self.forecast_plane = None
        self._forecast_solve = self.kit.forecast_solve
        self._forecast_solve_sh = self.kit.forecast_solve_sh
        #: per-round admission cap (tenancy weighted-fair admission sets
        #: it per cycle; None = admit the whole active queue).  Applied
        #: in priority order AFTER the PreEnqueue gates, so a capped
        #: round still schedules the most important pods first.
        self.round_pod_limit: int | None = None
        #: pods held back by the cap in the last round (fairness surface)
        self.last_overflow = 0
        #: PodBatch capacity floor: the tenant-axis batched solve stacks
        #: several tenants' batches on a leading axis, which needs every
        #: tenant padded to the SAME pod bucket
        self.batch_capacity_floor = 0
        #: reservation lifecycle (plugins/reservation parity): reserve-pods
        #: schedule through the normal rounds, Available sets get a
        #: reservation-first exact solve pre-pass
        from koordinator_tpu.scheduler.reservations import ReservationCache

        self.reservations = ReservationCache()
        self._rsv_solve = self.kit.rsv_solve
        #: fine-grained allocators (nodenumaresource / deviceshare Reserve):
        #: LSR/LSE pods take exclusive cpusets, device requests take minors
        #: at bind; annotation payloads surface in resource_status
        self.cpu_manager = cpu_manager
        self.device_manager = device_manager
        #: per-node vendor device-plugin lock annotations (the node-object
        #: annotation in the reference; vendors' plugins clear it via
        #: clear_device_node_lock when they finish a pod)
        self._device_node_locks: dict[str, dict[str, str]] = {}
        self.resource_status: dict[str, dict] = {}
        #: quota overuse revoke controller (enable_overuse_revoke)
        self.overuse_revoke = None
        #: ha.LeaderElector — rounds no-op while not leading (the reference
        #: leader-elects the whole scheduling loop, server.go)
        self.elector = elector
        #: bound on pods routed through the sequential reservation pre-pass
        #: per round — a popular owner selector must not drag a 50k-pod
        #: round onto the O(P) exact scan (extras solve normally and can
        #: draw reservations next round)
        self.rsv_prepass_cap = 2048
        self._rsv_match_cache: tuple[tuple, np.ndarray] | None = None

        # -- preemption (PostFilter) state --
        # default: only preempt when someone is wired to actually evict the
        # victim (otherwise the scheduler would free accounting for pods that
        # keep running, double-booking nodes)
        self.enable_preemption = (
            enable_preemption if enable_preemption is not None
            else preempt_fn is not None
        )
        #: called as preempt_fn(victim_name, preemptor_name) on each eviction
        self.preempt_fn = preempt_fn
        self.bound: dict[str, BoundPod] = {}
        self.pdbs: dict[str, PdbRecord] = {}
        #: preemptor pod -> nominated node name (nominatedNodeName semantics)
        self.nominations: dict[str, str] = {}
        #: node INSTANCE each nomination's charge was assumed against
        #: (snapshot.node_generation at assume time)
        self._nomination_gen: dict[str, int] = {}
        self._preempt = self.kit.preempt
        self._preempt_chain = self.kit.preempt_chain
        #: bound on PostFilter work per round (mirror of rsv_prepass_cap):
        #: at most this many failed pods attempt preemption in one round —
        #: a quota-starved 50k queue must not turn PostFilter into 50k
        #: device calls (upstream bounds the preemption cycle's work the
        #: same way, coscheduling preemption.go:206).  Excess pods stay
        #: pending and retry next round.
        self.preempt_cap = 1024
        #: single-pod preemptors are chained in jitted scans of this size
        #: (one dispatch per chunk, not per pod); gangs use the host loop
        self.preempt_chunk = 256

        # -- snapshot-staleness watchdog / degraded mode --
        #: seconds the sync feed may be silent before rounds flip into
        #: degraded mode; None disables the watchdog.  Constraint-based
        #: packing only keeps its guarantees against fresh-or-conservative
        #: state: a stalled delta feed means usage/allocatable (and the
        #: manager-derived batch capacity riding them) are arbitrarily
        #: stale, so degraded rounds (a) suspend BE/batch-dim admission —
        #: the consumers of the stale-derived overcommit capacity — and
        #: (b) drop the incremental candidate cache and solve full-pass
        #: until the feed re-warms.
        self.staleness_threshold_sec = staleness_threshold_sec
        #: hysteresis: exit degraded only once the feed age is back under
        #: this (default threshold/2) so a feed trickling right at the
        #: threshold doesn't flap admission on and off
        self.staleness_exit_sec = staleness_exit_sec
        self.degraded = False
        self.degraded_since: float | None = None
        self.degraded_entries = 0
        #: pods held out of the last round by degraded-mode suspension
        self.last_suspended = 0

        # -- tracing + round flight recorder --
        from koordinator_tpu.scheduler.flight_recorder import FlightRecorder

        #: trace EVERY enqueued pod (a root span per pod) even without a
        #: propagated context.  Off by default: per-pod spans are O(P)
        #: host work per round, and untraced operation should pay one
        #: round span, not 50k — a caller-propagated TraceContext (the
        #: wire path) always traces its pod regardless of this flag.
        self.trace_pods = trace_pods
        #: live trace context per pending pod (the enqueue span); popped
        #: when the pod binds (the bind span parents to it) or leaves
        self.pod_traces: dict[str, tracing.TraceContext] = {}
        #: bounded pod-name -> trace_id registry surviving bind, for
        #: /debug/trace/<pod> lookups
        self._pod_trace_ids: dict[str, str] = {}
        self.round_seq = 0
        #: ring capacity is a knob (--flight-ring-size): a long soak's
        #: report joins verdicts to rounds, so the ring must hold enough
        #: rounds to cover the report's window — size it so
        #: round_flight_overwritten_total stays near zero over the run
        self.flight_recorder = FlightRecorder(
            capacity=flight_ring_size,
            slow_threshold_s=self.monitor.timeout_sec)
        #: device-side share of the round's solve (time blocked on
        #: jitted results), accumulated across solve dispatches
        self._solve_device_s = 0.0
        #: dispatch-half wall carried into the host half's single
        #: "Solve" phase observation (pipelined round split, ISSUE 11)
        self._solve_carry_s = 0.0
        self._last_dirty_node_frac = 0.0
        self._last_dirty_pod_frac = 0.0
        self._last_staleness_s: float | None = None
        self._round_recordable = False
        #: journey-ledger solve-dispatch edge; None outside a round so
        #: out-of-round binds fall back to their own commit stamp
        self._journey_round_t0: float | None = None

        # -- placement explainability (ISSUE 6) --
        from koordinator_tpu.scheduler.explanation import ExplanationRing

        #: kill switch (--no-explain): when False the Diagnose phase
        #: falls back to the per-pod host recompute, no explanations are
        #: retained, and the unschedulability rollups stay silent
        self.explain = explain
        self._explain_counts = self.kit.explain_counts
        self._slack_sums = self.kit.slack_sums
        #: bounded pod-keyed retention behind /debug/explain/<pod>
        self.explain_ring = ExplanationRing()
        #: {top reason -> pod count} rollup of the last round (flight
        #: recorder + unschedulable_pods gauge source)
        self._last_unschedulable_top: dict[str, int] = {}
        #: pods _active_pods held out this round, for explanation
        #: recording (suspension / rejected gangs happen before Diagnose
        #: ever sees the pod)
        self._last_suspended_names: list[str] = []
        self._last_gang_rejected_names: list[str] = []

        # -- self-observability (ISSUE 5) --
        #: chaos-harness fault injector (transport.faults.FaultInjector);
        #: the Solve phase consults on_solve() when attached — None (the
        #: default) costs one attribute check per round
        self.faults = faults
        #: SloMonitor attached by the binary assembly (serves /debug/slo
        #: and fires flight-recorder dumps on fast-burn breaches)
        self.slo_monitor = None
        #: trend.TrendEngine attached by the binary assembly (serves
        #: /debug/steady: steady/drifting/leaking verdicts over the
        #: self-telemetry and queue-depth series); None => typed 501
        self.trend_engine = None
        #: introspection.ProfilerCapture behind /debug/profile; None =
        #: the endpoint answers 403 (gated off by default)
        self.profile_capture = None

    def stop(self) -> None:
        """Assembly-level teardown (Assembled.stop): stops the attached
        SLO sampler thread when one is running."""
        if self.slo_monitor is not None:
            self.slo_monitor.stop()

    def attach_forecast_plane(self, plane) -> None:
        """Install the forecast plane (forecast/plane.ForecastPlane):
        grown to the snapshot's capacity and pinned under the solver
        mesh's node sharding when one is active, so the admission
        reserve and the charged solve never reshard.  The round prelude
        feeds it (observe + cadenced refresh) whenever
        ``forecast_mode != "off"``."""
        with self.lock:
            if plane.capacity < self.snapshot.capacity:
                plane.grow(self.snapshot.capacity)
            if self.mesh is not None and self.snapshot.solver_sharding_active:
                plane.set_sharding(self.kit.node_sharding)
            plane.metric_labels = dict(self._tl() or {})
            self.forecast_plane = plane

    def _forecast_reserve(self):  # koordlint: guarded-by(self.lock)
        """The round's (N, R) forecast-headroom reserve, or None when
        forecasting is off / the plane is absent or not yet refreshed —
        the predicate every forecast branch keys on, so ``off`` never
        touches a forecast entry."""
        if self.forecast_mode == "off" or self.forecast_plane is None:
            return None
        return self.forecast_plane.admission_reserve(self.snapshot.state)

    # -- registration -------------------------------------------------------

    def register_gang(self, record: GangRecord) -> None:
        with self.lock:
            if record.wait_time_sec is None:
                record.wait_time_sec = self.gang_default_timeout_sec
            self.gangs[record.name] = record

    def register_pdb(self, record: PdbRecord) -> None:
        with self.lock:
            self.pdbs[record.name] = record

    def add_bound_pod(self, pod: BoundPod,
                      resource_status: dict | None = None) -> None:
        """Seed a pre-existing bound pod (informer replay at startup).

        Owns the accounting: the pod's request is reserved on its node here,
        and released by :meth:`remove_bound_pod` — callers never touch the
        snapshot directly, so a pod the scheduler already evicted (popped
        from ``bound``) cannot be double-freed by a late informer delete.

        ``resource_status`` replays the pod's fine-grained annotations
        ({"resource-status": {"cpuset": "0,1"}, "device-allocated": {...}})
        into the CPU/device managers so restart can't re-grant pinned cores
        or in-use device minors to new pods."""
        with self.lock:
            self.bound[pod.name] = pod
            if pod.node in self.snapshot.node_index:
                self.snapshot.reserve(pod.node, pod.requests)
            if resource_status:
                self._restore_fine_grained(pod, resource_status)

    def _restore_fine_grained(self, pod: BoundPod, status: dict) -> None:
        """Annotations are persisted external data: a malformed or stale
        payload (topology changed across restart) skips that pod's restore
        instead of crashing the informer replay."""
        rs = status.get("resource-status") or {}
        cpuset = rs.get("cpuset", "") if isinstance(rs, dict) else ""
        if cpuset and self.cpu_manager is not None:
            from koordinator_tpu.scheduler.cpu_manager import (
                EXCLUSIVE_PCPU_LEVEL,
                parse_cpuset_bounded,
            )

            try:
                cpus = parse_cpuset_bounded(str(cpuset))
            except ValueError:
                cpus = []
            if cpus and self.cpu_manager.restore(
                    pod.node, pod.name, cpus,
                    exclusive_policy=EXCLUSIVE_PCPU_LEVEL):
                self.resource_status.setdefault(pod.name, {})[
                    "resource-status"] = rs
        devices = status.get("device-allocated") or {}
        if devices and self.device_manager is not None:
            if self.device_manager.restore(pod.node, pod.name, devices):
                # serve the RE-DERIVED truth, not the raw payload: a
                # partially-restored annotation (unknown types, stale
                # minors) must not be reported as tracked
                self.resource_status.setdefault(pod.name, {})[
                    "device-allocated"] = (
                        self.device_manager.device_allocated_annotation(
                            pod.node, pod.name))

    def remove_bound_pod(self, name: str) -> None:
        """Release a bound pod's node reservation iff still tracked (quota
        stays with the caller: eviction paths release it themselves).

        A pod that allocated through a reservation gives its drawn vector
        back to the reservation remainder (the reserved capacity stays
        charged to the node, hidden from non-owners) and frees only its
        spill; once the reservation is gone/consumed, the drawn backing
        charge frees with the pod."""
        with self.lock:
            pod = self.bound.pop(name, None)
            if pod is not None:
                self._release_bound_capacity(pod)

    def _release_bound_capacity(self, bp: BoundPod) -> None:
        """Shared freeing for a bound pod leaving the cluster (informer
        delete, eviction, preemption): fine-grained allocations, then the
        reservation-aware node unreserve."""
        self._release_fine_grained(bp.name, bp.node)
        if bp.node not in self.snapshot.node_index:
            return
        if (self.snapshot.node_generation.get(bp.node, 0)
                != bp.node_generation):
            # the node this pod was charged to is GONE; the same name now
            # labels a fresh instance that started clean — decrementing
            # it would drive node_requested negative (the reservation
            # drawn/spill split below also died with the old instance)
            return
        free_vec = bp.requests
        if bp.reservation is not None and bp.rsv_drawn is not None:
            drawn = bp.rsv_drawn.astype(np.int64)
            if self.reservations.return_allocation(
                    bp.reservation, drawn, bp.rsv_generation):
                free_vec = np.maximum(
                    bp.requests.astype(np.int64) - drawn, 0)
            else:
                free_vec = np.maximum(
                    bp.requests.astype(np.int64), drawn)
        self.snapshot.unreserve(bp.node, free_vec.astype(np.int32))

    def delete_pod(self, name: str) -> None:
        """Informer pod delete, whatever state the pod is in: a pending or
        nominated pod is dequeued; a bound pod releases BOTH its node
        reservation and its quota charge (the _commit_bind mirror)."""
        with self.lock:
            if name in self.pending or name in self.nominations:
                self.dequeue(name)
            bound = self.bound.get(name)
            if bound is not None:
                self.remove_bound_pod(name)
                self._charge_quota_used(bound, sign=-1)

    def enable_overuse_revoke(self, revoke_fn,
                              delay_evict_sec: float = 5.0) -> None:
        """Turn on the elastic-quota overuse revoke loop
        (quota_overuse_revoke.go): each round, quotas whose used exceeds
        runtime continuously past the delay get their least-important pods
        revoked until they fit.  ``revoke_fn(pod, quota)`` is REQUIRED —
        it performs the external eviction; the scheduler's own accounting
        releases here, and freeing capacity no one actually evicts would
        oversubscribe the node."""
        from koordinator_tpu.quota.overuse_revoke import (
            QuotaOveruseRevokeController,
        )

        self.overuse_revoke = QuotaOveruseRevokeController(
            self, revoke_fn=revoke_fn, delay_evict_sec=delay_evict_sec,
            clock=self.clock)

    def add_reservation(self, spec) -> None:
        """Accept a Reservation CR: placement happens next round (a pinned
        node goes Available directly; otherwise a synthetic reserve-pod
        schedules through the normal solve).

        Re-applying an existing name is an update: if the placed charge is
        unchanged (same requests, same pin) only the mutable spec fields
        move; otherwise the old reservation is removed first (returning its
        remainder) so the new one can't double-charge the node."""
        from koordinator_tpu.scheduler.reservations import ReservationPhase

        with self.lock:
            spec.created_at = self.clock()
            old = self.reservations.get(spec.name)
            if old is not None and old.phase in (
                ReservationPhase.AVAILABLE, ReservationPhase.SUCCEEDED
            ):
                if (np.array_equal(old.requests, spec.requests)
                        and spec.node in (None, old.node)):
                    old.owners = spec.owners
                    old.ttl_sec = spec.ttl_sec
                    old.restricted = spec.restricted
                    # owner edits change who matches: drop the cached
                    # owner-match matrix (generation stays — bind records
                    # against this instance remain valid)
                    self._rsv_match_cache = None
                    return
                self.remove_reservation(spec.name)
            self.reservations.upsert(spec)
            # a still-queued reserve-pod carries the OLD requests vector;
            # drop it so the next tick re-enqueues the updated one
            if self.pending.pop(RSV_POD_PREFIX + spec.name, None) is not None:
                self._pending_rev += 1

    def remove_reservation(self, name: str) -> None:
        """Reservation CR deleted: return the unallocated remainder and drop
        any in-flight reserve-pod."""
        with self.lock:
            self.reservations.remove(name, self.snapshot)
            if self.pending.pop(RSV_POD_PREFIX + name, None) is not None:
                self._pending_rev += 1

    def _reservation_tick(self, now: float) -> None:  # koordlint: guarded-by(self.lock)
        """Expire reservations; move Pending ones toward Available (pinned
        node: direct, with a fit check; else enqueue a reserve-pod)."""
        for name in self.reservations.fail_stale_instances(self.snapshot):
            if self.auditor is not None:
                self.auditor.record(name, "ReservationFailed",
                                    "node instance gone")
        for name in self.reservations.expire_tick(now, self.snapshot):
            # a Pending reservation that expired drops its reserve-pod too
            if self.pending.pop(RSV_POD_PREFIX + name, None) is not None:
                self._pending_rev += 1
            if self.auditor is not None:
                self.auditor.record(name, "ReservationExpired", "")
        # terminal specs are settled accounting-wise; purge so long-running
        # schedulers don't pay an ever-growing Reservations tick
        self.reservations.gc()
        for spec in self.reservations.pending():
            if spec.node is not None:
                # pre-pinned: goes Available only if it actually fits —
                # make_available charges the node, and an over-committed
                # charge would block the node for everyone (the un-pinned
                # path gets this fit check from the reserve-pod solve)
                row = self.snapshot.node_index.get(spec.node)
                if row is None:
                    continue
                free = (
                    np.asarray(self.snapshot.state.node_allocatable[row])
                    - np.asarray(self.snapshot.state.node_requested[row])
                )
                if np.all(spec.requests <= free):
                    self.reservations.make_available(
                        spec.name, spec.node, self.snapshot, now)
                continue
            key = RSV_POD_PREFIX + spec.name
            if key not in self.pending:
                self.pending[key] = PodSpec(
                    name=key, requests=spec.requests.astype(np.int32),
                    priority=9000, node_selector=dict(spec.node_selector),
                    tolerations=dict(spec.tolerations))
                self._pending_rev += 1

    def _reservation_prepass(self, pods, batch, quota, result):  # koordlint: guarded-by(self.lock)
        """Reservation-first exact solve over owner-matched pods (plugin.go
        Reserve + nominator semantics): matched pods allocate from their
        reservation's remainder before the general solve sees them.  Returns
        the (possibly shrunk) batch and quota."""
        avail = self.reservations.available()
        if not avail:
            return batch, quota
        # fully-consumed reservations have nothing to lend — skip the
        # whole pre-pass (and its O(P) host-side owner matching)
        if not any(np.any(s.requests > s.allocated) for s in avail
                   if s.allocated is not None):
            return batch, quota
        rsv_set, names = self.reservations.build_set(self.snapshot)
        # the O(pods x reservations) python owner matching is cached
        # between rounds over an unchanged queue + reservation set (the
        # PodBatch cache analog): steady-state rounds pay a dict lookup
        # the key must cover everything the matrix depends on: the active
        # pod ROW ORDER (gang rejection shrinks _active_pods without
        # bumping _pending_rev), and reservation identity/owners (owner
        # edits clear the cache in add_reservation)
        mkey = (self._pending_rev,
                tuple(p.name for p in pods),
                tuple(s.generation for s in avail))
        cached = self._rsv_match_cache
        if cached is not None and cached[0] == mkey:
            match = cached[1]          # read-only below: no defensive copy
        else:
            match = self.reservations.match_matrix(
                pods, batch.capacity, rsv_set.capacity)
            # reserve-pods can't consume reservations; gang members keep
            # all-or-nothing semantics in the main solve
            for i, pod in enumerate(pods):
                if pod.name.startswith(RSV_POD_PREFIX) or pod.gang:
                    match[i] = False
            self._rsv_match_cache = (mkey, match)
        matched = np.asarray(batch.valid) & match.any(axis=1)
        if not matched.any():
            return batch, quota
        if int(matched.sum()) > self.rsv_prepass_cap:
            prio = np.asarray(batch.priority)
            rows = np.flatnonzero(matched)
            keep = rows[np.argsort(-prio[rows], kind="stable")
                        [: self.rsv_prepass_cap]]
            matched = np.zeros_like(matched)
            matched[keep] = True
        small, idx = batch.compact(matched)
        m_small = np.zeros((small.capacity, rsv_set.capacity), bool)
        m_small[: len(idx)] = match[idx]
        a_r, rc, new_state, _, new_quota = self._rsv_solve(
            self.snapshot.state, small, self.config, rsv_set,
            jnp.asarray(m_small), quota)
        a_r, rc = np.asarray(a_r), np.asarray(rc)
        self.snapshot.adopt_state(new_state,
                                  changed_rows=np.unique(a_r[a_r >= 0]))
        sub_pods = [pods[i] for i in idx]
        drawn = self.reservations.commit_allocations(names, sub_pods, a_r, rc)
        bound_rows = [int(idx[j]) for j in range(len(sub_pods))
                      if int(a_r[j]) >= 0]
        for j, pod in enumerate(sub_pods):
            if int(a_r[j]) >= 0:
                r = int(rc[j])
                rname = (names[r] if 0 <= r < len(names)
                         and drawn[j] is not None else None)
                rspec = (self.reservations.get(rname)
                         if rname is not None else None)
                self._commit_bind(
                    pod, self.snapshot.node_name(int(a_r[j])), result,
                    reservation=rname, rsv_drawn=drawn[j],
                    rsv_generation=(rspec.generation if rspec else 0))
        if bound_rows:
            mask = np.zeros(batch.capacity, bool)
            mask[bound_rows] = True
            batch = batch.replace(valid=batch.valid & ~jnp.asarray(mask))
        return batch, (new_quota if new_quota is not None else quota)

    # koordlint: guarded-by(self.lock)
    def _commit_reserve_pod(self, pod: PodSpec, node: str,
                            result: SchedulingResult, now: float) -> None:
        """The reserve-pod 'bound': its Reservation becomes Available.  The
        solve already charged the reserved vector to node_requested, so no
        further snapshot accounting (make_available charges only on the
        pinned-node path, which bypasses the solve)."""
        from koordinator_tpu.scheduler.reservations import ReservationPhase

        rname = pod.name[len(RSV_POD_PREFIX):]
        if self.pending.pop(pod.name, None) is not None:
            self._pending_rev += 1
        spec = self.reservations.get(rname)
        if spec is None:
            # CR deleted mid-round: release the solve's charge
            self.snapshot.unreserve(node, pod.requests)
            return
        # the solve already charged the reserved vector: open without
        # re-charging (the shared transition keeps both paths identical)
        self.reservations.make_available(
            rname, node, self.snapshot, now=now, charge=False)
        result.assignments[pod.name] = node
        if self.explanations is not None:
            self.explanations.delete(pod.name)
        if self.auditor is not None:
            self.auditor.record(pod.name, "ReservationAvailable", node)

    def enqueue(self, pod: PodSpec) -> None:
        with self.lock:
            self._enqueue_locked(pod)

    def enqueue_many(self, pods: list[PodSpec]) -> None:
        """Admit a batch under ONE lock acquisition (ISSUE 19): the
        deltasync binding routes contiguous pod_add runs here so a
        loadgen burst costs one lock round-trip, not one per pod.
        Per-pod semantics (arrival accounting, trace roots, pending
        revision bumps) are exactly the sequential loop's."""
        if not pods:
            return
        with self.lock:
            for pod in pods:
                self._enqueue_locked(pod)

    def _enqueue_locked(self, pod: PodSpec) -> None:  # koordlint: guarded-by(self.lock)
        # arrival-process accounting (ISSUE 9): rate() of this is
        # the admission rate the churn load generator drives.  Only
        # NEW names count — a resync bootstrap replays pod_add for
        # every still-pending pod, and re-counting the whole queue
        # would paint a phantom arrival spike on the dashboards
        if pod.name not in self.pending:
            metrics.pods_enqueued_total.inc(labels=self._tl())
            # journey-ledger enqueue stamp (ISSUE 20): first enqueue only
            # — a resync replay must not reset the pod's queue-wait clock
            if journey.LEDGER.enabled:
                journey.LEDGER.note_enqueue(
                    pod.name, getattr(pod, "arrival_ts", 0.0))
        self.pending[pod.name] = pod
        self._pending_rev += 1
        # the pod's trace starts (or joins) here: a propagated
        # context (wire push applying under tracing.activate) always
        # traces; trace_pods opts untraced pods into root spans.
        # Synthetic reserve-pods are placement vehicles, not user
        # workloads — they stay untraced like they stay unaudited.
        ctx = tracing.current_context()
        if ((ctx is not None or self.trace_pods)
                and not pod.name.startswith(RSV_POD_PREFIX)):
            sp = tracing.TRACER.start_span(
                "scheduler.enqueue", service="scheduler", parent=ctx,
                attributes={"pod": pod.name,
                            "priority": int(pod.priority)})
            sp.end()
            self.pod_traces[pod.name] = sp.context()
            self._register_pod_trace(pod.name, sp.trace_id)

    def _register_pod_trace(self, name: str, trace_id: str) -> None:
        """Bounded name -> trace_id map for /debug/trace/<pod>: survives
        bind (the interesting queries are about bound pods), trimmed
        oldest-first so a years-long scheduler doesn't leak."""
        ids = self._pod_trace_ids
        ids.pop(name, None)          # re-enqueue refreshes recency
        ids[name] = trace_id
        if len(ids) > 8192:
            for key in list(ids)[: len(ids) // 2]:
                del ids[key]

    def pod_trace_id(self, name: str) -> str | None:
        """Most recent trace_id recorded for a pod (debug surface)."""
        return self._pod_trace_ids.get(name)

    def dequeue(self, pod_name: str) -> None:
        # a deleted nominated preemptor must release its assumed reservation
        # and quota charge, and must not pin a future same-named pod
        with self.lock:
            pod = self.pending.pop(pod_name, None)
            self.pod_traces.pop(pod_name, None)
            if pod is not None:
                self._pending_rev += 1
                journey.LEDGER.forget(pod_name)
            if pod_name in self.nominations and pod is not None:
                self._nomination_release(pod)
            else:
                self.nominations.pop(pod_name, None)
                self._nomination_gen.pop(pod_name, None)

    # -- snapshot-staleness watchdog ----------------------------------------

    def note_sync_event(self) -> None:
        """An informer/sync event was applied: the state feed is alive.
        Called by the deltasync dispatch layer (remote watch client and
        in-process binding drain alike)."""
        self.snapshot.mark_sync(self.clock())

    def _staleness_tick(self, now: float) -> None:  # koordlint: guarded-by(self.lock)
        """Flip degraded mode on/off from the sync feed's age.  Runs at
        round start under the round lock."""
        threshold = self.staleness_threshold_sec
        age = self.snapshot.staleness(now)
        self._last_staleness_s = age   # flight-recorder surface
        if threshold is None or age is None:
            # watchdog disabled, or no feed has ever spoken (a scheduler
            # warming up has nothing to be stale RELATIVE to)
            return
        metrics.state_staleness_seconds.set(age, labels=self._tl())
        if not self.degraded and age > threshold:
            self.degraded = True
            self.degraded_since = now
            self.degraded_entries += 1
            # the candidate cache was built from now-untrusted deltas;
            # degraded rounds solve full-pass and re-warm on exit
            self._cand_cache = None
            metrics.degraded_mode.set(1.0, labels=self._tl())
            metrics.degraded_transitions_total.inc(
                labels={"phase": "enter", **(self._tl() or {})})
        elif self.degraded:
            exit_thr = (self.staleness_exit_sec
                        if self.staleness_exit_sec is not None
                        else threshold / 2.0)
            if age <= exit_thr:
                self.degraded = False
                self.degraded_since = None
                self._cand_cache = None
                metrics.degraded_mode.set(0.0, labels=self._tl())
                metrics.degraded_transitions_total.inc(
                    labels={"phase": "exit", **(self._tl() or {})})

    def _suspended_while_degraded(self, pod: PodSpec) -> bool:
        """Admission suspended for this pod while degraded?  BE pods and
        any pod consuming batch/mid dims: those pools are DERIVED from
        the (now stale) usage reports, so admitting against them is how
        a stale scheduler overcommits real machines.  Prod pods keep
        scheduling — their allocatable is configured, not derived.
        Reserve-pods ride along normally (a Reservation's charge is
        validated against allocatable at placement like any prod pod)."""
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.api.resources import BATCH_DIMS, MID_DIMS

        if pod.name.startswith(RSV_POD_PREFIX):
            return False
        if int(pod.qos) == int(QoSClass.BE):
            return True
        req = np.asarray(pod.requests)
        return bool(any(int(req[d]) > 0 for d in (*BATCH_DIMS, *MID_DIMS)))

    # -- the scheduling round ----------------------------------------------

    def _active_pods(self) -> list[PodSpec]:
        """PreEnqueue: skip pods of rejected gangs; while degraded, hold
        back BE/batch-dim pods (stale-state admission suspension)."""
        out = []
        suspended = 0
        self._last_suspended_names = []
        self._last_gang_rejected_names = []
        for pod in self.pending.values():
            if pod.gang is not None:
                gang = self.gangs.get(pod.gang)
                if gang is not None and gang.rejected:
                    if not pod.name.startswith(RSV_POD_PREFIX):
                        self._last_gang_rejected_names.append(pod.name)
                    continue
            if self.degraded and self._suspended_while_degraded(pod):
                suspended += 1
                if not pod.name.startswith(RSV_POD_PREFIX):
                    self._last_suspended_names.append(pod.name)
                continue
            out.append(pod)
        self.last_suspended = suspended
        metrics.degraded_suspended_pods.set(float(suspended),
                                            labels=self._tl())
        out.sort(key=lambda p: (-p.priority, p.creation, p.name))
        # weighted-fair admission cap (tenancy, ISSUE 11): a capped
        # round admits only its share of the cycle's pod budget —
        # highest-priority first, the overflow stays pending and is
        # charged to nobody (it retries next cycle with fresh credits)
        limit = self.round_pod_limit
        if limit is not None and len(out) > max(limit, 0):
            self.last_overflow = len(out) - max(limit, 0)
            out = out[: max(limit, 0)]
        else:
            self.last_overflow = 0
        return out

    def _build_batch(self, pods: list[PodSpec], gang_index: dict[str, int],
                     quota_index: dict[str, int]) -> PodBatch:
        hinted = self.hints is not None and any(
            self.hints.has_hint(pod.name) for pod in pods
        )
        # cache key: anything that feeds the batch tensors. pending_rev
        # covers pod contents (mutations go through enqueue/dequeue), the
        # name tuple covers active-set changes (gang parking/rejection),
        # capacity covers node-array growth, class_count covers new label/
        # taint equivalence classes (node->class reassignment to an existing
        # class flows through ClusterState.node_class, not the batch)
        key = (
            self._pending_rev,
            tuple(pod.name for pod in pods),
            tuple(sorted(gang_index.items())),
            tuple(sorted(quota_index.items())),
            self.snapshot.capacity,
            self.snapshot.class_count,
            self.batch_capacity_floor,
        )
        if (not hinted and self._batch_cache is not None
                and self._batch_cache[0] == key):
            return self._batch_cache[1]
        p = len(pods)
        # the tenant-axis batched solve stacks several tenants' batches
        # on a leading axis, so every tenant pads to the SAME bucket
        # (batch_capacity_floor; 0 for a standalone scheduler)
        cap = _bucket(max(p, self.batch_capacity_floor, 1), minimum=16)
        n_cap = self.snapshot.capacity
        requests = np.zeros((p, self.snapshot.dims), np.int32)
        priority = np.zeros(p, np.int32)
        qos = np.zeros(p, np.int8)
        gang_id = np.full(p, -1, np.int32)
        quota_id = np.full(p, -1, np.int32)
        non_preempt = np.zeros(p, bool)
        rot = np.zeros(p, np.int32)

        # stable rotation ids: a pod keeps its candidate tie-break when
        # the queue shifts around it (the incremental candidate cache's
        # row-independence depends on this).  The registry is pruned
        # against the live queue so a years-long scheduler doesn't leak.
        if len(self._rot_ids) > 4 * max(len(self.pending), 64):
            live = set(self.pending)
            self._rot_ids = {name: rid for name, rid in
                             self._rot_ids.items() if name in live}
        for i, pod in enumerate(pods):
            rid = self._rot_ids.get(pod.name)
            if rid is None:
                rid = self._rot_ids[pod.name] = self._rot_counter
                # 31-bit wrap: the id is a tie-break rotation identity
                # (modular by construction), and an unbounded counter
                # would overflow the int32 rot tensor after ~2.1e9
                # distinct pod names in one process lifetime
                self._rot_counter = (self._rot_counter + 1) & 0x7FFFFFFF
            rot[i] = rid

        # row-level reuse from the previous build: an incremental queue
        # change (the steady-state delta) re-fills only the rows whose
        # pod is new or re-specced; unchanged rows gather from the last
        # build's host arrays in one vectorized copy.  Only valid when
        # the id mappings and selector-mask width are unchanged — they
        # parameterize row CONTENT.
        c_cap = self.snapshot.class_capacity
        prev = self._batch_host if not hinted else None
        reuse_ok = (
            prev is not None
            and prev["gang_index"] == gang_index
            and prev["quota_index"] == quota_index
            and prev["class_cap"] == c_cap
            # class COUNT, not just the padded width: a new equivalence
            # class within the same bucket changes every pod's selector
            # row content (the new class's column)
            and prev["class_count"] == self.snapshot.class_count
            and prev["dims"] == self.snapshot.dims
        )
        sel = np.zeros((p, c_cap), bool) if not hinted else None
        fill_rows: list[int] = []
        if reuse_ok:
            src, dst = [], []
            prev_row, prev_spec = prev["row_of"], prev["specs"]
            for i, pod in enumerate(pods):
                j = prev_row.get(pod.name)
                if j is not None and prev_spec.get(pod.name) is pod:
                    src.append(j)
                    dst.append(i)
                else:
                    fill_rows.append(i)
            if dst:
                src_a, dst_a = np.asarray(src), np.asarray(dst)
                requests[dst_a] = prev["requests"][src_a]
                priority[dst_a] = prev["priority"][src_a]
                qos[dst_a] = prev["qos"][src_a]
                gang_id[dst_a] = prev["gang_id"][src_a]
                quota_id[dst_a] = prev["quota_id"][src_a]
                non_preempt[dst_a] = prev["non_preempt"][src_a]
                sel[dst_a] = prev["sel"][src_a]
        else:
            fill_rows = list(range(p))

        memo: dict[tuple, np.ndarray] = {}
        for i in fill_rows:
            pod = pods[i]
            requests[i] = pod.requests
            priority[i] = pod.priority
            qos[i] = pod.qos
            if pod.gang is not None and pod.gang in gang_index:
                gang_id[i] = gang_index[pod.gang]
            if pod.quota is not None and pod.quota in quota_index:
                quota_id[i] = quota_index[pod.quota]
            non_preempt[i] = pod.non_preemptible
            if sel is not None:
                sel_key = (
                    tuple(sorted(pod.node_selector.items())),
                    tuple(sorted(pod.tolerations.items())),
                )
                row = memo.get(sel_key)
                if row is None:
                    row = self.snapshot.selector_row_for(pod)
                    memo[sel_key] = row
                sel[i] = row

        # placement constraints: factored O(P·C) equivalence-class masks by
        # default; the dense O(P·N) path only when a pod carries per-node
        # hint edits (rare — skip/prefer hints from the hinter)
        if hinted:
            feasible = np.zeros((p, n_cap), bool)
            for i, pod in enumerate(pods):
                row = self.snapshot.feasibility_row(pod)
                feasible[i] = self.hints.apply_to_mask(pod.name, row)
            mask_kw = dict(feasible=feasible)
        else:
            mask_kw = dict(selector_mask=sel, class_capacity=c_cap)
        batch = PodBatch.build(
            requests, priority=priority, qos=qos, gang_id=gang_id,
            quota_id=quota_id, non_preemptible=non_preempt,
            node_capacity=n_cap, capacity=cap, rot_id=rot, **mask_kw,
        )
        if (not hinted and self.mesh is not None
                and self.kit.pod_shards > 1
                and self.snapshot.solver_sharding_active
                and self.kit.pods_shardable(batch.capacity)):
            # pin the batch under the 2-D mesh's pod-axis NamedSharding:
            # the cached batch is reused across steady-state rounds, so
            # the sharded entries consume it in place instead of paying
            # a host->device reshard per call.  Gated on the SAME
            # solver_sharding_active predicate as the solves — a mesh
            # present but inactive (capacity below the min-nodes floor)
            # runs single-device entries, which must not receive a
            # mesh-committed batch.  Donation-safe: no solve entry
            # donates the batch (only the state and the refresh's cache
            # donate — koordlint's donation-flow rule polices it).
            from koordinator_tpu.parallel import mesh as pmesh

            batch = pmesh.shard_pod_batch(batch, self.mesh)
        if not hinted:
            self._batch_cache = (key, batch)
            self._batch_host = {
                "row_of": {pod.name: i for i, pod in enumerate(pods)},
                "specs": {pod.name: pod for pod in pods},
                "requests": requests, "priority": priority, "qos": qos,
                "gang_id": gang_id, "quota_id": quota_id,
                "non_preempt": non_preempt, "sel": sel,
                "gang_index": dict(gang_index),
                "quota_index": dict(quota_index),
                "class_cap": c_cap,
                "class_count": self.snapshot.class_count,
                "dims": self.snapshot.dims,
            }
        self.batch_rebuilds += 1
        return batch

    def _build_gang_info(self, pods: list[PodSpec]) -> tuple[GangInfo, dict[str, int]]:
        names = sorted({p.gang for p in pods if p.gang is not None})
        index = {n: i for i, n in enumerate(names)}
        groups: dict[str, int] = {}
        min_member = np.zeros(max(len(names), 1), np.int32)
        group_id = np.arange(max(len(names), 1), dtype=np.int32)
        for name, i in index.items():
            gang = self.gangs.get(name)
            min_member[i] = gang.min_member if gang else 0
            if gang and gang.group:
                group_id[i] = groups.setdefault(gang.group, i)
        return (
            GangInfo.build(min_member[: len(names)], group_id[: len(names)])
            if names else GangInfo.build(np.zeros(0, np.int32)),
            index,
        )

    def _refresh_quota_tree(self) -> None:
        """GroupQuotaManager duty: a leaf quota's request is what its pods
        ask for — already-admitted usage plus this round's pending requests
        — then re-derive runtime (fingerprint-cached in the tree)."""
        pending: dict[str, np.ndarray] = {}
        for pod in self.pending.values():
            if pod.quota is not None and pod.quota in self.quota_tree.nodes:
                cur = pending.setdefault(
                    pod.quota, np.zeros(self.snapshot.dims, np.int64)
                )
                cur += pod.requests.astype(np.int64)
        for name, qnode in self.quota_tree.nodes.items():
            if self.quota_tree.children[name]:
                continue  # parents aggregate from children
            self.quota_tree.set_request(
                name, qnode.used + pending.get(
                    name, np.zeros(self.snapshot.dims, np.int64))
            )
        self.quota_tree.refresh_runtime()

    def _build_quota(self) -> tuple[QuotaDeviceState | None, dict[str, int]]:
        if self.quota_tree is None:
            return None, {}
        self._refresh_quota_tree()
        return QuotaDeviceState.from_tree(self.quota_tree)

    def _apply_topology_plans(
        self, batch: PodBatch, gang_index: dict[str, int]
    ) -> PodBatch:
        """FindOneNode parity (``frameworkext/interface.go:120``,
        ``coscheduling.go:137-144``): a gang with network-topology
        requirements gets a placement plan up front; each member's feasible
        set is pinned to its planned node. A gang whose plan fails is masked
        out of the round entirely (all-or-nothing at plan level)."""
        if self.topology_tree is None:
            return batch
        # densifying the factored mask is O(P·N): skip it entirely unless
        # some gang in this round actually carries topology requirements
        if not any(
            self.gangs.get(name) is not None
            and self.gangs[name].topology is not None
            for name in gang_index
        ):
            return batch
        gang_ids = np.asarray(batch.gang_id)
        feasible = np.array(batch.feasible_rows(self.snapshot.state))
        valid = np.array(batch.valid)
        changed = False
        for name, gi in gang_index.items():
            gang = self.gangs.get(name)
            if gang is None or gang.topology is None:
                continue
            mask = (gang_ids == gi) & valid
            if not mask.any():
                continue
            # quality mode swaps in the rank-aware, topology-distance
            # planner (quality/topo_gang): same feasibility kernels,
            # minimal-diameter commit rule
            if self.quality_mode != "off":
                from koordinator_tpu.quality.topo_gang import (
                    plan_gang_placement_quality,
                )

                plan_fn = plan_gang_placement_quality
            else:
                plan_fn = plan_gang_placement
            plan = plan_fn(
                self.snapshot.state, batch, mask, self.topology_tree,
                gang.topology, cfg=self.config,
            )
            changed = True
            desired = gang.topology.desired_slots or int(mask.sum())
            planned = np.flatnonzero(mask & (plan >= 0))
            if len(planned) < min(desired, int(mask.sum())):
                # no gather plan at all -> the whole gang backs off
                valid[mask] = False
                continue
            # pin planned members; surplus members (pending > desired_slots)
            # stay unpinned and schedule freely once the gang is permitted
            feasible[planned] = False
            feasible[planned, plan[planned]] = True
        if not changed:
            return batch
        # topology pinning needs per-(pod, node) edits: densify the mask
        return batch.replace(
            feasible=jnp.asarray(feasible), valid=jnp.asarray(valid),
            selector_mask=None,
        )

    def _tl(self) -> dict | None:
        """Per-tenant metric labels; None for an untenanted scheduler so
        its series (and every existing dashboard/test) are unchanged."""
        return {"tenant": self.tenant} if self.tenant else None

    def _round_begin(self) -> None:  # koordlint: guarded-by(self.lock)
        """Reset the per-round accumulators (shared by the serial
        schedule_round wrapper and the pipelined round_device entry)."""
        self.round_seq += 1
        self.monitor.start_round()
        self._solve_device_s = 0.0
        self._solve_carry_s = 0.0
        #: first dispatch edge of the round (timeline device-busy
        #: derivation); consumed by the first block edge
        self._tl_device_t0 = None
        self._last_dirty_node_frac = 0.0
        self._last_dirty_pod_frac = 0.0
        self._last_unschedulable_top = {}
        self._round_recordable = False
        #: solve-dispatch edge for the journey ledger's queue_wait/solve
        #: stage split — round-scoped: set here, read by the bind-commit
        #: paths, cleared again when the host half returns so an
        #: out-of-round bind never inherits a previous round's edge
        self._journey_round_t0 = time.perf_counter()

    def _current_path(self) -> str:
        return (self.last_solve_path
                if self.last_solver == "batch" else "greedy")

    # koordlint: guarded-by(self.lock)
    def _round_flight_record(self, result: SchedulingResult, trace_id: str,
                             start_wall: float, duration: float,
                             path: str, half: str) -> None:
        from koordinator_tpu.scheduler.flight_recorder import RoundRecord

        self.flight_recorder.record(RoundRecord(
            round=self.round_seq,
            trace_id=trace_id,
            start_time=start_wall,
            duration_s=duration,
            solver=self.last_solver,
            solve_path=path,
            pods=result.round_pods,
            placed=len(result.assignments),
            failed=len(result.failures),
            suspended=self.last_suspended,
            degraded=self.degraded,
            staleness_s=self._last_staleness_s,
            dirty_node_frac=self._last_dirty_node_frac,
            dirty_pod_frac=self._last_dirty_pod_frac,
            solve_wall_s=self.monitor.round_timings.get(
                "Solve", 0.0),
            solve_device_s=self._solve_device_s,
            phase_s=dict(self.monitor.round_timings),
            sheds_total=metrics.solve_deadline_shed_total.value(),
            top_unschedulable=dict(self._last_unschedulable_top),
            tenant=self.tenant,
            half=half,
            quality_mode=self.quality_mode,
            quality_iterations=self._last_quality_iters,
        ))

    def schedule_round(self) -> SchedulingResult:
        """Solve the current pending queue; reserve, bind, diagnose.

        Every round runs inside a ``scheduler.round`` span (joined to
        the caller's trace when one rode the solve request) whose
        attributes double as the round's flight record; rounds that got
        past the elector/barrier gates land in the flight recorder ring
        (``/debug/rounds``), slow/degraded ones dump automatically.

        The round is internally split into an explicit DEVICE half
        (:meth:`_round_device`: prelude, batch build, solve dispatch)
        and HOST half (:meth:`_round_host`: block, rescue, commit,
        diagnose); this serial wrapper runs them back to back under one
        lock hold, while the tenancy front-end drives
        :meth:`round_device`/:meth:`round_host` directly so round N+1's
        device solve overlaps round N's host commit."""
        with self.lock:
            self._round_begin()
            start_wall = time.time()
            t0 = time.perf_counter()
            with tracing.TRACER.span(
                    "scheduler.round", service="scheduler",
                    attributes={"round": self.round_seq}) as span:
                result = self._round_host(self._round_device())
                duration = time.perf_counter() - t0
                path = self._current_path()
                if not self._round_recordable:
                    # elector-standby / barrier-gated: last_solver and
                    # last_solve_path are STALE leftovers of the last
                    # deciding round — stamping them here would claim a
                    # solve that never ran
                    span.set_attributes({"gated": True})
                else:
                    span.set_attributes({
                        "solver": self.last_solver,
                        "solve_path": path,
                        "pods": result.round_pods,
                        "placed": len(result.assignments),
                        "failed": len(result.failures),
                        "suspended": self.last_suspended,
                        "degraded": self.degraded,
                        "staleness_s": self._last_staleness_s,
                        "dirty_node_frac": self._last_dirty_node_frac,
                        "dirty_pod_frac": self._last_dirty_pod_frac,
                        "solve_wall_s": self.monitor.round_timings.get(
                            "Solve", 0.0),
                        "solve_device_s": self._solve_device_s,
                    })
            if self._round_recordable:
                self._round_flight_record(result, span.trace_id,
                                          start_wall, duration, path,
                                          half="round")
            if self._round_recordable:
                self._publish_round_introspection()
            if (self._round_recordable and self.tenant_front is None
                    and timeline.RECORDER.enabled):
                # an untenanted scheduler's round IS its cycle: the
                # timeline observatory reconstructs/attributes the same
                # window the tenancy front-end would (ISSUE 18)
                doc = timeline.RECORDER.finish_cycle(
                    self.round_seq, t0, time.perf_counter(),
                    mode="round")
                if doc is not None:
                    self.flight_recorder.annotate_round(
                        self.round_seq, self.tenant,
                        cycle_seq=doc["cycle"],
                        cycle_critical_cause=doc["critical_cause"],
                        cycle_critical_seconds=doc["critical_seconds"])
            return result

    def round_device(self) -> "RoundHandle":
        """Public DEVICE-half entry for pipelined operation (the tenancy
        front-end).  The caller MUST hold ``self.lock`` across the
        ``round_device`` -> ``round_host`` pair — the handle references
        in-flight donated state, and an informer mutation between the
        halves would solve one queue and commit another.  Each half
        leaves its own flight record (``half="solve"``/``"commit"``) so
        ``/debug/rounds`` attributes slow halves to a tenant."""
        start_wall = time.time()
        t0 = time.perf_counter()
        # blanket the device half as lowest-priority host work: the
        # typed segments inside (build_batch, dispatch, lock_wait) win
        # the sweep; only the inter-phase glue lands here instead of in
        # the unattributed residual
        with timeline.RECORDER.section("host_other", "round.prepare",
                                       self.tenant):
            self._round_begin()
            with tracing.TRACER.span(
                    "scheduler.round.solve", service="scheduler",
                    attributes={"round": self.round_seq,
                                "tenant": self.tenant}) as span:
                handle = self._round_device()
        handle.start_wall = start_wall
        handle.t0 = t0
        if self._round_recordable and not handle.done:
            self._round_flight_record(
                handle.result, span.trace_id, start_wall,
                time.perf_counter() - t0, self._current_path(),
                half="solve")
        return handle

    def round_host(self, handle: "RoundHandle") -> SchedulingResult:
        """Public HOST-half entry: block on the dispatched solve and
        commit.  Pairs with :meth:`round_device` under one lock hold."""
        # blanket the host half like round_device does: block waits keep
        # their device_block priority, commit glue stops leaking into
        # the unattributed residual
        with timeline.RECORDER.section("host_other", "round.commit",
                                       self.tenant):
            with tracing.TRACER.span(
                    "scheduler.round.commit", service="scheduler",
                    attributes={"round": self.round_seq,
                                "tenant": self.tenant}) as span:
                result = self._round_host(handle)
            if self._round_recordable:
                self._round_flight_record(
                    result, span.trace_id, handle.start_wall,
                    time.perf_counter() - handle.t0, self._current_path(),
                    half="commit")
                self._publish_round_introspection()
        return result

    # koordlint: guarded-by(self.lock)
    def _publish_round_introspection(self) -> None:
        # device-resident footprint of the persistent solver
        # tensors, from array metadata only (no sync): the
        # live-bytes half of the introspection surface
        from koordinator_tpu.ops import introspection as insp

        metrics.solver_device_bytes.set(
            float(insp.device_bytes(self.snapshot.state)),
            labels={"kind": "cluster_state"})
        cand = self._cand_cache
        metrics.solver_device_bytes.set(
            float(insp.device_bytes(
                cand["cache"] if cand else None)),
            labels={"kind": "candidate_cache"})
        # sharded-solve introspection: the active nodes-axis
        # width plus the per-device slice of each persistent
        # tensor (a lopsided shard is a placement bug)
        active = (self.mesh is not None
                  and self.snapshot.solver_sharding_active)
        active_shards = self.solver_shard_count if active else 1
        pod_shards = self.kit.pod_shards if active else 1
        metrics.solver_shard_count.set(float(active_shards))
        # per-axis split of the 2-D mesh (ISSUE 14): the flat
        # shard count can't distinguish 2x4 from 1x8
        metrics.solver_axis_shard_count.set(
            float(active_shards), labels={"axis": "nodes"})
        metrics.solver_axis_shard_count.set(
            float(pod_shards), labels={"axis": "pods"})
        if active_shards > 1 or pod_shards > 1:
            for kind, tree in (
                ("cluster_state", self.snapshot.state),
                ("candidate_cache",
                 cand["cache"] if cand else None),
            ):
                for (pi, ni), nbytes in (
                        insp.device_bytes_by_mesh_shard(
                            tree, self.mesh).items()):
                    metrics.solver_device_bytes.set(
                        float(nbytes),
                        labels={"kind": kind,
                                "shard": f"p{pi}n{ni}"})
        if self.explain:
            # per-dim capacity slack: the headroom context for
            # the round's fit_<dim> rejection counts
            from koordinator_tpu.api.resources import ResourceDim

            free_sum, alloc_sum = self._slack_sums(
                self.snapshot.state)
            free_sum = np.asarray(free_sum)
            alloc_sum = np.asarray(alloc_sum)
            for dim in ResourceDim:
                total = float(alloc_sum[dim])
                metrics.capacity_slack.set(
                    (float(free_sum[dim]) / total) if total > 0
                    else 1.0,
                    labels={"dim": dim.name.lower()})

    def _recover_solve_failure(self) -> None:  # koordlint: guarded-by(self.lock)
        """The jitted solves DONATE the state buffers: an execution-time
        failure mid-round has already consumed them, and without
        recovery every later round would die on "Array has been
        deleted".  (Trace/compile errors — the common failure class —
        raise before any donation executes, so the buffers are still
        live and nothing is rebuilt.)  The conservative rebuild keeps
        the scheduler alive and never-overcommitting; a sync resync
        restores exact accounting."""
        if any(getattr(leaf, "is_deleted", lambda: False)()
               for leaf in jax.tree.leaves(self.snapshot.state)):
            self.snapshot.rebuild_conservative()
        self._cand_cache = None

    def _round_device(self) -> RoundHandle:  # koordlint: guarded-by(self.lock)
        """The DEVICE half of a round: gates, host prelude (reservation
        tick, nominations, quota revoke), PreEnqueue, BatchBuild, and
        the solve DISPATCH — no blocking on device results.  JAX's
        async dispatch returns immediately, so when the host half (or
        another tenant's) commit work runs next, this round's solve is
        already executing on the device.

        Donation contract (the double-buffered hand-off): the
        dispatched solve donates ``snapshot.state``'s buffers and the
        snapshot is re-pointed at the returned in-flight arrays before
        this method returns — the blessed swap.  The PRE-dispatch state
        must never be stashed (koordlint's donation-safety corpus seeds
        both sides of this idiom); reads of ``snapshot.state`` between
        the halves are safe and simply block until the solve lands.

        Internally ``prepare`` (through BatchBuild) and ``dispatch``
        are separate steps so the tenancy front-end can gather every
        tenant's prepared batch and dispatch ONE tenant-axis batched
        program instead (tenancy._batched_dispatch)."""
        return self._round_dispatch(self._round_prepare())

    def _round_prepare(self) -> RoundHandle:  # koordlint: guarded-by(self.lock)
        """Gates + host prelude + PreEnqueue + BatchBuild (no solve)."""
        # set at round START — before any early return, including the
        # barrier gate, so a backlog building behind the barrier is visible.
        # Synthetic rsv:: reserve-pods are excluded (they are placement
        # vehicles, not user backlog — the auditor filters them the same way)
        metrics.pending_pods.set(float(sum(
            1 for name in self.pending
            if not name.startswith(RSV_POD_PREFIX))), labels=self._tl())
        handle = RoundHandle(result=SchedulingResult({}, {}, 0))
        if self.elector is not None and not self.elector.tick():
            # standby replica: keep syncing state, decide nothing — and
            # surface the standby (empty) result on the debug API instead
            # of a stale leader-era diagnosis
            self.last_result = handle.result
            handle.done = True
            return handle
        if self.barrier is not None and not self.barrier.check():
            # stale cache after restart: refuse to decide until the informer
            # replays past the barrier (sync_barrier.go semantics)
            handle.done = True
            return handle
        now = self.clock()
        handle.now = now
        # a round that got this far decided (or legitimately found
        # nothing to decide): it belongs in the flight recorder —
        # standby/barrier-gated rounds above do not
        self._round_recordable = True
        self._staleness_tick(now)
        if self.forecast_mode != "off" and self.forecast_plane is not None:
            # feed the forecast plane from the freshly-flushed usage
            # tensor (pre-dispatch: the state buffers are live) and
            # refresh predictions on the plane's own cadence
            self.snapshot.flush()
            self.forecast_plane.observe_state(self.snapshot.state)
            self.forecast_plane.maybe_refresh()
        result = handle.result
        self.last_result = result  # debug-API diagnosis surface
        if len(self.reservations):
            with self.monitor.phase("Reservations"):
                self.snapshot.flush()   # pinned-fit check reads device rows
                self._reservation_tick(now)
        if self.nominations:
            with self.monitor.phase("Nominated"):
                self.snapshot.flush()
                self._resolve_nominations(result)
        if self.overuse_revoke is not None and self.quota_tree is not None:
            with self.monitor.phase("QuotaRevoke"):
                # AFTER nominations (their released quota charges must not
                # trigger needless evictions) and BEFORE the solve (freed
                # headroom is visible to this round's admission); the
                # monitor must see a FRESH runtime — a stale/zeroed one
                # would flag healthy quotas
                self._refresh_quota_tree()
                self.overuse_revoke.revoke_once()
        with self.monitor.phase("PreEnqueue"):
            pods = self._active_pods()
        if not pods:
            # an all-suspended / all-parked queue still explains itself:
            # the held-out pods' explanations and the unschedulability
            # rollups must not depend on anything having SOLVED
            if self.explain:
                self._record_round_explanations(
                    [], result, [], set(), len(self.snapshot.node_index))
            handle.done = True
            return handle
        if self.auditor is not None:
            # one attempt per workload key per round — a gang is one
            # scheduling attempt, not len(members) attempts; synthetic
            # reserve-pods are not workloads
            for key in {pod.gang or pod.name for pod in pods
                        if not pod.name.startswith(RSV_POD_PREFIX)}:
                self.auditor.record_attempt(key)

        with self.monitor.phase("BatchBuild"):
            self.snapshot.flush()
            gangs, gang_index = self._build_gang_info(pods)
            quota, quota_index = self._build_quota()
            batch = self._build_batch(pods, gang_index, quota_index)
            batch = self._apply_topology_plans(batch, gang_index)
            # padding-waste fraction of the power-of-two pod bucketing:
            # device memory/FLOPs spent on rows no pod occupies
            metrics.solver_batch_padding_waste.set(
                1.0 - len(pods) / max(batch.capacity, 1))

        if (self.debug_service is not None
                and self.debug_service.dump_top_n_scores > 0):
            # debug-only extra solve: dump per-pod node scores.  BEFORE
            # the solve phase: the jitted solves donate the state
            # buffers, so pre-solve state is unreadable once they run.
            # The dump records scores against the PRE-ROUND accounting —
            # on reservation rounds that is now before the reservation
            # prepass adopts its bindings (previously the dump ran after
            # it), so it shows the state the round STARTED from
            from koordinator_tpu.ops.assignment import score_pods

            scores, _ = score_pods(self.snapshot.state, batch, self.config)
            self.debug_service.record_scores(
                pods, np.asarray(scores),
                [self.snapshot.node_name(r) or str(r)
                 for r in range(self.snapshot.state.capacity)],
            )
        handle.pods, handle.batch = pods, batch
        handle.gangs, handle.gang_index = gangs, gang_index
        handle.quota = quota
        return handle

    def _round_dispatch(self, handle: RoundHandle) -> RoundHandle:  # koordlint: guarded-by(self.lock)
        """Dispatch the prepared round's solve (async); see
        :meth:`_round_device` for the donation contract."""
        if handle.done:
            return handle
        pods, batch, result = handle.pods, handle.batch, handle.result
        gangs, gang_index = handle.gangs, handle.gang_index
        quota = handle.quota
        # dispatch wall is carried into the host half's single "Solve"
        # phase observation (monitor.phase carry_s) so the round still
        # produces exactly ONE Solve latency observation — the SLO
        # engine's per-observation bad fractions must not dilute
        dispatch_t0 = time.perf_counter()
        try:
            if self.faults is not None:
                # chaos seam: an injected solve delay lands in the
                # round's Solve scheduling_duration observation (via
                # carry_s) — the synthetic latency regression the SLO
                # engine's burn windows must catch (tests/test_slo_monitor)
                self.faults.on_solve()
            if len(self.reservations):
                batch, quota = self._reservation_prepass(
                    pods, batch, quota, result)
            solver = ("batch" if len(pods) >= self.batch_solver_threshold
                      else "greedy")
            self.last_solver = solver
            # forecast path (ISSUE 15): an active forecast round solves
            # with the headroom reserve charged into the accounting for
            # the duration of the solve.  The reserve re-shapes every
            # node's visible free capacity, so the incremental candidate
            # cache (scored against UNcharged state) and the quality
            # escalation latch (slack measured without the reserve) both
            # stand down — forecast rounds take the full charged path.
            forecast_reserve = self._forecast_reserve()
            handle.forecast_reserve = forecast_reserve
            # quality path (ISSUE 13): an escalated gangless round
            # solves with the LP-relaxation packing engine instead of
            # the greedy propose/accept rounds.  Gang rounds keep the
            # gang_assign path (all-or-nothing semantics live there;
            # quality mode reaches them through the topology planner
            # in _apply_topology_plans instead).
            use_quality = (
                forecast_reserve is None
                and not gang_index
                and (self.quality_mode == "lp"
                     or (self.quality_mode == "auto"
                         and self._quality_escalate)))
            # incremental fast path: a gangless batch round re-scores only
            # the delta against the persistent candidate cache; gang
            # rounds, hinted (dense-mask) rounds, the exact greedy
            # solver — and DEGRADED rounds, whose cache was built from
            # a stalled feed — keep the one-call full path
            use_inc = (not use_quality
                       and forecast_reserve is None
                       and solver == "batch" and self.incremental_solve
                       and not self.degraded
                       and not gang_index
                       and batch.selector_mask is not None)
            if use_quality:
                solver = "batch"   # the host half's rescue/commit path
                self.last_solver = solver
                self.last_solve_path = "quality_lp"
                metrics.incremental_solve_total.inc(
                    labels={"path": "quality_lp"})
                use_mesh = (self.mesh is not None
                            and self.snapshot.solver_sharding_active
                            and self._quality_solve_sh is not None)
                qfn = (self._quality_solve_sh if use_mesh
                       else self._quality_solve)
                # pre-solve slack (async device sums, blocked on in the
                # host half): the quality_slack_recovered baseline.
                # Dispatched BEFORE the donating solve consumes the
                # state buffers.
                slack_before = self._slack_sums(self.snapshot.state)
                assignments, new_state, new_quota, qiters = qfn(
                    self.snapshot.state, batch, self.config, quota)
                # the blessed swap (see the full-path branch below)
                self.snapshot.state = new_state
                # the LP solve re-packed everything: the candidate
                # cache's top-k is stale against the new accounting
                self._cand_cache = None
                handle.assignments = assignments
                handle.new_state = new_state
                handle.new_quota = new_quota
                handle.quality = {"iters": qiters,
                                  "slack_before": slack_before}
            elif use_inc:
                handle.inc = self._dispatch_batch_incremental(
                    pods, batch, quota)
                handle.assignments = handle.inc["a"]
                handle.new_state = handle.inc["state"]
                handle.new_quota = handle.inc["quota"]
            else:
                if solver == "batch":
                    self.last_solve_path = (
                        "forecast_full" if forecast_reserve is not None
                        else "full_gang" if gang_index
                        else "full_dense" if batch.selector_mask is None
                        else "degraded" if self.degraded
                        else "disabled")
                    metrics.incremental_solve_total.inc(labels={
                        "path": self.last_solve_path})
                if forecast_reserve is not None:
                    solve_fn = (self._forecast_solve_sh
                                if self._use_sharded_solve(batch)
                                else self._forecast_solve)
                    assignments, new_state, new_quota = solve_fn(
                        self.snapshot.state, forecast_reserve, batch,
                        self.config, gangs, quota,
                        passes=self.gang_passes, solver=solver,
                    )
                else:
                    solve_fn = (self._solve_sh
                                if self._use_sharded_solve(batch)
                                else self._solve)
                    assignments, new_state, new_quota = solve_fn(
                        self.snapshot.state, batch, self.config, gangs,
                        quota, passes=self.gang_passes, solver=solver,
                    )
                # the blessed swap: the jitted solve donated the old
                # state buffers; the snapshot re-points at the in-flight
                # result immediately so nothing can read the dead ones
                self.snapshot.state = new_state
                handle.assignments = assignments
                handle.new_state = new_state
                handle.new_quota = new_quota
        except Exception:
            self._recover_solve_failure()
            raise
        finally:
            self._solve_carry_s += time.perf_counter() - dispatch_t0
            if timeline.RECORDER.enabled:
                # the async solve starts executing during this window:
                # its start doubles as the device-busy leading edge the
                # idle derivation pairs with the block edge
                timeline.RECORDER.add(
                    dispatch_t0, time.perf_counter(), "dispatch",
                    "round.dispatch", self.tenant)
                if self._tl_device_t0 is None:
                    self._tl_device_t0 = dispatch_t0
        # the prepass may have shrunk the batch and charged the quota
        handle.batch, handle.quota, handle.solver = batch, quota, solver
        # stamped here so the pipelined solve-half flight record carries
        # the admitted count (the host half re-stamps the same value)
        result.round_pods = len(pods)
        return handle

    # koordlint: guarded-by(self.lock)
    # koordlint: shape[a: P i32 rep, new_state: NxR i32 nodes]
    def round_adopt_batched(self, handle: RoundHandle, a, new_state,
                            new_quota, est_accum, cache, k: int,
                            method: str) -> RoundHandle:
        """Adopt one tenant's slice of a TENANT-AXIS batched solve as
        this round's dispatched pass 1 (tenancy front-end;
        ``tenancy._batched_dispatch`` ran one ``vmap``-batched
        select+pass1 program over every tenant's stacked state).
        Mirrors the serial ``full_cold`` branch bookkeeping: the dirty
        set is consumed, the candidate cache re-warms from the batched
        selection (so the NEXT round goes incremental), and the finish
        context hands the pass-2 loop to :meth:`_round_host`."""
        snap = self.snapshot
        # the batched program re-selected every candidate: consume the
        # dirty set exactly like the serial full-selection path does
        snap.consume_candidate_dirty()
        self.last_solver = "batch"
        self.last_solve_path = "tenant_batched"
        metrics.incremental_solve_total.inc(
            labels={"path": "tenant_batched"})
        host = self._batch_host
        self._cand_cache = {
            "cache": cache,
            "row_of": host["row_of"],
            "specs": host["specs"],
            "n": snap.capacity, "k": k, "spread": self.cand_spread,
            "method": method, "cfg": self.config,
        }
        # the blessed swap, batched form: the stacked program consumed a
        # COPY of the per-tenant states (stacking copies), so the old
        # buffers stay live until this re-point drops them
        snap.state = new_state
        handle.solver = "batch"
        handle.assignments = a
        handle.new_state = new_state
        handle.new_quota = new_quota
        handle.inc = {"a": a, "state": new_state, "quota": new_quota,
                      "est_accum": est_accum, "batch": handle.batch,
                      "k": k, "method": method, "use_mesh": False}
        handle.result.round_pods = len(handle.pods)
        return handle

    # koordlint: guarded-by(self.lock)
    # koordlint: shape[a: P i32 rep, new_state: NxR i32 nodes]
    def round_adopt_quality_batched(self, handle: RoundHandle, a,
                                    new_state, new_quota, qiters,
                                    slack_before) -> RoundHandle:
        """Adopt one tenant's slice of the QUALITY tenant-axis solve
        (tenancy._dispatch_quality_axis_inner ran one vmapped
        lp_pack_assign over every escalated tenant's stacked state).
        Mirrors the standalone use_quality branch of _round_dispatch
        exactly: blessed swap, candidate-cache invalidation (the LP
        solve re-packed everything), and the handle.quality context
        _quality_round_finish consumes."""
        self.last_solver = "batch"
        self.last_solve_path = "quality_lp_batched"
        metrics.incremental_solve_total.inc(
            labels={"path": "quality_lp_batched"})
        # the blessed swap, batched form (see round_adopt_batched)
        self.snapshot.state = new_state
        self._cand_cache = None
        handle.solver = "batch"
        handle.assignments = a
        handle.new_state = new_state
        handle.new_quota = new_quota
        handle.quality = {"iters": qiters,
                          "slack_before": slack_before}
        handle.result.round_pods = len(handle.pods)
        return handle

    def _round_host(self, handle: RoundHandle) -> SchedulingResult:  # koordlint: guarded-by(self.lock)
        """The HOST half: block on the dispatched solve, run the exact
        rescue pass, then Reserve/Bind/Diagnose/PostFilter — the commit
        work round N+1's device solve overlaps under pipelined
        operation (tenancy front-end)."""
        if handle.done:
            self._journey_round_t0 = None   # gated round: no solve edge
            return handle.result
        pods, batch, result = handle.pods, handle.batch, handle.result
        gangs, quota, solver = handle.gangs, handle.quota, handle.solver
        now = handle.now
        assignments = handle.assignments
        new_state, new_quota = handle.new_state, handle.new_quota
        try:
            with self.monitor.phase("Solve",
                                    carry_s=self._solve_carry_s):
                self._solve_carry_s = 0.0
                if handle.inc is not None:
                    assignments, new_state, new_quota = (
                        self._finish_batch_incremental(handle.inc))
                a = np.asarray(self._block_timed(assignments))
                leftover = np.asarray(batch.valid) & (a < 0)
                if solver == "batch" and bool(leftover[: len(pods)].any()):
                    # exact rescue pass over the leftovers: the batch engine's
                    # top-k/round approximation may fail pods a greedy scan
                    # would place, and a solver-approximation failure must
                    # never feed preemption, the gang WaitTime machine, or a
                    # persisted ScheduleFailed explanation. Rolled-back gangs
                    # come back whole; SURPLUS members of a gang already
                    # satisfied this round rescue as gangless pods (min_member
                    # is met — extras bind individually) so pre_enqueue/rollback
                    # inside the rescue solve can't strand them.
                    ga = np.asarray(batch.gang_id)
                    placed = np.bincount(
                        ga[(ga >= 0) & (a >= 0)], minlength=gangs.capacity)
                    satisfied = placed >= np.asarray(gangs.min_member)
                    gid = batch.gang_id
                    rescue_gid = jnp.where(
                        (gid >= 0) & jnp.asarray(satisfied)[jnp.maximum(gid, 0)],
                        -1, gid)
                    # compact the leftovers first: the exact greedy solve is a
                    # sequential scan over the POD AXIS, so rescuing 50 pods
                    # must cost a 64-row scan, not the full 50k-row batch.
                    # ``leftover`` is the single source of truth for which rows
                    # rescue (compact keeps exactly those and marks the rest of
                    # the padded capacity invalid).
                    small, idx = batch.replace(gang_id=rescue_gid).compact(
                        leftover)
                    if handle.forecast_reserve is not None:
                        # a forecast round's rescue must see the SAME
                        # charged accounting as its main solve — an
                        # uncharged rescue would re-admit exactly the
                        # pods the reserve just filtered
                        rescue_fn = (self._forecast_solve_sh
                                     if self._use_sharded_solve(small)
                                     else self._forecast_solve)
                        r_small, new_state, new_quota = rescue_fn(
                            new_state, handle.forecast_reserve, small,
                            self.config, gangs, new_quota,
                            passes=self.gang_passes, solver="greedy",
                        )
                    else:
                        rescue_fn = (self._solve_sh
                                     if self._use_sharded_solve(small)
                                     else self._solve)
                        r_small, new_state, new_quota = rescue_fn(
                            new_state, small, self.config, gangs,
                            new_quota,
                            passes=self.gang_passes, solver="greedy",
                        )
                    self.snapshot.state = new_state
                    r_full = np.full(batch.capacity, -1, np.int32)
                    r_full[idx] = np.asarray(
                        self._block_timed(r_small))[: len(idx)]
                    assignments = jnp.where(
                        assignments >= 0, assignments, jnp.asarray(r_full))
                    a = np.asarray(assignments)
        except Exception:
            # execution-time donation failure: the block above is where
            # a dispatched-then-failed solve actually SURFACES, so the
            # conservative-rebuild recovery runs in both halves
            self._recover_solve_failure()
            raise
        result.round_pods = len(pods)
        # wall vs. device: the Solve phase's wall time is in the monitor;
        # this is the share spent blocked on jitted solve results
        metrics.solver_device_latency.observe(
            self._solve_device_s,
            labels={"path": (self.last_solve_path if solver == "batch"
                             else "greedy")},
            exemplar=({"trace_id": tracing.current_trace_id()}
                      if tracing.current_context() is not None else None))
        with self.monitor.phase("Reserve"):
            self.snapshot.adopt_state(new_state,
                                      changed_rows=np.unique(a[a >= 0]))
        if (handle.forecast_reserve is not None
                and self.forecast_plane is not None):
            # one small (R,) device reduction per FORECAST round (the
            # off mode never pays it): how much of the cluster the
            # admission reserve held back this round.  Tenant-labelled
            # like every scheduler gauge — per-tenant planes must not
            # overwrite each other's telemetry.
            metrics.forecast_admission_reserved_fraction.set(
                self.forecast_plane.reserve_fraction(
                    handle.forecast_reserve, self.snapshot.state),
                labels=self._tl())

        with self.monitor.phase("Bind"):
            placed_gangs: set[str] = set()
            binds: list[tuple[PodSpec, str]] = []
            for i, pod in enumerate(pods):
                node_row = int(a[i])
                if node_row >= 0:
                    node = self.snapshot.node_name(node_row)
                    if pod.name.startswith(RSV_POD_PREFIX):
                        self._commit_reserve_pod(pod, node, result, now)
                        continue
                    binds.append((pod, node))
                    if pod.gang:
                        placed_gangs.add(pod.gang)
            self._commit_bind_batch(binds, result)

        with self.monitor.phase("Diagnose"):
            admitted = None
            if quota is not None:
                from koordinator_tpu.quota.admission import quota_admission_mask

                # attribute against the POST-solve quota: a pod that lost
                # the headroom to this round's placements failed BECAUSE of
                # quota, even though pre-solve admission would have passed.
                # (Blame is applied per pod below only when nodes were
                # otherwise feasible — a pod that failed on capacity or
                # affinity keeps its real reason.)
                diag_quota = new_quota if new_quota is not None else quota
                admitted = np.asarray(quota_admission_mask(
                    diag_quota, batch.requests, batch.quota_id,
                    batch.non_preemptible
                ))
            fail_rows = [
                i for i, pod in enumerate(pods)
                if int(a[i]) < 0
                # a pod in assignments was bound by the reservation
                # pre-pass (batch row invalidated before the main solve)
                and pod.name not in result.assignments
            ]
            counts = feas = None
            row_pos: dict[int, int] = {}
            if self.explain and fail_rows:
                # ONE device reduction over the compacted failed rows
                # (ops/explain.explain_counts) instead of a host numpy
                # mask recompute per failed pod — O(F·NUM_REASONS) comes
                # back, the (F, N) masks never leave the device
                from koordinator_tpu.scheduler.diagnosis import (
                    diagnosis_from_counts,
                )

                fmask = np.zeros(batch.capacity, bool)
                fmask[fail_rows] = True
                small, idx = batch.compact(fmask)
                c_dev, f_dev = self._explain_counts(
                    self.snapshot.state, small, self.config)
                # plain block, NOT _block_timed: _solve_device_s feeds
                # the flight record's Solve-phase wall-vs-device split
                # (already observed by solver_device_latency), and
                # Diagnose-phase device time would skew both
                counts = np.asarray(jax.block_until_ready(c_dev))
                feas = np.asarray(f_dev)
                row_pos = {int(r): j for j, r in enumerate(idx)}
            total_nodes = len(self.snapshot.node_index)
            failed_gangs: set[str] = set()
            for i in fail_rows:
                pod = pods[i]
                if counts is not None:
                    # diagnosis_from_counts was imported when the kernel
                    # ran (counts is only non-None on that path)
                    j = row_pos[i]
                    diag = diagnosis_from_counts(
                        counts[j], int(feas[j]), total_nodes,
                        quota_admitted=True)
                else:
                    diag = explain_pod(
                        self.snapshot.state, batch, self.config, i,
                        quota_admitted=True,
                    )
                if (admitted is not None and not admitted[i]
                        and diag.feasible_nodes > 0):
                    # nodes were available but the quota (as of this
                    # round's placements) says no: quota is the cause
                    if diag.reason_counts is not None:
                        diag.reason_counts["quota"] = diag.feasible_nodes
                    diag = dataclasses.replace(
                        diag, quota_rejected=True, feasible_nodes=0)
                result.failures[pod.name] = diag
                if pod.gang:
                    failed_gangs.add(pod.gang)

            # gang WaitTime state machine (Permit timeout semantics)
            for name in failed_gangs - placed_gangs:
                gang = self.gangs.get(name)
                if gang is None:
                    continue
                if gang.first_failure is None:
                    gang.first_failure = now
                elif now - gang.first_failure > gang.wait_time_sec:
                    gang.rejected = True
            for name in placed_gangs:
                gang = self.gangs.get(name)
                if gang is not None:
                    gang.first_failure = None
            if self.explain:
                self._record_round_explanations(
                    pods, result, fail_rows, failed_gangs, total_nodes)

        if self.enable_preemption and result.failures:
            with self.monitor.phase("PostFilter"):
                self._run_preemption(pods, batch, result)

        if self.explanations is not None:
            # persist AFTER PostFilter so nominations land on the CR
            # (successful binds already cleared theirs in _commit_bind)
            for pod in pods:
                if pod.name.startswith(RSV_POD_PREFIX):
                    # an unplaced reservation retries next round; it is not
                    # a user pod and must not persist ScheduleFailed CRs
                    continue
                diag = result.failures.get(pod.name)
                if diag is not None:
                    self.explanations.record(pod.name, diag)
                    if self.auditor is not None:
                        self.auditor.record(pod.gang or pod.name,
                                            "ScheduleFailed", diag.message())

        # every round in an ON mode runs the finish hook: "lp" gang
        # rounds must reset _last_quality_iters to 0 or their flight
        # records would carry the previous LP round's iteration count
        if handle.quality is not None or self.quality_mode != "off":
            self._quality_round_finish(handle, result)

        metrics.pending_pods.set(float(len(self.pending)),
                                 labels=self._tl())  # post-bind queue
        # round over: binds landed after this point (nomination
        # conversions, reservation draws outside a round) stamp their
        # own commit edge instead of inheriting this round's
        self._journey_round_t0 = None
        return result

    # -- solve-quality mode (ISSUE 13) --------------------------------------

    def arm_quality_escalation(self) -> None:
        """Arm the auto-mode escalation latch by hand — a warmup aid.

        A harness (tools/loadgen) can force its warm round onto the LP
        path so the quality program's one-time jit compile lands BEFORE
        any latency-SLO or trend window opens; without this, auto mode
        pays the compile on the first round that escalates mid-run.
        No-op when ``quality_mode == "off"``; the latch re-evaluates
        from real slack at the end of every round, so arming never
        sticks past the next round.
        """
        if self.quality_mode != "off":
            with self.lock:
                self._quality_escalate = True

    def _quality_round_finish(self, handle: RoundHandle, result) -> None:  # koordlint: guarded-by(self.lock)
        """Quality-round accounting + the auto-mode escalation latch.

        Runs at the END of the host half so the slack sums see the
        round's final accounting (rescue pass included) and the outcome
        label sees the diagnosed failures.  One cheap jitted (R,)
        reduction per round — the same kernel the explain rollup uses.
        """
        from koordinator_tpu.api.resources import ResourceDim

        free_sum, alloc_sum = self._slack_sums(self.snapshot.state)
        free_sum = np.asarray(free_sum)
        alloc_sum = np.asarray(alloc_sum)
        # min over provisioned dims: escalation means EVERY dimension
        # still has headroom worth winning back (a cluster out of CPU
        # but swimming in memory has nothing a better packing recovers)
        slack_min = min(
            (float(free_sum[d]) / float(alloc_sum[d])
             for d in ResourceDim if float(alloc_sum[d]) > 0),
            default=0.0)
        self._quality_escalate = slack_min > self.quality_slack_threshold
        if handle.quality is None:
            self._last_quality_iters = 0
            return
        iters = int(np.asarray(self._block_timed(
            handle.quality["iters"])))
        self._last_quality_iters = iters
        metrics.quality_iterations.observe(float(iters),
                                           labels=self._tl())
        free_b, alloc_b = (np.asarray(x)
                           for x in handle.quality["slack_before"])
        for dim in ResourceDim:
            total = float(alloc_b[dim])
            recovered = ((float(free_b[dim]) - float(free_sum[dim]))
                         / total if total > 0 else 0.0)
            metrics.quality_slack_recovered.set(
                max(recovered, 0.0), labels={"dim": dim.name.lower()})
        outcome = "partial" if result.failures else "complete"
        metrics.quality_rounds.inc(
            labels={"mode": self.quality_mode, "outcome": outcome})

    # -- incremental delta-driven solve -------------------------------------

    def _block_timed(self, value):  # koordlint: guarded-by(self.lock)
        """Block on a jitted solve's result, accumulating the wait into
        the round's device-time share (``_solve_device_s``).  The
        dispatch itself returns immediately (async execution), so time
        spent HERE is device compute + transfer — the wall-vs-device
        split the flight recorder and round span report."""
        t0 = time.perf_counter()
        value = jax.block_until_ready(value)
        t1 = time.perf_counter()
        self._solve_device_s += t1 - t0
        if timeline.RECORDER.enabled:
            timeline.RECORDER.add(t0, t1, "device_block",
                                  "block_until_ready", self.tenant)
            # device-busy span: the dispatch edge (when this round
            # dispatched async work) to this block edge.  A block with
            # no tracked dispatch (rescue pass) contributes just its
            # own wait — an under-estimate of busy, never of idle.
            busy_t0 = getattr(self, "_tl_device_t0", None)
            timeline.RECORDER.add(busy_t0 if busy_t0 is not None else t0,
                                  t1, timeline.DEVICE_BUSY,
                                  "solve", self.tenant)
            self._tl_device_t0 = None
        return value

    def sharding_report(self) -> dict:
        """The /debug/slo "sharded solve" section: active mesh shape,
        per-device bytes of the persistent solver tensors, and the
        recompile counters per (fn, shape) bucket — shape buckets carry
        an ``@<n>shard`` suffix while the mesh is active, so a
        per-mesh-shape compile regression reads straight off this
        document (and off ``solver_recompiles_total{shape}``)."""
        from koordinator_tpu.ops import introspection as insp
        from koordinator_tpu.parallel.mesh import NODES_AXIS, PODS_AXIS

        cand = self._cand_cache

        def _by_shard(tree):
            # keyed by (pod_shard, node_shard) mesh coordinate when the
            # mesh exists (ISSUE 14), flat device id otherwise
            if self.mesh is not None:
                return {f"p{pi}n{ni}": b for (pi, ni), b in
                        insp.device_bytes_by_mesh_shard(
                            tree, self.mesh).items()}
            return {str(d): b for d, b in
                    insp.device_bytes_by_shard(tree).items()}

        return {
            "solver_shard_count": (self.solver_shard_count
                                   if self.mesh is not None else 1),
            "active": bool(self.mesh is not None
                           and self.snapshot.solver_sharding_active),
            "mesh": ({"pods": int(self.mesh.shape[PODS_AXIS]),
                      "nodes": int(self.mesh.shape[NODES_AXIS])}
                     if self.mesh is not None else None),
            "pod_shard_count": (self.kit.pod_shards
                                if self.mesh is not None else 1),
            "shard_min_nodes": self.shard_min_nodes,
            "device_bytes_by_shard": {
                "cluster_state": _by_shard(self.snapshot.state),
                "candidate_cache": _by_shard(
                    cand["cache"] if cand else None),
            },
            "recompiles_by_shape": {
                f"{lbl.get('fn', '?')}[{lbl.get('shape', '?')}]": int(v)
                for lbl, v in metrics.solver_recompiles.items()},
        }

    def _use_sharded_solve(self, batch: PodBatch) -> bool:  # koordlint: guarded-by(self.lock)
        """Should this batch run the explicit shard_map gang/greedy twin
        (``kit.solve_sh``)?  Yes when the mesh is active for the current
        node capacity, the batch carries the factored selector-mask
        feasibility form (a dense (P, N) mask cannot tile over the 2-D
        mesh), and the batch capacity divides over the pods axis."""
        return (self._solve_sh is not None
                and self.snapshot.solver_sharding_active
                and batch.selector_mask is not None
                and self.kit.pods_shardable(batch.capacity))

    def _solve_batch_incremental(self, pods, batch: PodBatch, quota):  # koordlint: guarded-by(self.lock)
        """One-call form of the incremental solve (dispatch + finish):
        kept for callers outside the round pipeline.  Returns
        (assignments, new_state, new_quota) like gang_assign."""
        return self._finish_batch_incremental(
            self._dispatch_batch_incremental(pods, batch, quota))

    def _dispatch_batch_incremental(self, pods, batch: PodBatch, quota) -> dict:  # koordlint: guarded-by(self.lock)
        """The no-gang batch solve with the persistent device-resident
        candidate cache (ops/batch_assign incremental section) — the
        DEVICE half: candidate refresh/selection and the pass-1 solve
        are dispatched (async) and returned as a finish context for
        :meth:`_finish_batch_incremental`; nothing heavy is blocked on
        here (the (P,) ``touch`` readback for dirty-pod mapping is the
        one small sync).

        Steady state: the round re-scores only dirty rows — pods newly
        arrived/re-specced or whose cached candidates touch a dirty node —
        against a dirty-node column mask accumulated by the snapshot from
        the deltas applied under this scheduler's round lock, then merges
        them into the cached (P, k) tensor.  When the dirty fraction
        crosses ``incremental_dirty_threshold`` (or no valid cache
        exists) the full selection runs instead and re-warms the cache.
        Either way the propose/accept passes afterwards mirror
        gang_assign's gangless pass loop bit for bit, so flipping paths
        never changes acceptance decisions — staleness in the cache can
        only cost candidate recall, and acceptance re-checks fit and
        quota exactly.
        """
        from koordinator_tpu.ops import batch_assign as ba

        snap = self.snapshot
        n = snap.capacity
        k = min(self.cand_k, n)
        method = self.cand_method
        if method == "auto":
            method = "approx" if jax.default_backend() == "tpu" else "exact"
        # sharded-by-default: when the solver mesh is active for this
        # capacity, selection/refresh/passes run the shard_map entries
        # (recall-exact selection; bit-identical acceptance) and the
        # state donates in place under its node-axis NamedSharding; the
        # batch capacity must additionally divide over the pods axis
        # (always true for power-of-two axis sizes)
        use_mesh = (self.mesh is not None and snap.solver_sharding_active
                    and self.kit.pods_shardable(batch.capacity))
        if use_mesh:
            method = "sharded"

            def _select(st, b):
                return self._select_scored_sh(
                    st, b, self.config, k=k, spread_bits=self.cand_spread,
                    with_scores=True)
        else:
            def _select(st, b):
                return self._select_scored(
                    st, b, self.config, k=k, spread_bits=self.cand_spread,
                    method=method, with_scores=True)
        refresh_fn = (self._refresh_cands_sh if use_mesh
                      else self._refresh_cands)
        pass1_fn = self._pass1_sh if use_mesh else self._pass1
        meta = self._cand_cache
        cache_ok = (
            meta is not None
            and meta["n"] == n
            and meta["k"] == k
            and meta["spread"] == self.cand_spread
            and meta["method"] == method
            # identity via the OBJECT, not id(): a freed config's address
            # can be reused by its replacement (CPython free lists)
            and meta["cfg"] is self.config
        )
        # consumed exactly once per cache rebuild/refresh — both branches
        # below leave a cache that reflects post-consume state
        dirty_rows = [r for r in snap.consume_candidate_dirty() if r < n]

        path = "full_cold"
        cache = None
        if cache_ok:
            node_frac = len(dirty_rows) / max(len(snap.node_index), 1)
            row_of, specs = meta["row_of"], meta["specs"]
            map_rows = np.zeros(batch.capacity, np.int32)
            map_ok = np.zeros(batch.capacity, bool)
            changed = np.zeros(batch.capacity, bool)
            for i, pod in enumerate(pods):
                j = row_of.get(pod.name)
                if j is not None and specs.get(pod.name) is pod:
                    map_rows[i] = j
                    map_ok[i] = True
                else:
                    changed[i] = True
            dirty_np = np.zeros(n, bool)
            dirty_np[dirty_rows] = True
            dpad = _bucket(max(len(dirty_rows), 1), minimum=64)
            drows = np.zeros(dpad, np.int32)
            drows[: len(dirty_rows)] = dirty_rows
            dvalid = np.zeros(dpad, bool)
            dvalid[: len(dirty_rows)] = True
            aligned, touch = self._align_cands(
                meta["cache"], jnp.asarray(map_rows), jnp.asarray(map_ok),
                jnp.asarray(dirty_np))
            dirty_pods = changed | np.asarray(touch)
            pod_frac = float(dirty_pods.sum()) / max(len(pods), 1)
            metrics.incremental_dirty_fraction.set(
                node_frac, labels={"kind": "nodes"})
            metrics.incremental_dirty_fraction.set(
                pod_frac, labels={"kind": "pods"})
            metrics.incremental_dirty_pods.set(float(dirty_pods.sum()))
            self._last_dirty_node_frac = node_frac
            self._last_dirty_pod_frac = pod_frac
            if max(node_frac, pod_frac) <= self.incremental_dirty_threshold:
                path = "incremental"
                cand_key, cache = refresh_fn(
                    snap.state, batch, self.config, aligned,
                    jnp.asarray(drows), jnp.asarray(dvalid),
                    k=k, spread_bits=self.cand_spread)
                if dirty_pods.any():
                    small, idx = batch.compact(dirty_pods)
                    sk, sn, ss = _select(snap.state, small)
                    rows_pad = np.full(small.capacity, batch.capacity,
                                       np.int32)
                    rows_pad[: len(idx)] = idx
                    cache = self._scatter_cands(
                        cache, jnp.asarray(rows_pad), sk, sn, ss)
            else:
                path = "full_fallback"
        if cache is None:
            ck, cn, cs = _select(snap.state, batch)
            cache = ba.CandidateCache(ck, cn, cs)
        metrics.incremental_solve_total.inc(labels={"path": path})
        # the batch build already computed this round's name→row / spec
        # maps for its own row reuse — share them instead of a third O(P)
        # walk (the driver only runs on non-hinted batches, which always
        # populate _batch_host)
        host = self._batch_host
        self._cand_cache = {
            "cache": cache,
            "row_of": host["row_of"],
            "specs": host["specs"],
            "n": n, "k": k, "spread": self.cand_spread,
            "method": method, "cfg": self.config,
        }
        self.last_solve_path = path

        # gangless gang_assign pass loop, pass 1: over the
        # cached/refreshed candidates.  The pass donates the state it
        # consumes; re-pointing snapshot.state at the returned state
        # keeps the snapshot on LIVE buffers (trace/compile errors —
        # the realistic failure class — raise before any donation
        # executes; an execution-time failure mid-chain is
        # unrecoverable without a sync resync either way).  On any
        # failure the cache is dropped so the next round re-warms
        # instead of trusting un-bookkept state.
        try:
            a, state, quota, est_accum = pass1_fn(
                snap.state, batch, quota, cache.cand_key, cache.cand_node,
                self.config, rounds=self.solve_rounds)
            snap.state = state
        except Exception:
            self._cand_cache = None
            raise
        return {"a": a, "state": state, "quota": quota,
                "est_accum": est_accum, "batch": batch, "k": k,
                "method": method, "use_mesh": use_mesh}

    def _finish_batch_incremental(self, ctx: dict):  # koordlint: guarded-by(self.lock)
        """HOST half of the incremental solve: block on pass 1, then
        run the later passes full-selecting over the COMPACTED leftovers
        (small × N, not P × N) against the est-usage-augmented state —
        identical decisions to the one-call form, dispatch point aside."""
        snap = self.snapshot
        batch = ctx["batch"]
        state, quota, est_accum = ctx["state"], ctx["quota"], ctx["est_accum"]
        k, method, use_mesh = ctx["k"], ctx["method"], ctx["use_mesh"]
        try:
            a_np = np.asarray(self._block_timed(ctx["a"]))
            for _ in range(1, self.gang_passes):
                leftover = np.asarray(batch.valid) & (a_np < 0)
                if not leftover.any():
                    break
                small, idx = batch.compact(leftover)
                if use_mesh:
                    a2, state, quota, est_accum = self._pass2_sh(
                        state, est_accum, small, quota, self.config, k=k,
                        rounds=self.solve_rounds,
                        spread_bits=self.cand_spread)
                else:
                    a2, state, quota, est_accum = self._pass2(
                        state, est_accum, small, quota, self.config, k=k,
                        rounds=self.solve_rounds,
                        spread_bits=self.cand_spread, method=method)
                snap.state = state
                a2_np = np.asarray(self._block_timed(a2))[: len(idx)]
                placed = a2_np >= 0
                if not placed.any():
                    break
                a_np[idx[placed]] = a2_np[placed]
        except Exception:
            self._cand_cache = None
            raise
        return jnp.asarray(a_np), state, quota

    # -- placement explainability (ISSUE 6) ---------------------------------

    # koordlint: guarded-by(self.lock)
    def _record_round_explanations(
        self, pods, result: SchedulingResult, fail_rows: list[int],
        failed_gangs: set[str], total_nodes: int,
    ) -> None:
        """Assemble :class:`PlacementExplanation` records for every pod
        the round left unplaced — solve failures (from the device
        kernel's counts now on the diagnoses), degraded-suspended pods,
        and rejected-gang parkees — then publish the cluster rollups:
        ``unschedulable_pods{reason}`` (top reason per pod),
        ``filter_reject_fraction{reason}``, and the flight recorder's
        ``top_unschedulable`` summary."""
        from koordinator_tpu.ops import explain as ex
        from koordinator_tpu.scheduler.explanation import PlacementExplanation

        explanations: list[PlacementExplanation] = []
        for i in fail_rows:
            pod = pods[i]
            if pod.name.startswith(RSV_POD_PREFIX):
                # reservation vehicles retry next round; they are not
                # user workloads (mirrors the auditor/tracing exclusion)
                continue
            diag = result.failures.get(pod.name)
            if diag is None:
                continue
            # node_invalid counts PADDED state rows too (padding and
            # removed nodes are the same validity bit) — it would swamp
            # the real reasons, so the served explanation partitions
            # only the LIVE nodes: feasible + sum(reasons) == total
            reasons = {name: count
                       for name, count in (diag.reason_counts or {}).items()
                       if count > 0 and name != "node_invalid"}
            feasible = diag.feasible_nodes
            if (pod.gang is not None and pod.gang in failed_gangs
                    and feasible > 0):
                # nodes were individually feasible; the gang barrier
                # (minMember/rollback) held the placement back
                reasons["gang_barrier"] = feasible
                feasible = 0
            explanations.append(PlacementExplanation(
                pod=pod.name, round=self.round_seq,
                total_nodes=total_nodes, feasible_nodes=feasible,
                reasons=reasons, trace_id=self.pod_trace_id(pod.name),
                quota=pod.quota if diag.quota_rejected else None,
                gang=pod.gang))
        for name in self._last_suspended_names:
            explanations.append(PlacementExplanation(
                pod=name, round=self.round_seq, total_nodes=total_nodes,
                feasible_nodes=0,
                reasons={"degraded_suspended": total_nodes},
                trace_id=self.pod_trace_id(name),
                gang=getattr(self.pending.get(name), "gang", None)))
        for name in self._last_gang_rejected_names:
            explanations.append(PlacementExplanation(
                pod=name, round=self.round_seq, total_nodes=total_nodes,
                feasible_nodes=0, reasons={"gang_barrier": total_nodes},
                trace_id=self.pod_trace_id(name),
                gang=getattr(self.pending.get(name), "gang", None)))

        top: dict[str, int] = {}
        reason_sums: dict[str, int] = {}
        for exp in explanations:
            self.explain_ring.record(exp)
            reason = exp.top_reason()
            if reason is not None:
                top[reason] = top.get(reason, 0) + 1
            for name, count in exp.reasons.items():
                reason_sums[name] = reason_sums.get(name, 0) + count
        self._last_unschedulable_top = dict(
            sorted(top.items(), key=lambda kv: (-kv[1], kv[0])))
        # republish EVERY reason each round so a cleared reason reads 0
        # instead of its last nonzero value lingering on the dashboard
        for name in ex.REASON_NAMES:
            metrics.unschedulable_pods.set(
                float(top.get(name, 0)), labels={"reason": name})
        if explanations and total_nodes:
            denom = len(explanations) * total_nodes
            for name, total in reason_sums.items():
                metrics.filter_reject_fraction.observe(
                    total / denom, labels={"reason": name})

    def pod_explanation(self, name: str):
        """Latest retained :class:`PlacementExplanation` for a pod."""
        return self.explain_ring.get(name)

    def explain_candidates(self, name: str, k: int = 5) -> list[dict] | None:
        """Per-term score decomposition (ops/explain.decompose_scores) of
        a pod's top-k candidate nodes — or, for a bound pod, its winning
        node — against CURRENT state.  On-demand debug surface: one
        small (1, N) score pass, no hot-path cost.  None = unknown pod.
        """
        from koordinator_tpu.ops import explain as ex
        from koordinator_tpu.ops.assignment import score_pods

        with self.lock:
            pod = self.pending.get(name)
            bound = self.bound.get(name)
            if pod is None and bound is None:
                return None
            self.snapshot.flush()
            state = self.snapshot.state

            def decompose(batch, node_rows: np.ndarray) -> list[dict]:
                cand = jnp.asarray(node_rows[None, :].astype(np.int32))
                terms = {t: np.asarray(v)[0]
                         for t, v in ex.decompose_scores(
                             state, batch, self.config, cand).items()}
                return [
                    {"node": self.snapshot.node_name(int(r)) or str(int(r)),
                     "score": int(terms["total"][j]),
                     "terms": {t: int(v[j]) for t, v in terms.items()
                               if t != "total"}}
                    for j, r in enumerate(node_rows)
                ]

            if pod is not None:
                batch = PodBatch.build(
                    pod.requests[None].astype(np.int32),
                    priority=np.array([pod.priority], np.int32),
                    feasible=self.snapshot.feasibility_row(pod)[None],
                    node_capacity=self.snapshot.capacity, capacity=16,
                )
                scores, feasible = score_pods(state, batch, self.config)
                row = np.asarray(scores[0])
                masked = np.where(np.asarray(feasible[0]), row, -1)
                order = np.argsort(-masked, kind="stable")[:max(k, 1)]
                order = order[masked[order] >= 0]
                if order.size == 0:
                    return []
                return decompose(batch, order)
            row_idx = self.snapshot.node_index.get(bound.node)
            if row_idx is None:
                return []
            batch = PodBatch.build(
                bound.requests[None].astype(np.int32),
                node_capacity=self.snapshot.capacity, capacity=16)
            out = decompose(batch, np.array([row_idx], np.int32))
            out[0]["winner"] = True
            return out

    # koordlint: guarded-by(self.lock)
    def _commit_bind(
        self, pod: PodSpec, node: str, result: SchedulingResult,
        charge_quota: bool = True,
        reservation: str | None = None,
        rsv_drawn: np.ndarray | None = None,
        rsv_generation: int = 0,
    ) -> None:
        """Shared bind bookkeeping: assignments, bound registry, quota used.

        ``charge_quota=False`` converts a nomination whose quota charge is
        already on the tree (``_nomination_assume``)."""
        commit_t0 = time.perf_counter()
        result.assignments[pod.name] = node
        if self.pending.pop(pod.name, None) is not None:
            self._pending_rev += 1
        self.nominations.pop(pod.name, None)
        self._nomination_gen.pop(pod.name, None)
        self.bound[pod.name] = BoundPod(
            name=pod.name, node=node, requests=pod.requests,
            priority=pod.priority, quota=pod.quota,
            non_preemptible=pod.non_preemptible,
            labels=pod.labels, gang=pod.gang,
            reservation=reservation, rsv_drawn=rsv_drawn,
            rsv_generation=rsv_generation,
            node_generation=self.snapshot.node_generation.get(node, 0),
        )
        if charge_quota:
            self._charge_quota_used(pod, sign=1)
        self._allocate_fine_grained(pod, node)
        # bind marker in the POD's trace (parented to its enqueue span,
        # linked to the round's trace by attribute), and the trace
        # annotation the deployment shell carries onto the bound pod
        # object — the koordlet's reconciler joins the trace from it,
        # the way the reference propagates through patched annotations.
        # AFTER _allocate_fine_grained: that call replaces the pod's
        # resource_status entry wholesale.
        ctx = self.pod_traces.pop(pod.name, None)
        if ctx is not None:
            sp = tracing.TRACER.start_span(
                "scheduler.bind", service="scheduler", parent=ctx,
                attributes={"pod": pod.name, "node": node,
                            "round": self.round_seq,
                            "round_trace_id": tracing.current_trace_id()})
            sp.end()
            self.resource_status.setdefault(pod.name, {})[
                tracing.TRACE_ANNOTATION] = sp.context().to_annotation()
        if self.bind_fn is not None:
            self.bind_fn(pod.name, node)
        # success side of ScheduleExplanation/auditor lifecycle lives here so
        # nominated binds (Nominated phase, before _active_pods) clear their
        # stale failure explanations too
        if self.explanations is not None:
            self.explanations.delete(pod.name)
        if self.auditor is not None:
            self.auditor.record(pod.gang or pod.name, "ScheduleSuccess", node)
        if journey.LEDGER.enabled:
            round_t0 = self._journey_round_t0
            journey.LEDGER.record_bind_batch(
                self.tenant, (pod,),
                round_start_perf=(round_t0 if round_t0 is not None
                                  else commit_t0),
                commit_perf=commit_t0)

    # koordlint: guarded-by(self.lock)
    def _commit_bind_batch(self, binds: list[tuple[PodSpec, str]],
                           result: SchedulingResult) -> None:
        """One batched commit for a round's whole bind set (ISSUE 19).

        Sequential ``_commit_bind`` re-walks the quota tree and bumps
        ``q.used`` once per pod — at 1k binds/round that is 1k int64
        adds plus 1k dict probes of pure host time inside the round's
        critical section.  Here the per-pod registry bookkeeping stays a
        (cheap) loop, but quota recharge is grouped: one
        ``np.sum(stack)`` per (quota, non_preemptible) group and ONE
        ``q.used`` update per touched quota node.  Integer adds commute
        and int64 never rounds, so the grouped totals are bit-identical
        to the sequential charges (the reserve_batch precedent).  Per-
        pod surfaces — ``resource_status``, trace stamping, fine-grained
        allocation, explanations, auditor records — are preserved
        exactly, in bind order.  ``bind_batch_fn`` (when set) receives
        the whole set once: the seam for one deltasync emission per
        round instead of one frame per pod."""
        if not binds:
            return
        commit_t0 = time.perf_counter()
        # phase 1: registry bookkeeping (assignments / pending /
        # nominations / bound), in order — later same-name entries win
        # exactly as they would sequentially
        for pod, node in binds:
            result.assignments[pod.name] = node
            if self.pending.pop(pod.name, None) is not None:
                self._pending_rev += 1
            self.nominations.pop(pod.name, None)
            self._nomination_gen.pop(pod.name, None)
            self.bound[pod.name] = BoundPod(
                name=pod.name, node=node, requests=pod.requests,
                priority=pod.priority, quota=pod.quota,
                non_preemptible=pod.non_preemptible,
                labels=pod.labels, gang=pod.gang,
                node_generation=self.snapshot.node_generation.get(node, 0),
            )
        # phase 2: grouped quota recharge — one used-vector update per
        # touched quota node instead of one per pod
        if self.quota_tree is not None:
            groups: dict[tuple[str, bool], list[np.ndarray]] = {}
            for pod, _node in binds:
                if pod.quota and pod.quota in self.quota_tree.nodes:
                    groups.setdefault(
                        (pod.quota, bool(pod.non_preemptible)), []
                    ).append(pod.requests)
            for (quota, non_preemptible), reqs in groups.items():
                q = self.quota_tree.nodes[quota]
                total = np.sum(np.stack(reqs).astype(np.int64), axis=0)
                q.used = q.used + total
                if non_preemptible:
                    q.non_preemptible_used = (
                        q.non_preemptible_used + total)
        # phase 3: per-pod surfaces, in bind order (fine-grained state
        # mutates per node+pod; trace stamping must follow it because
        # _allocate_fine_grained replaces resource_status wholesale)
        for pod, node in binds:
            self._allocate_fine_grained(pod, node)
            ctx = self.pod_traces.pop(pod.name, None)
            if ctx is not None:
                sp = tracing.TRACER.start_span(
                    "scheduler.bind", service="scheduler", parent=ctx,
                    attributes={"pod": pod.name, "node": node,
                                "round": self.round_seq,
                                "round_trace_id":
                                    tracing.current_trace_id()})
                sp.end()
                self.resource_status.setdefault(pod.name, {})[
                    tracing.TRACE_ANNOTATION] = (
                        sp.context().to_annotation())
            if self.explanations is not None:
                self.explanations.delete(pod.name)
            if self.auditor is not None:
                self.auditor.record(pod.gang or pod.name,
                                    "ScheduleSuccess", node)
        if self.bind_batch_fn is not None:
            self.bind_batch_fn([(pod.name, node) for pod, node in binds])
        elif self.bind_fn is not None:
            for pod, node in binds:
                self.bind_fn(pod.name, node)
        # journey ledger (ISSUE 20): one vectorized pass records the whole
        # round's e2e + stage latencies.  Pure observation — runs after
        # every decision and quota charge above is already committed, so
        # KOORD_JOURNEY=0 is bit-identical on scheduling outcomes.
        if journey.LEDGER.enabled:
            round_t0 = self._journey_round_t0
            journey.LEDGER.record_bind_batch(
                self.tenant, [pod for pod, _node in binds],
                round_start_perf=(round_t0 if round_t0 is not None
                                  else commit_t0),
                commit_perf=commit_t0)

    def _allocate_fine_grained(self, pod: PodSpec, node: str) -> None:
        """Reserve-phase fine-grained allocation (nodenumaresource Reserve:
        resource_manager.go:357 allocateCPUSet; deviceshare Reserve +
        PreBind device-allocated annotation).  An allocation that cannot be
        satisfied degrades to the shared pool / no pinning rather than
        failing an already-committed bind — the koordlet share-pool hook
        still applies its per-QoS cpuset."""
        from koordinator_tpu.api.qos import QoSClass
        from koordinator_tpu.api.resources import ResourceDim

        status: dict[str, dict] = {}
        if (self.cpu_manager is not None
                and int(pod.qos) in (int(QoSClass.LSR), int(QoSClass.LSE))
                and self.cpu_manager.node(node) is not None):
            from koordinator_tpu.scheduler.cpu_manager import (
                EXCLUSIVE_PCPU_LEVEL,
            )

            cores = int(pod.requests[ResourceDim.CPU]) // 1000
            if cores >= 1:
                cpus = self.cpu_manager.allocate(
                    node, pod.name, cores,
                    exclusive_policy=EXCLUSIVE_PCPU_LEVEL)
                if cpus is not None:
                    status["resource-status"] = (
                        self.cpu_manager.resource_status(node, pod.name))
        if self.device_manager is not None:
            gpu = int(pod.requests[ResourceDim.GPU])
            gpu_mem = int(pod.requests[ResourceDim.GPU_MEMORY])
            if gpu > 0 and self.device_manager.state("gpu") is not None:
                minors = self.device_manager.allocate(
                    "gpu", node, pod.name, gpu, gpu_mem)
                if minors is not None:
                    status["device-allocated"] = (
                        self.device_manager.device_allocated_annotation(
                            node, pod.name))
                    self._adapt_device_plugin(pod, node, status)
        if status:
            self.resource_status[pod.name] = status

    def _adapt_device_plugin(self, pod: PodSpec, node: str,
                             status: dict) -> None:
        """DevicePluginAdaption gate: translate the allocation into vendor
        device-plugin annotations (device_plugin_adapter.go:100).  The
        reference fails PreBind on an adapt error; this seam is documented
        degrade-not-fail (see _allocate_fine_grained), so an inexpressible
        allocation records the error on the status instead and skips the
        vendor dialect — operators see it, the bind proceeds unpinned."""
        from koordinator_tpu.features import SCHEDULER_GATES

        if not SCHEDULER_GATES.enabled("DevicePluginAdaption"):
            return
        from koordinator_tpu.scheduler import device_plugin_adapter as dpa

        spec = self.snapshot.node_specs.get(node)
        node_labels = spec.labels if spec is not None else {}
        locks = self._device_node_locks.setdefault(node, {})
        try:
            # the adapter's default wall clock, NOT self.clock: the
            # annotations are UnixNano timestamps consumed by EXTERNAL
            # vendor plugins comparing against time.Now() — a monotonic
            # scheduler clock would stamp the year 1970
            res = dpa.adapt_for_device_plugin(
                status["device-allocated"],
                gpu_vendor=node_labels.get(dpa.LABEL_GPU_VENDOR, ""),
                gpu_model=node_labels.get(dpa.LABEL_GPU_MODEL, ""),
                pod_labels=pod.labels,
                node_annotations=locks,
            )
        except dpa.AdaptError as e:
            status["device-plugin"] = {"error": str(e)}
            return
        if dpa.LABEL_HAMI_VGPU_NODE in res.pod_labels:
            res.pod_labels[dpa.LABEL_HAMI_VGPU_NODE] = node
        locks.update(res.node_annotations)
        status["device-plugin"] = {
            "annotations": res.pod_annotations,
            "labels": res.pod_labels,
            "node_annotations": dict(res.node_annotations),
        }

    def clear_device_node_lock(self, node: str, key: str) -> None:
        """The vendor device plugin finished a pod and removed its node
        lock annotation (device_plugin_adapter.go: 'will automatically
        remove it after allocation of a pod')."""
        self._device_node_locks.get(node, {}).pop(key, None)

    def _release_fine_grained(self, pod_name: str, node: str) -> None:
        if self.cpu_manager is not None:
            self.cpu_manager.release(node, pod_name)
        if self.device_manager is not None:
            self.device_manager.release(node, pod_name)
        self.resource_status.pop(pod_name, None)

    def _charge_quota_used(self, pod: PodSpec, sign: int) -> None:
        if (pod.quota and self.quota_tree is not None
                and pod.quota in self.quota_tree.nodes):
            q = self.quota_tree.nodes[pod.quota]
            q.used = q.used + sign * pod.requests.astype(np.int64)
            if pod.non_preemptible:
                q.non_preemptible_used = (
                    q.non_preemptible_used + sign * pod.requests.astype(np.int64)
                )

    # -- nominated pods (nominatedNodeName semantics) -----------------------

    def _nomination_assume(self, pod: PodSpec, node: str) -> None:
        """Account a nomination: reserve the node AND charge the quota, so
        neither the victims' freed capacity nor the quota headroom can be
        double-spent before the preemptor binds."""
        self.snapshot.reserve(node, pod.requests)
        self._charge_quota_used(pod, sign=1)
        self.nominations[pod.name] = node
        self._nomination_gen[pod.name] = (
            self.snapshot.node_generation.get(node, 0))

    def _nomination_release(self, pod: PodSpec) -> None:
        """Undo :meth:`_nomination_assume` (stale nomination / pod deleted)."""
        node = self.nominations.pop(pod.name, None)
        if node is None:
            return
        self.snapshot.unreserve_instance(
            node, pod.requests, self._nomination_gen.pop(pod.name, 0))
        self._charge_quota_used(pod, sign=-1)

    def _nominated_fit(self, pod: PodSpec, row: int) -> bool:
        """Re-run Filter for a nominated pod on its nominated node (with the
        pod's own assumed accounting already released by the caller)."""
        from koordinator_tpu.ops.assignment import score_pods

        batch = PodBatch.build(
            pod.requests[None].astype(np.int32),
            priority=np.array([pod.priority], np.int32),
            feasible=self.snapshot.feasibility_row(pod)[None],
            node_capacity=self.snapshot.capacity, capacity=16,
        )
        _, feasible = score_pods(self.snapshot.state, batch, self.config)
        if not bool(feasible[0, row]):
            return False
        if pod.quota is not None and self.quota_tree is not None:
            return self.quota_tree.admits(
                pod.quota, pod.requests, pod.non_preemptible
            )
        return True

    def _resolve_nominations(self, result: SchedulingResult) -> None:
        """Fast-path for preemptors nominated in an earlier round.

        A nominated pod's resources were assumed (node reservation + quota
        charge) at preemption time, so nothing else could take the victims'
        freed capacity.  Here each pod's own assumption is briefly released,
        Filter re-runs on the nominated node, and the pod either binds there
        or loses the nomination and rejoins the batch with its full feasible
        set.  Gang members resolve all-or-nothing: if any member's nominated
        node stopped being viable, the whole gang's nominations are released
        (partial gang binds below minMember are never produced)."""
        groups: dict[str, list[PodSpec]] = {}
        for name in list(self.nominations):
            pod = self.pending.get(name)
            if pod is None:
                self.nominations.pop(name, None)  # pod gone; nothing assumed
                self._nomination_gen.pop(name, None)
                continue
            groups.setdefault(pod.gang or f"\0solo:{name}", []).append(pod)

        for members in groups.values():
            assumed: list[tuple[PodSpec, str]] = []  # re-assumed, not yet bound
            ok = True
            for pod in members:
                node_name = self.nominations[pod.name]
                row = self.snapshot.node_index.get(node_name)
                # release own assumption, re-check with peers' still held
                self._nomination_release(pod)
                if row is None or not self._nominated_fit(pod, row):
                    ok = False
                    break
                self._nomination_assume(pod, node_name)
                assumed.append((pod, node_name))
            if ok:
                for pod, node_name in assumed:
                    # assumption becomes the bind accounting (no re-reserve,
                    # no re-charge)
                    self._commit_bind(pod, node_name, result,
                                      charge_quota=False)
            else:
                # release every member still holding an assumption (the
                # failed member already released; release() no-ops for it)
                for pod in members:
                    self._nomination_release(pod)

    # -- preemption (PostFilter) --------------------------------------------

    def _pdb_arrays(self) -> tuple[list[str], np.ndarray]:
        names = sorted(self.pdbs)
        allowed = np.array(
            [self.pdbs[n].allowed for n in names], np.int32
        ).reshape(-1)
        if not names:
            allowed = np.zeros(1, np.int32)  # padded budget row, never matched
        return names, allowed

    def _build_scheduled(self, quota_index: dict[str, int]):
        """Flatten self.bound into a ScheduledPods tensor (+ name order)."""
        from koordinator_tpu.ops.preemption import ScheduledPods

        pdb_names, _ = self._pdb_arrays()
        pdb_index = {n: i for i, n in enumerate(pdb_names)}
        names = sorted(self.bound)
        v = len(names)
        req = np.zeros((max(v, 1), self.snapshot.dims), np.int32)
        node = np.full(max(v, 1), -1, np.int32)
        pri = np.zeros(max(v, 1), np.int32)
        qid = np.full(max(v, 1), -1, np.int32)
        nonp = np.zeros(max(v, 1), bool)
        pdb = np.full(max(v, 1), -1, np.int32)
        for i, name in enumerate(names):
            bp = self.bound[name]
            row = self.snapshot.node_index.get(bp.node)
            if (row is not None
                    and self.snapshot.node_generation.get(bp.node, 0)
                    != bp.node_generation):
                # bound to a PREVIOUS instance of a re-added node: its
                # capacity was never charged to the current row, so it
                # must not be a victim candidate — "evicting" it would
                # let the solve assume freed capacity that was never
                # there and nominate a preemptor past allocatable
                # (caught by the preemption churn suite)
                row = None
            req[i] = bp.requests
            node[i] = row if row is not None else -1
            pri[i] = bp.priority
            if bp.quota is not None and bp.quota in quota_index:
                qid[i] = quota_index[bp.quota]
            nonp[i] = bp.non_preemptible
            # a pod matching several PDBs carries its most-constraining one
            # (smallest remaining budget) for the violating classification;
            # eviction decrements every matching budget (commit path)
            matches = [
                pi for pn, pi in pdb_index.items()
                if self.pdbs[pn].matches(bp.labels)
            ]
            if matches:
                pdb[i] = min(
                    matches, key=lambda pi: self.pdbs[pdb_names[pi]].allowed
                )
        return ScheduledPods.build(
            req[:v] if v else req[:0], node[:v] if v else node[:0],
            priority=pri[:v] if v else None, quota_id=qid[:v] if v else None,
            non_preemptible=nonp[:v] if v else None,
            pdb_id=pdb[:v] if v else None,
        ), names

    def _quota_headroom(self, quota_name: str | None) -> np.ndarray | None:
        """(R,) runtime - used for the pod's quota (postFilterState.usedLimit
        semantics) — victims must bring used back under it."""
        if quota_name is None or self.quota_tree is None:
            return None
        qnode = self.quota_tree.nodes.get(quota_name)
        if qnode is None:
            return None
        from koordinator_tpu.quota.admission import HEADROOM_CLAMP
        from koordinator_tpu.quota.tree import UNBOUNDED

        # dims outside the quota's declared max are unchecked (quotav1.Mask
        # semantics): give them unbounded headroom so a fair-share deficit on
        # an undeclared dim cannot block preemption that admission allows
        hr = np.where(
            qnode.max != UNBOUNDED, qnode.runtime - qnode.used, HEADROOM_CLAMP
        )
        return np.clip(hr, -HEADROOM_CLAMP, HEADROOM_CLAMP).astype(np.int32)

    def _run_preemption(self, pods, batch, result: SchedulingResult) -> None:
        """PostFilter: for each still-unschedulable pod, find a min-cost
        victim set, evict, and nominate.  Gang members preempt all-or-nothing
        (job-level preemption, coscheduling preemption.go:206); quota-rejected
        pods preempt within their quota (elasticquota preempt.go:111)."""
        # reserve-pods don't preempt here: their nominate/bind flow is the
        # reservation lifecycle, not the pod nomination machine
        failed = [p for p in pods if p.name in result.failures
                  and not p.name.startswith(RSV_POD_PREFIX)]
        if not failed:
            return
        quota_index = (
            {} if self.quota_tree is None
            else {n: i for i, n in enumerate(sorted(self.quota_tree.nodes))}
        )
        sched, bound_names = self._build_scheduled(quota_index)
        if not bound_names:
            return
        pdb_names, pdb_allowed = self._pdb_arrays()
        pdb_allowed = jnp.asarray(pdb_allowed)
        state = self.snapshot.state

        # group failed pods: gangs preempt as a job, others individually,
        # highest-priority first
        failed.sort(key=lambda p: (-p.priority, p.creation, p.name))
        jobs: list[list[PodSpec]] = []
        seen_gangs: set[str] = set()
        for p in failed:
            if p.gang is not None:
                if p.gang in seen_gangs:
                    continue
                seen_gangs.add(p.gang)
                jobs.append([q for q in failed if q.gang == p.gang])
            else:
                jobs.append([p])

        # per-round budget (mirror rsv_prepass_cap): a quota-starved 50k
        # queue must not become 50k dry-runs in one round.  Highest-priority
        # jobs first (already sorted); a gang that does not fit the
        # remaining budget is skipped whole (all-or-nothing), the rest
        # retry next round.  Applied BEFORE the O(F·N) mask expansion below
        # so the per-round host cost is O(cap·N), not O(F·N).
        budget = self.preempt_cap
        capped: list[list[PodSpec]] = []
        for job in jobs:
            if budget <= 0:
                break
            if any(p.preemption_policy == "Never" for p in job):
                continue
            if len(job) > budget:
                continue
            capped.append(job)
            budget -= len(job)
        if not capped:
            return

        pod_row = {p.name: i for i, p in enumerate(pods)}
        # expand feasibility + threshold masks only for the capped
        # preemptors (O(cap·N), not O(P·N) — preemption is the rare path)
        from koordinator_tpu.ops import scoring
        from koordinator_tpu.ops.assignment import _threshold_mask

        fail_rows = np.array(
            sorted({pod_row[p.name] for job in capped for p in job}),
            np.int32,
        )
        feasible_np = {
            r: np.asarray(batch.feasible_row(state, int(r)))
            for r in fail_rows
        }
        # preemption cannot lower measured usage, so nodes over the loadaware
        # threshold stay infeasible (the dry-run re-runs Filter in the
        # reference, which includes the usage-threshold check)
        pod_est = scoring.estimate_pod_usage_by_band(
            batch.requests[jnp.asarray(fail_rows)],
            self.config.estimator_factors, self.config.estimator_defaults,
        )
        thr = np.asarray(_threshold_mask(
            self.config, state.node_usage, state.node_agg_usage,
            state.node_allocatable, pod_est,
        ))
        thr_np = {int(r): thr[i] for i, r in enumerate(fail_rows)}

        i = 0
        while i < len(capped):
            job = capped[i]
            if len(job) == 1 and job[0].gang is None:
                # run of consecutive single-pod preemptors: one jitted
                # chain dispatch instead of one dispatch per pod
                chunk: list[PodSpec] = []
                while (i < len(capped) and len(capped[i]) == 1
                       and capped[i][0].gang is None
                       and len(chunk) < self.preempt_chunk):
                    chunk.append(capped[i][0])
                    i += 1
                state, sched, pdb_allowed = self._run_preempt_chunk(
                    chunk, state, sched, pdb_allowed, quota_index,
                    bound_names, pod_row, feasible_np, thr_np, result,
                )
                continue
            i += 1
            state, sched, pdb_allowed = self._run_preempt_job(
                job, state, sched, pdb_allowed, quota_index, bound_names,
                pod_row, feasible_np, thr_np, result,
            )

    def _run_preempt_job(
        self, job, state, sched, pdb_allowed, quota_index, bound_names,
        pod_row, feasible_np, thr_np, result,
    ):
        """One gang (or host-path single) job: sequential dry-runs with
        all-or-nothing commit.  Returns the evolved (state, sched, pdb)."""
        from koordinator_tpu.quota.admission import HEADROOM_CLAMP

        cur_state, cur_sched, cur_pdb = state, sched, pdb_allowed
        outcomes = []
        # quota consumed/freed by this job's earlier members (nominated
        # requests minus same-quota victims): the tree is only charged at
        # commit, so the dry run must not double-spend headroom
        job_assumed: dict[str, np.ndarray] = {}
        for p in job:
            quota_hr = self._quota_headroom(p.quota)
            same_quota = quota_hr is not None
            if same_quota and p.quota in job_assumed:
                quota_hr = np.clip(
                    quota_hr.astype(np.int64) - job_assumed[p.quota],
                    -HEADROOM_CLAMP, HEADROOM_CLAMP,
                ).astype(np.int32)
            qid = quota_index.get(p.quota, -1) if p.quota else -1
            # feasibility row from the solve batch (affinity/selector)
            # ANDed with the usage-threshold filter; preemption fixes
            # neither affinity nor measured-load failures
            row = feasible_np[pod_row[p.name]] & thr_np[pod_row[p.name]]
            out = self._preempt(
                cur_state, cur_sched,
                jnp.asarray(p.requests.astype(np.int32)),
                jnp.int32(p.priority), jnp.int32(qid),
                jnp.asarray(row), cur_pdb,
                quota_headroom=(
                    jnp.asarray(quota_hr) if same_quota else None
                ),
                same_quota_only=same_quota,
            )
            node_row = int(out.node)
            if node_row < 0:
                # all-or-nothing: drop the job's tentative evictions
                return state, sched, pdb_allowed
            victim_names = [
                bound_names[v]
                for v in np.flatnonzero(np.asarray(out.victims))
            ]
            outcomes.append((p, int(out.node), victim_names))
            if p.quota is not None:
                delta = p.requests.astype(np.int64)
                for vname in victim_names:
                    bp = self.bound[vname]
                    if bp.quota == p.quota:
                        delta = delta - bp.requests.astype(np.int64)
                job_assumed[p.quota] = (
                    job_assumed.get(p.quota, 0) + delta
                )
            cur_state, cur_sched, cur_pdb = out.state, out.sched, out.pdb_allowed

        # commit: evict victims, record nominations, update diagnosis.
        # Later jobs see this job's evictions + nominations; bound_names
        # order is unchanged (evicted rows are invalid in sched).
        for p, node_row, victim_names in outcomes:
            self._commit_one_preemption(p, node_row, victim_names, result)
        return cur_state, cur_sched, cur_pdb

    def _run_preempt_chunk(
        self, chunk, state, sched, pdb_allowed, quota_index, bound_names,
        pod_row, feasible_np, thr_np, result,
    ):
        """A run of single-pod preemptors in ONE jitted chain dispatch
        (ops/preemption.preempt_chain).  Semantics match calling
        :meth:`_run_preempt_job` per pod; the chunk is padded to
        ``preempt_chunk`` rows so chain lengths don't retrace."""
        from koordinator_tpu.quota.admission import HEADROOM_CLAMP

        c = self.preempt_chunk
        r = chunk[0].requests.shape[0]
        n = self.snapshot.capacity
        reqs = np.zeros((c, r), np.int32)
        pris = np.zeros(c, np.int32)
        qids = np.full(c, -1, np.int32)
        feas = np.zeros((c, n), bool)
        same_q = np.zeros(c, bool)
        active = np.zeros(c, bool)
        # (Q, R) runtime - used per quota row; rows the chunk never touches
        # stay fully open
        q_rows = max(len(quota_index), 1)
        base_hr = np.full((q_rows, r), HEADROOM_CLAMP, np.int32)
        for name, qi in quota_index.items():
            hr = self._quota_headroom(name)
            if hr is not None:
                base_hr[qi] = hr
        for j, p in enumerate(chunk):
            reqs[j] = p.requests.astype(np.int32)
            pris[j] = p.priority
            qids[j] = quota_index.get(p.quota, -1) if p.quota else -1
            feas[j] = feasible_np[pod_row[p.name]] & thr_np[pod_row[p.name]]
            same_q[j] = self._quota_headroom(p.quota) is not None
            active[j] = True

        out = self._preempt_chain(
            state, sched, jnp.asarray(reqs), jnp.asarray(pris),
            jnp.asarray(qids), jnp.asarray(feas), jnp.asarray(same_q),
            jnp.asarray(active), pdb_allowed, jnp.asarray(base_hr),
        )
        nodes = np.asarray(out.node)
        victims = np.asarray(out.victims)
        for j, p in enumerate(chunk):
            if nodes[j] < 0:
                continue
            victim_names = [
                bound_names[v] for v in np.flatnonzero(victims[j])
            ]
            self._commit_one_preemption(p, int(nodes[j]), victim_names,
                                        result)
        return out.state, out.sched, out.pdb_allowed

    def _commit_one_preemption(
        self, p, node_row: int, victim_names: list[str], result,
    ) -> None:
        """Host commit for one successful preemptor: evict victims (free
        capacity, release quota, charge PDBs, call preempt_fn), assume the
        preemptor's nomination, and record it on the round result."""
        node_name = self.snapshot.node_name(node_row)
        for vname in victim_names:
            bp = self.bound.pop(vname)
            # shared freeing: fine-grained allocations and
            # reservation-aware unreserve (a reservation-backed
            # victim returns its drawn vector, not raw capacity)
            self._release_bound_capacity(bp)
            if bp.quota and self.quota_tree is not None \
                    and bp.quota in self.quota_tree.nodes:
                q = self.quota_tree.nodes[bp.quota]
                q.used = q.used - bp.requests.astype(np.int64)
                if bp.non_preemptible:
                    q.non_preemptible_used = (
                        q.non_preemptible_used
                        - bp.requests.astype(np.int64)
                    )
            # every matching PDB pays for the disruption
            for pn in self.pdbs:
                if self.pdbs[pn].matches(bp.labels):
                    self.pdbs[pn].allowed -= 1
            if self.preempt_fn is not None:
                self.preempt_fn(vname, p.name)
        # assume the preemptor's resources (node reservation + quota
        # charge): nothing may claim the freed capacity or headroom
        # before the preemptor binds or the nomination is cleared
        self._nomination_assume(p, node_name)
        result.nominations[p.name] = (node_name, victim_names)
        diag = result.failures.get(p.name)
        if diag is not None:
            diag.preempt_node = node_name
            diag.preempt_victims = victim_names
