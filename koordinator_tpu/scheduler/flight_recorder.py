"""Round flight recorder: a ring buffer of per-round telemetry.

Every scheduling round leaves one :class:`RoundRecord` — solve path,
dirty fractions, per-phase timings, the wall-vs-device solve split,
degraded/staleness state, shed/suspension counts, and the round's
trace_id — so "why was round 48213 slow" is answered from one artifact
instead of five binaries' logs.  Slow or degraded rounds are dumped to
the scheduler log automatically (bounded: one line per offending round)
and counted in ``round_flight_dumps_total``; the whole ring is
queryable at ``GET /debug/rounds`` on the scheduler's HTTP gateway and
debug service, and ``tools/trace_dump.py --slowest-round`` prints the
same fields from a JSONL trace export (the round span carries them as
attributes).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from collections import deque
from typing import Optional

from koordinator_tpu import metrics

logger = logging.getLogger("koordinator_tpu.scheduler")


@dataclasses.dataclass
class RoundRecord:
    """One round's flight data (all host-side scalars; JSON-able)."""

    round: int
    trace_id: str
    start_time: float            # wall clock, cross-process comparable
    duration_s: float
    solver: str                  # greedy | batch
    solve_path: str              # incremental | full_* | degraded | none
    pods: int                    # pods the round solved over
    placed: int
    failed: int
    suspended: int               # held out by degraded-mode admission
    degraded: bool
    staleness_s: Optional[float]  # sync-feed age at round start
    dirty_node_frac: float
    dirty_pod_frac: float
    solve_wall_s: float          # the Solve phase's wall time
    solve_device_s: float        # time blocked on jitted solve results
    phase_s: dict[str, float] = dataclasses.field(default_factory=dict)
    #: cumulative solve-shed counter at round end (deltas between
    #: records localize WHICH round the sheds landed in)
    sheds_total: float = 0.0
    #: {top reject reason -> unplaced pod count} from the round's
    #: placement-explanation rollup (ops/explain taxonomy); empty when
    #: nothing failed or explain accounting is off — a slow/degraded
    #: dump then answers "slow doing WHAT" and "failing WHY" in one line
    top_unschedulable: dict[str, int] = dataclasses.field(
        default_factory=dict)
    #: tenancy attribution (ISSUE 11): which tenant's round this record
    #: covers ("" = untenanted scheduler), and which pipeline half —
    #: "round" for a serial round, "solve"/"commit" for the two records
    #: a pipelined round leaves, so /debug/rounds and soak_report
    #: attribute a slow half to a tenant
    tenant: str = ""
    half: str = "round"
    #: solve-quality mode of the scheduler (ISSUE 13): off | lp | auto —
    #: and, when the round solved on the LP path, the rounding-iteration
    #: count it used (0 on greedy rounds), so a slow quality round's
    #: dump answers "how many LP phases did that cost" in place
    quality_mode: str = "off"
    quality_iterations: int = 0
    #: critical-path join (ISSUE 18): the timeline observatory's verdict
    #: for the cycle this round ran in — which cause dominated the
    #: cycle's covering chain and for how long — annotated after the
    #: cycle reconstructs (cycle_seq = -1 until then / with the
    #: recorder disabled), so a slow round's record names what the
    #: WHOLE cycle was actually spending its wall on
    cycle_seq: int = -1
    cycle_critical_cause: str = ""
    cycle_critical_seconds: float = 0.0
    dump_reason: Optional[str] = None   # slow | degraded when dumped

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)


class FlightRecorder:
    """Bounded ring of RoundRecords with automatic slow/degraded dumps.

    Single-writer (records are appended under the scheduler's round
    lock); readers take list() snapshots, which is safe against a
    concurrent append on CPython deques.
    """

    def __init__(self, capacity: int = 256,
                 slow_threshold_s: float = 1.0):
        self.capacity = capacity
        #: rounds slower than this dump their record (mirrors the
        #: monitor's slow-round warning threshold by default)
        self.slow_threshold_s = slow_threshold_s
        self.records: deque[RoundRecord] = deque(maxlen=capacity)
        self.dumps = 0
        self.overwrites = 0

    def _dump(self, rec: RoundRecord, reason: str) -> None:
        """The dump side effects — ONE home for the counter label, the
        bookkeeping, and the log line, shared by the automatic
        slow/degraded path and external triggers."""
        if rec.dump_reason is None:
            rec.dump_reason = reason
        self.dumps += 1
        metrics.round_flight_dumps.inc(labels={"reason": reason})
        logger.warning("round flight record (%s): %s", reason,
                       json.dumps(rec.to_doc(), default=str))

    def record(self, rec: RoundRecord) -> None:
        reason = None
        if rec.duration_s > self.slow_threshold_s:
            reason = "slow"
        elif rec.degraded:
            reason = "degraded"
        if reason is not None:
            self._dump(rec, reason)
        if len(self.records) == self.capacity:
            # the ring is about to evict its oldest record — dump
            # reasons are counted above, but silent eviction was
            # invisible until this counter (ISSUE 5 satellite)
            self.overwrites += 1
            metrics.round_flight_overwritten.inc()
        self.records.append(rec)

    def dump_now(self, reason: str) -> bool:
        """Dump the most recent record on an external trigger (the SLO
        burn-rate engine's fast-burn breach) with the trigger's reason
        (e.g. ``slo:scheduling_latency_p99``).  False when no round has
        been recorded yet."""
        rec = self.last()
        if rec is None:
            return False
        self._dump(rec, reason)
        return True

    def annotate_round(self, round_seq: int, tenant: str,
                       **fields) -> int:
        """Back-annotate every in-ring record of one round (both halves
        of a pipelined round) with cycle-level fields — the timeline
        observatory's critical-path verdict lands here AFTER the cycle
        reconstructs.  Records already dumped to the log carry
        cycle_seq=-1; the ring (and any later dump) carries the join.
        Returns the number of records annotated."""
        n = 0
        for rec in list(self.records):
            if rec.round == round_seq and rec.tenant == tenant:
                for key, value in fields.items():
                    setattr(rec, key, value)
                n += 1
        return n

    def snapshot(self, limit: Optional[int] = None) -> list[dict]:
        """Newest-first record docs (the /debug/rounds body)."""
        records = list(self.records)[::-1]
        if limit is not None and limit >= 0:
            records = records[:limit]
        return [r.to_doc() for r in records]

    def slowest(self) -> Optional[dict]:
        records = list(self.records)
        if not records:
            return None
        return max(records, key=lambda r: r.duration_s).to_doc()

    def last(self) -> Optional[RoundRecord]:
        records = list(self.records)
        return records[-1] if records else None
