"""Vendor device-plugin adaptation for the bind path.

The reference's DevicePluginAdapter
(`pkg/scheduler/plugins/deviceshare/device_plugin_adapter.go:100`)
translates koord-scheduler's fine-grained device allocation into the
annotation/label dialects third-party device plugins understand, so those
plugins can act as allocators without modification.  Kubelet never shows a
device plugin the pod manifest, so vendors key off bind timestamps,
node-lock annotations, and vendor-specific allocation annotations.

This module is that translation layer for the repo's bind flow: input is
the repo's device-allocated payload
(``DeviceManager.device_allocated_annotation`` —
``{type: [{"minor", "resources": {"core", "memory"}}]}``), output is an
:class:`AdaptResult` of pod annotations/labels and node annotations (the
node lock).  Gated behind the ``DevicePluginAdaption`` feature
(features.py), matching the reference gate.

Memory units: the repo's device tensors carry memory in MiB
(ops/deviceshare.py contract), so the allocation payload's ``memory`` is
MiB here; vendor units convert from that (Cambricon 256 MiB vMemory
units, MetaX 1 MiB vRAM units — `device_plugin_adapter.go:83,90`, which
divide byte quantities by the same unit sizes).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable, Mapping, Optional

SCHEDULING_PREFIX = "scheduling.koordinator.sh"
ANNOTATION_BIND_TIMESTAMP = f"{SCHEDULING_PREFIX}/bind-timestamp"
ANNOTATION_GPU_MINORS = f"{SCHEDULING_PREFIX}/gpu-minors"

# vendor dialects (device_plugin_adapter.go:46-90)
ANNOTATION_PREDICATE_TIME = "predicate-time"
ANNOTATION_HUAWEI_NPU_CORE = "huawei.com/npu-core"
ANNOTATION_HUAWEI_ASCEND_310P = "huawei.com/Ascend310P"
ANNOTATION_CAMBRICON_ASSIGNED = "CAMBRICON_DSMLU_ASSIGHED"
ANNOTATION_CAMBRICON_PROFILE = "CAMBRICON_DSMLU_PROFILE"
ANNOTATION_CAMBRICON_LOCK = "cambricon.com/dsmlu.lock"
ANNOTATION_METAX_ALLOCATED = "metax-tech.com/gpu-devices-allocated"
ANNOTATION_HAMI_LOCK = "hami.io/mutex.lock"
LABEL_GPU_ISOLATION_PROVIDER = f"{SCHEDULING_PREFIX}/gpu-isolation-provider"
LABEL_HAMI_VGPU_NODE = "hami.io/vgpu-node"
ISOLATION_PROVIDER_HAMI_CORE = "hami-core"

#: node labels carrying the GPU vendor/model (the reference reads the same
#: pair off Device-CR labels, extension/device_share.go:63)
LABEL_GPU_VENDOR = "node.koordinator.sh/gpu-vendor"
LABEL_GPU_MODEL = "node.koordinator.sh/gpu-model"

GPU_VENDOR_HUAWEI = "huawei"
GPU_VENDOR_CAMBRICON = "cambricon"
GPU_VENDOR_METAX = "metax"

CAMBRICON_VMEMORY_UNIT_MIB = 256
METAX_VRAM_UNIT_MIB = 1

#: node-lock staleness bound (device_plugin_adapter.go:97 nodeLockTimeout)
NODE_LOCK_TIMEOUT_SECONDS = 5 * 60.0


class AdaptError(ValueError):
    """Allocation cannot be expressed in the vendor's dialect."""


@dataclasses.dataclass
class AdaptResult:
    """Annotations/labels the bind flow must apply."""

    pod_annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    pod_labels: dict[str, str] = dataclasses.field(default_factory=dict)
    #: node lock annotation (key -> timestamp str); the vendor's plugin
    #: removes it after it processes the pod
    node_annotations: dict[str, str] = dataclasses.field(default_factory=dict)


def _minors_str(allocs: list[dict], prefix: str = "") -> str:
    return ",".join(f"{prefix}{int(a['minor'])}" for a in allocs)


def adapt_for_device_plugin(
    allocation: Mapping[str, list[dict]],
    gpu_vendor: str = "",
    gpu_model: str = "",
    pod_labels: Optional[Mapping[str, str]] = None,
    node_annotations: Optional[Mapping[str, str]] = None,
    clock: Callable[[], float] = time.time,
) -> AdaptResult:
    """Translate one pod's device allocation for vendor device plugins.

    ``allocation`` is the repo's device-allocated payload; ``gpu_vendor`` /
    ``gpu_model`` come from the node's Device CR labels.  Raises
    :class:`AdaptError` when the allocation cannot be expressed (the
    reference fails the PreBind the same way) — including a held,
    non-stale node lock for vendors that require one.
    """
    out = AdaptResult()
    now_ns = int(clock() * 1e9)
    # general adapter: every pod gets the bind timestamp
    out.pod_annotations[ANNOTATION_BIND_TIMESTAMP] = str(now_ns)

    gpu = allocation.get("gpu")
    if not gpu:
        return out

    # general GPU adapter: minor list + HAMi vGPU node pin
    out.pod_annotations[ANNOTATION_GPU_MINORS] = _minors_str(gpu)
    labels = dict(pod_labels or {})
    if labels.get(LABEL_GPU_ISOLATION_PROVIDER) == ISOLATION_PROVIDER_HAMI_CORE:
        out.pod_labels[LABEL_HAMI_VGPU_NODE] = ""  # bind fills node name

    if gpu_vendor == GPU_VENDOR_HUAWEI:
        out.pod_annotations[ANNOTATION_PREDICATE_TIME] = str(now_ns)
        if gpu_model == "Ascend-310P3-300I-DUO":
            out.pod_annotations[ANNOTATION_HUAWEI_ASCEND_310P] = (
                _minors_str(gpu, "Ascend310P-"))
        else:
            template = gpu[0].get("template", "")
            if template:  # vNPU: one shared-resource template
                out.pod_annotations[ANNOTATION_HUAWEI_NPU_CORE] = (
                    f"{int(gpu[0]['minor'])}-{template}")
            else:
                out.pod_annotations[ANNOTATION_HUAWEI_NPU_CORE] = (
                    _minors_str(gpu))
    elif gpu_vendor == GPU_VENDOR_CAMBRICON:
        if len(gpu) > 1:
            raise AdaptError(
                "multiple gpu share is not supported on device side")
        res = gpu[0].get("resources", {})
        core = res.get("core")
        if core is None:
            raise AdaptError("gpu core resource is required")
        memory = int(res.get("memory", 0))
        if memory < CAMBRICON_VMEMORY_UNIT_MIB:
            raise AdaptError(
                f"gpu memory must not be less than "
                f"{CAMBRICON_VMEMORY_UNIT_MIB} MiB")
        _check_node_lock(node_annotations, ANNOTATION_CAMBRICON_LOCK,
                         clock())
        out.pod_annotations[ANNOTATION_CAMBRICON_ASSIGNED] = "false"
        out.pod_annotations[ANNOTATION_CAMBRICON_PROFILE] = (
            f"{int(gpu[0]['minor'])}_{int(core)}"
            f"_{memory // CAMBRICON_VMEMORY_UNIT_MIB}")
        out.node_annotations[ANNOTATION_CAMBRICON_LOCK] = str(now_ns)
    elif gpu_vendor == GPU_VENDOR_METAX:
        requests = []
        for a in gpu:
            res = a.get("resources", {})
            core = res.get("core")
            if core is None:
                raise AdaptError("gpu core resource is required")
            memory = int(res.get("memory", 0))
            if memory < METAX_VRAM_UNIT_MIB:
                raise AdaptError(
                    f"gpu memory must not be less than "
                    f"{METAX_VRAM_UNIT_MIB} MiB")
            requests.append({
                "uuid": str(a.get("id", a["minor"])),
                "compute": int(core),
                "vRam": memory // METAX_VRAM_UNIT_MIB,
            })
        _check_node_lock(node_annotations, ANNOTATION_HAMI_LOCK, clock())
        out.pod_annotations[ANNOTATION_METAX_ALLOCATED] = json.dumps(
            [requests], separators=(",", ":"))
        out.node_annotations[ANNOTATION_HAMI_LOCK] = str(now_ns)
    return out


def _check_node_lock(node_annotations: Optional[Mapping[str, str]],
                     key: str, now: float) -> None:
    """Vendors whose plugins cannot disambiguate concurrent pods take a
    node-level lock annotation; a held, non-stale lock rejects the bind
    (the plugin removes the lock when it finishes).  Stale locks
    (> NODE_LOCK_TIMEOUT_SECONDS) are overwritten, matching lockNode's
    timeout recovery."""
    held = (node_annotations or {}).get(key)
    if not held:
        return
    try:
        held_ns = int(held)
    except ValueError:
        return  # corrupt lock value: treat as stale
    if now - held_ns / 1e9 < NODE_LOCK_TIMEOUT_SECONDS:
        raise AdaptError(f"node lock {key} is held")
