"""Scheduler startup sync barrier (reference: ``cmd/koord-scheduler/app/
sync_barrier.go:70-229`` — after a restart, write a barrier marker through
the apiserver and refuse to schedule until the informer stream has replayed
past it, so decisions never run on a stale cache).

Abstracted over the event source: ``mark()`` stamps a monotonically
increasing barrier version into the source (the reference patches a pod);
``observed_version()`` reports the latest version the informer has seen.
``wait_until_synced`` polls with a deadline. Pass the barrier to
``Scheduler(barrier=...)`` — rounds no-op until it opens.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class SyncBarrier:
    def __init__(
        self,
        mark: Callable[[], int],
        observed_version: Callable[[], int],
        timeout_seconds: float = 30.0,
        clock=time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._mark = mark
        self._observed = observed_version
        self.timeout_seconds = timeout_seconds
        self.clock = clock
        self.sleep = sleep
        self._barrier_version: Optional[int] = None
        self.synced = False

    def start(self) -> int:
        """Stamp the barrier; scheduling stays gated until it is observed."""
        self._barrier_version = self._mark()
        self.synced = False
        return self._barrier_version

    def check(self) -> bool:
        """Non-blocking: has the informer replayed past the barrier?"""
        if self.synced:
            return True
        if self._barrier_version is None:
            return True  # never started: no gate (fresh process, empty cache)
        if self._observed() >= self._barrier_version:
            self.synced = True
        return self.synced

    def wait_until_synced(self, poll_interval: float = 0.05) -> bool:
        """Blocking wait with the configured timeout. On timeout the barrier
        OPENS anyway (the reference logs and proceeds — scheduling forever
        beats never scheduling) but returns False so callers can record it."""
        deadline = self.clock() + self.timeout_seconds
        while not self.check():
            if self.clock() >= deadline:
                self.synced = True
                return False
            self.sleep(poll_interval)
        return True
