"""Scheduler debug/services API (reference: ``frameworkext/services/
services.go:32-51`` — a gin HTTP server where every plugin mounts endpoints
under ``/apis/v1/plugins/<name>``; plus ``frameworkext/debug.go`` runtime
flag toggles).

Transport-agnostic core: a route registry mapping paths to callables that
return JSON-able objects; ``serve_forever`` optionally exposes it over the
stdlib HTTP server. Built-in routes cover the reference's debug surface:
nodes, pending pods, gangs, quotas, last-round diagnosis, metrics scrape,
and the runtime-togglable top-N score dump.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

import numpy as np


class DebugApiError(Exception):
    """A debug route failing with a SPECIFIC status (gate closed, busy)
    instead of the blanket 500 — both HTTP surfaces map it verbatim."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def debug_rounds_body(scheduler, size: int) -> dict:
    """The /debug/rounds payload — ONE builder shared by DebugService
    and the HTTP gateway so the two surfaces cannot drift."""
    return {"rounds": scheduler.flight_recorder.snapshot(size)}


def debug_slo_body(scheduler) -> dict:
    """The /debug/slo payload (shared by DebugService and the HTTP
    gateway): the SLO burn-rate engine's latest evaluation."""
    monitor = getattr(scheduler, "slo_monitor", None)
    if monitor is None:
        raise DebugApiError(501, "no SLO monitor attached "
                                 "(scheduler binaries only)")
    # copy: report() may return the monitor's shared internal dict (the
    # background sampler's _last_report); inserting into it would race
    # concurrent scrapes and pollute the stored report
    body = dict(monitor.report())
    # sharded-solve introspection rides the SLO document: shard count,
    # per-device bytes, recompiles per (fn, shape@mesh) bucket
    report = getattr(scheduler, "sharding_report", None)
    if report is not None:
        body["sharding"] = report()
    return body


def debug_steady_body(scheduler, params: dict | None = None) -> dict:
    """The /debug/steady payload (shared by DebugService and the HTTP
    gateway): the long-horizon trend engine's per-series
    steady/drifting/leaking verdicts, joined to the SLO engine's breach
    state — "is this thing leaking or drifting under churn" as one
    document.

    ``?window=N`` overrides the evaluation window (seconds).  When an
    SLO monitor is attached its sampler runs first, so an on-demand
    request (no background cadence) still evaluates current telemetry;
    repeated scrapes build the window organically like /debug/slo."""
    engine = getattr(scheduler, "trend_engine", None)
    if engine is None:
        raise DebugApiError(501, "no trend engine attached "
                                 "(scheduler binaries only)")
    window = (params or {}).get("window")
    if window is not None:
        try:
            window = float(window)
        except (TypeError, ValueError):
            raise DebugApiError(400, "window must be a number") from None
        if not (window > 0):   # also rejects NaN
            raise DebugApiError(400, "window must be positive")
    monitor = getattr(scheduler, "slo_monitor", None)
    if monitor is not None:
        monitor.sample_once()
    body = engine.evaluate(window_s=window)
    if monitor is not None:
        slo = monitor.report()
        body["slo_breached"] = slo.get("breached", [])
        body["slo_breaches_total"] = {
            d["name"]: d["breaches_total"] for d in slo.get("slos", [])}
    return body


def debug_forecast_body(scheduler, params: dict | None = None) -> dict:
    """The /debug/forecast payload (shared by DebugService and the HTTP
    gateway): the forecast plane's horizon policy, prediction-error
    stats, and per-node predicted peaks — plus the scheduler's mode and
    the last admission-reserve fraction.

    ``?nodes=N`` bounds the per-node section (default 64, ordered by
    predicted CPU peak — the nodes the plane is about to act on).
    Typed 501 without a plane (forecast mode off / non-scheduler
    binaries), 400 on a malformed bound."""
    plane = getattr(scheduler, "forecast_plane", None)
    if plane is None:
        raise DebugApiError(501, "no forecast plane attached "
                                 "(--forecast-mode off or non-scheduler "
                                 "binary)")
    nodes = (params or {}).get("nodes", 64)
    try:
        nodes = int(nodes)
    except (TypeError, ValueError):
        raise DebugApiError(400, "nodes must be an integer") from None
    if nodes < 0:
        raise DebugApiError(400, "nodes must be >= 0")
    snapshot = getattr(scheduler, "snapshot", None)
    row_names = ({row: name for name, row in snapshot.node_index.items()}
                 if snapshot is not None else None)
    # the reserve fraction rides the plane's report (per-plane state —
    # a shared global gauge would cross tenants' planes)
    body = plane.report(max_nodes=nodes, row_names=row_names)
    body["mode"] = getattr(scheduler, "forecast_mode", "off")
    from koordinator_tpu import metrics

    body["evictions_prestaged_total"] = sum(
        v for _, v in metrics.forecast_evictions_prestaged.items())
    return body


def debug_tenants_body(scheduler) -> dict:
    """The /debug/tenants payload (shared by DebugService and the HTTP
    gateway): the multi-tenant front-end's rollup — per-tenant
    weight/share/credit, queue depth, degraded/suspension state, last
    solve path, plus the cycle's dispatch mode and host-wait fraction.

    Served through ANY tenant's scheduler (each per-tenant Scheduler
    carries a ``tenant_front`` back-reference) or directly through a
    :class:`~koordinator_tpu.scheduler.tenancy.TenantScheduler`; a
    single-tenant scheduler answers a typed 501."""
    front = (scheduler if hasattr(scheduler, "tenants_report")
             else getattr(scheduler, "tenant_front", None))
    if front is None:
        raise DebugApiError(501, "no tenancy front-end attached "
                                 "(multi-tenant schedulers only)")
    return front.tenants_report()


def debug_timeline_body(scheduler, params: dict | None = None) -> dict:
    """The /debug/timeline?cycles=N payload (shared by DebugService and
    the HTTP gateway): the critical-path observatory's reconstructed
    cycle gantts, newest first — typed segments, the wall-time
    attribution by cause (sums to 1.0 with an explicit unattributed
    residual), device-idle intervals derived from the dispatch/block
    edges, and the cycle's critical-path chain + dominant cause.

    The recorder is process-wide (``timeline.RECORDER``): a
    multi-tenant front's cycles and an untenanted scheduler's
    one-round cycles land in the same ring.  400 on a malformed
    bound; an empty ``cycles`` list (not an error) means no cycle has
    run with the recorder armed (e.g. ``--no-timeline``)."""
    from koordinator_tpu import timeline

    cycles = (params or {}).get("cycles", 8)
    try:
        cycles = int(cycles)
    except (TypeError, ValueError):
        raise DebugApiError(400, "cycles must be an integer") from None
    if cycles < 1:
        raise DebugApiError(400, "cycles must be >= 1")
    return {
        "enabled": timeline.RECORDER.enabled,
        "causes": list(timeline.ATTRIBUTION_CAUSES),
        "cycles": timeline.RECORDER.cycles(cycles),
    }


def debug_latency_body(scheduler, params: dict | None = None) -> dict:
    """The /debug/latency?tenant= payload (shared by DebugService and the
    HTTP gateway): the pod-journey ledger's per-(tenant, qos, stage)
    latency quantile table — TRUE per-pod arrival->bind e2e quantiles
    plus the stage decomposition (ingest, queue_wait, solve, commit),
    each from a mergeable log-bucketed sketch with <=1% relative error.

    501 when the ledger is off (``KOORD_JOURNEY=0`` / ``--no-journey``);
    400 (typed) on a tenant filter that matches no recorded series."""
    from koordinator_tpu import journey

    if not journey.LEDGER.enabled:
        raise DebugApiError(501, "journey ledger disabled "
                                 "(KOORD_JOURNEY=0 / --no-journey)")
    tenant = (params or {}).get("tenant")
    if tenant is not None:
        known = journey.LEDGER.tenants()
        if tenant not in known:
            raise DebugApiError(
                400, f"unknown tenant {tenant!r} "
                     f"(recorded: {', '.join(known) or 'none yet'})")
    doc = journey.LEDGER.report(tenant=tenant)
    doc["stages"] = list(journey.STAGES)
    doc["pending"] = journey.LEDGER.pending_count()
    return doc


def debug_profile_body(scheduler, seconds) -> dict:
    """The /debug/profile?seconds=N payload: an on-demand jax.profiler
    capture.  403 while the gate is off (the default), 409 while a
    capture is in flight — shared by both HTTP surfaces."""
    from koordinator_tpu.ops.introspection import ProfileBusy, ProfileDisabled

    capture = getattr(scheduler, "profile_capture", None)
    if capture is None:
        raise DebugApiError(403, "profiling endpoint disabled (enable at "
                                 "assembly with --enable-profile-endpoint)")
    import math

    try:
        seconds_f = float(seconds)
    except (TypeError, ValueError):
        raise DebugApiError(400, "seconds must be a number") from None
    if not math.isfinite(seconds_f):
        # nan survives float() and min/max clamping — it would start a
        # trace and then die in sleep() as a blanket 500
        raise DebugApiError(400, "seconds must be finite")
    try:
        return capture.capture(seconds_f)
    except ProfileDisabled as e:
        raise DebugApiError(403, str(e)) from None
    except ProfileBusy as e:
        raise DebugApiError(409, str(e)) from None


def debug_trace_body(scheduler, pod: str) -> dict:
    """The /debug/trace/<pod> payload; shared by DebugService and the
    HTTP gateway.  ``pod`` may arrive percent-encoded from either HTTP
    surface.  Unknown pods raise a TYPED 404 :class:`DebugApiError` so
    both surfaces serve the same status + body (previously the gateway
    and DebugService each hand-rolled the mapping)."""
    from urllib.parse import unquote

    from koordinator_tpu import tracing

    pod = unquote(pod)
    trace_id = scheduler.pod_trace_id(pod)
    if trace_id is None:
        raise DebugApiError(404, f"no trace recorded for pod {pod!r}")
    return {"pod": pod, "trace_id": trace_id,
            "spans": [s.to_doc() for s in
                      tracing.TRACER.spans_for_trace(trace_id)]}


def debug_explain_body(scheduler, pod: str,
                       params: dict | None = None) -> dict:
    """The /debug/explain/<pod> payload (shared by DebugService and the
    HTTP gateway): the pod's retained :class:`~koordinator_tpu.scheduler.
    explanation.PlacementExplanation` (reject-reason node counts joined
    to its trace_id and round) plus an on-demand per-term score
    decomposition of its current winning/top-k candidate nodes.

    ``?candidates=0`` skips the decomposition: it runs a (1, N) score
    pass under the scheduler's round lock, which a single operator query
    wants inline but a many-pod polling loop (tools/explain_summary.py)
    must not serialize rounds behind.

    Typed statuses: 404 for a pod the scheduler has never seen (no
    explanation retained, not pending, not bound) and for reserve-pods
    (``rsv::`` placement vehicles are not user workloads — query the
    reservation via /apis/v1/reservations instead)."""
    from urllib.parse import unquote

    from koordinator_tpu.scheduler.scheduler import RSV_POD_PREFIX

    want_candidates = str((params or {}).get("candidates", "1")
                          ).strip().lower() not in ("0", "false", "no",
                                                    "off")
    pod = unquote(pod)
    if pod.startswith(RSV_POD_PREFIX):
        raise DebugApiError(
            404, f"reserve-pod {pod!r} is a placement vehicle, not a "
                 "workload; its reservation is served at "
                 "/apis/v1/reservations")
    explanation = scheduler.pod_explanation(pod)
    pending = pod in scheduler.pending
    bound = scheduler.bound.get(pod)
    if explanation is None and not pending and bound is None:
        raise DebugApiError(
            404, f"no explanation recorded for pod {pod!r}")
    body = {
        "pod": pod,
        "status": ("bound" if bound is not None
                   else "pending" if pending else "gone"),
        "trace_id": scheduler.pod_trace_id(pod),
        "explanation": explanation.to_doc() if explanation else None,
        "explain_enabled": scheduler.explain,
    }
    if bound is not None:
        body["node"] = bound.node
    if want_candidates:
        candidates = scheduler.explain_candidates(pod)
        if candidates is not None:
            body["candidates"] = candidates
    return body


class DebugService:
    def __init__(self, scheduler=None):
        self.scheduler = scheduler
        self._routes: dict[str, Callable[[dict], object]] = {}
        self._prefix_routes: dict[str, Callable[[str, dict], object]] = {}
        self._lock = threading.Lock()
        #: debug.go: runtime-togglable top-N score dumping (0 = off)
        self.dump_top_n_scores = 0
        self.last_scores: Optional[dict] = None
        if scheduler is not None:
            self._register_builtin()

    # -- registry (plugins mount under /apis/v1/plugins/<name>/...) ----------

    def register(self, path: str, handler: Callable[[dict], object]) -> None:
        with self._lock:
            self._routes[path.rstrip("/")] = handler

    def register_plugin(self, plugin_name: str, sub_path: str,
                        handler: Callable[[dict], object]) -> None:
        self.register(f"/apis/v1/plugins/{plugin_name}/{sub_path.lstrip('/')}",
                      handler)

    def register_prefix(self, prefix: str,
                        handler: Callable[[str, dict], object]) -> None:
        """Parameterized route: ``handler(rest, params)`` receives the
        path remainder after ``prefix`` (e.g. the pod name under
        ``/debug/trace/``)."""
        with self._lock:
            self._prefix_routes[prefix] = handler

    def handle(self, path: str, params: dict | None = None) -> tuple[int, object]:
        """(status, body) — the transport-agnostic request entry."""
        with self._lock:
            handler = self._routes.get(path.rstrip("/"))
            prefix_routes = dict(self._prefix_routes)
        if handler is None:
            for prefix, ph in prefix_routes.items():
                if path.startswith(prefix) and len(path) > len(prefix):
                    rest = path[len(prefix):]
                    try:
                        return 200, ph(rest, params or {})
                    except DebugApiError as e:
                        return e.status, {"error": e.message}
                    except KeyError as e:
                        return 404, {"error": str(e)}
                    except Exception as e:  # noqa: BLE001
                        return 500, {"error": str(e)}
            return 404, {"error": f"no route {path}"}
        try:
            return 200, handler(params or {})
        except DebugApiError as e:
            return e.status, {"error": e.message}
        except Exception as e:  # noqa: BLE001 — debug API must not crash
            return 500, {"error": str(e)}

    # -- built-in routes ------------------------------------------------------

    def _register_builtin(self) -> None:
        self.register("/apis/v1/nodes", self._nodes)
        self.register("/apis/v1/pods", self._pods)
        self.register("/apis/v1/gangs", self._gangs)
        self.register("/apis/v1/quotas", self._quotas)
        self.register("/apis/v1/reservations", self._reservations)
        self.register("/apis/v1/resource-status", self._resource_status)
        self.register("/apis/v1/diagnosis", self._diagnosis)
        self.register("/apis/v1/__debug/scores", self._scores)
        self.register("/apis/v1/__debug/set-top-n", self._set_top_n)
        self.register("/metrics", self._metrics)
        self.register("/debug/rounds", self._rounds)
        self.register("/debug/slo", self._slo)
        self.register("/debug/steady", self._steady)
        self.register("/debug/forecast", self._forecast)
        self.register("/debug/tenants", self._tenants)
        self.register("/debug/timeline", self._timeline)
        self.register("/debug/latency", self._latency)
        self.register("/debug/profile", self._profile)
        self.register_prefix("/debug/trace/", self._trace)
        self.register_prefix("/debug/explain/", self._explain)

    def _nodes(self, params: dict) -> object:
        snapshot = self.scheduler.snapshot
        out = []
        for name, row in snapshot.node_index.items():
            spec = snapshot.node_specs.get(name)
            out.append({
                "name": name, "row": row,
                "allocatable": (
                    np.asarray(spec.allocatable).tolist() if spec else None
                ),
            })
        return out

    def _pods(self, params: dict) -> object:
        return [
            {"name": p.name, "priority": p.priority, "gang": p.gang,
             "quota": p.quota, "requests": np.asarray(p.requests).tolist()}
            for p in self.scheduler.pending.values()
        ]

    def _gangs(self, params: dict) -> object:
        return [
            {"name": g.name, "min_member": g.min_member,
             "rejected": g.rejected,
             "first_failure": g.first_failure}
            for g in self.scheduler.gangs.values()
        ]

    def _quotas(self, params: dict) -> object:
        tree = self.scheduler.quota_tree
        if tree is None:
            return []
        return [
            {"name": name,
             "min": np.asarray(node.min).tolist(),
             "max": np.asarray(node.max).tolist(),
             "used": np.asarray(node.used).tolist(),
             "runtime": np.asarray(tree.runtime_of(name)).tolist()}
            for name, node in tree.nodes.items()
        ]

    def _resource_status(self, params: dict) -> object:
        """Fine-grained allocation annotations per bound pod (cpuset
        resource-status + device-allocated payloads)."""
        return dict(self.scheduler.resource_status)

    def _reservations(self, params: dict) -> object:
        return [
            {"name": s.name, "phase": s.phase.value, "node": s.node,
             "requests": np.asarray(s.requests).tolist(),
             "allocated": (np.asarray(s.allocated).tolist()
                           if s.allocated is not None else None),
             "owner_pods": list(s.owner_pods),
             "allocate_once": s.allocate_once}
            for s in self.scheduler.reservations.specs()
        ]

    def _diagnosis(self, params: dict) -> object:
        import dataclasses as _dc

        result = getattr(self.scheduler, "last_result", None)
        if result is None:
            return {}
        return {
            pod: _dc.asdict(d) if _dc.is_dataclass(d) else str(d)
            for pod, d in result.failures.items()
        }

    def _scores(self, params: dict) -> object:
        return self.last_scores or {}

    def _set_top_n(self, params: dict) -> object:
        self.dump_top_n_scores = int(params.get("n", 0))
        return {"dump_top_n_scores": self.dump_top_n_scores}

    def _metrics(self, params: dict) -> object:
        from koordinator_tpu import metrics

        # aggregate exposition (all component registries): the same
        # scrape body the HTTP gateway serves, so both debug surfaces
        # agree; ?openmetrics=1 adds histogram exemplars
        return metrics.expose_all(openmetrics=metrics.parse_openmetrics_flag(
            params.get("openmetrics", "0")))

    def _rounds(self, params: dict) -> object:
        """The round flight recorder, newest first (?size=N)."""
        return debug_rounds_body(self.scheduler,
                                 int(params.get("size", 32)))

    def _slo(self, params: dict) -> object:
        """The SLO burn-rate engine's evaluation (/debug/slo)."""
        return debug_slo_body(self.scheduler)

    def _steady(self, params: dict) -> object:
        """The trend engine's steady-state verdicts (/debug/steady,
        ?window=N overrides the evaluation window)."""
        return debug_steady_body(self.scheduler, params)

    def _forecast(self, params: dict) -> object:
        """The forecast plane's horizon/error/per-node-peak document
        (/debug/forecast, ?nodes=N bounds the node section); typed 501
        without a plane."""
        return debug_forecast_body(self.scheduler, params)

    def _tenants(self, params: dict) -> object:
        """The multi-tenant rollup (/debug/tenants): per-tenant
        shares/queues/degraded state + cycle dispatch mode; typed 501
        without a tenancy front-end."""
        return debug_tenants_body(self.scheduler)

    def _timeline(self, params: dict) -> object:
        """The critical-path observatory's reconstructed cycle gantts
        (/debug/timeline?cycles=N): segments, wall-time attribution,
        device-idle intervals, critical path per cycle."""
        return debug_timeline_body(self.scheduler, params)

    def _latency(self, params: dict) -> object:
        """Pod-journey latency quantile table (/debug/latency?tenant=):
        per-(tenant, qos, stage) e2e + stage sketches; 501 when the
        ledger is off, typed 400 on an unknown tenant filter."""
        return debug_latency_body(self.scheduler, params)

    def _profile(self, params: dict) -> object:
        """On-demand jax.profiler capture (/debug/profile?seconds=N);
        403 unless the gate was enabled at assembly."""
        return debug_profile_body(self.scheduler,
                                  params.get("seconds", 1.0))

    def _trace(self, pod: str, params: dict) -> object:
        """Recent spans of one pod's trace (/debug/trace/<pod>);
        unknown pods surface the builder's typed 404."""
        return debug_trace_body(self.scheduler, pod)

    def _explain(self, pod: str, params: dict) -> object:
        """One pod's placement explanation (/debug/explain/<pod>):
        reject-reason node counts + candidate score decomposition
        (?candidates=0 skips the decomposition for polling loops)."""
        return debug_explain_body(self.scheduler, pod, params)

    def record_scores(self, pods: list, scores: np.ndarray,
                      node_names: list[str]) -> None:
        """Called by the scheduler after a solve when dumping is on."""
        n = self.dump_top_n_scores
        if n <= 0:
            return
        top = {}
        for i, pod in enumerate(pods):
            row = np.asarray(scores[i])
            order = np.argsort(row)[::-1][:n]
            top[getattr(pod, "name", str(i))] = [
                {"node": node_names[j] if j < len(node_names) else str(j),
                 "score": float(row[j])}
                for j in order
            ]
        self.last_scores = top

    # -- optional stdlib HTTP transport ---------------------------------------

    def serve_forever(self, port: int = 10251):  # pragma: no cover - manual
        from http.server import BaseHTTPRequestHandler, HTTPServer
        from urllib.parse import parse_qsl, urlparse

        service = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                parsed = urlparse(self.path)
                status, body = service.handle(
                    parsed.path, dict(parse_qsl(parsed.query))
                )
                if isinstance(body, str):
                    payload = body.encode()
                    ctype = "text/plain"
                else:
                    payload = json.dumps(body, default=str).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        HTTPServer(("127.0.0.1", port), Handler).serve_forever()
