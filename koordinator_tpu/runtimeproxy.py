"""koord-runtime-proxy: CRI-interposing proxy (reference:
``pkg/runtimeproxy/`` — gRPC service ``apis/runtime/v1alpha1/api.proto:148``
PreRunPodSandboxHook/PreCreateContainerHook/..., dispatcher
``dispatcher/dispatcher.go``, failover store ``store/``).

The legacy path for runtimes without NRI: kubelet's CRI calls pass through
this proxy, which consults registered hook servers before/after forwarding to
the real runtime. Transport here is in-process callables (the gRPC framing is
a deployment detail); semantics preserved:

- **fail-open dispatch**: a hook server error never blocks the CRI call —
  the request passes through unmodified (dispatcher.go behavior).
- **hook response merging**: hook servers return partial updates (labels,
  annotations, cgroup parent, resources, envs) merged into the CRI request.
- **failover store**: pod/container metadata recorded at creation so hooks
  can rebuild context after proxy restart.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Callable, Optional, Protocol


class HookType(enum.Enum):
    PRE_RUN_POD_SANDBOX = "PreRunPodSandbox"
    PRE_CREATE_CONTAINER = "PreCreateContainer"
    PRE_START_CONTAINER = "PreStartContainer"
    POST_START_CONTAINER = "PostStartContainer"
    PRE_UPDATE_CONTAINER_RESOURCES = "PreUpdateContainerResources"
    POST_STOP_POD_SANDBOX = "PostStopPodSandbox"


@dataclasses.dataclass
class HookRequest:
    """The CRI-call context handed to hook servers (api.proto shapes)."""

    pod_meta: dict = dataclasses.field(default_factory=dict)
    container_meta: dict = dataclasses.field(default_factory=dict)
    labels: dict = dataclasses.field(default_factory=dict)
    annotations: dict = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    resources: dict = dataclasses.field(default_factory=dict)
    envs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class HookResponse:
    """Partial updates to merge back into the CRI request."""

    labels: dict = dataclasses.field(default_factory=dict)
    annotations: dict = dataclasses.field(default_factory=dict)
    cgroup_parent: str = ""
    resources: dict = dataclasses.field(default_factory=dict)
    envs: dict = dataclasses.field(default_factory=dict)


class HookServer(Protocol):
    def handle(self, hook: HookType, request: HookRequest) -> Optional[HookResponse]: ...


class Dispatcher:
    """Routes hooks to registered servers, fail-open (dispatcher.go)."""

    def __init__(self):
        self._servers: dict[HookType, list[HookServer]] = {t: [] for t in HookType}
        self._lock = threading.Lock()

    def register(self, server: HookServer, hooks: list[HookType]) -> None:
        with self._lock:
            for hook in hooks:
                self._servers[hook].append(server)

    def dispatch(self, hook: HookType, request: HookRequest) -> HookRequest:
        with self._lock:
            servers = list(self._servers[hook])
        for server in servers:
            try:
                response = server.handle(hook, request)
            except Exception:  # noqa: BLE001 — fail-open by contract
                continue
            if response is None:
                continue
            request.labels.update(response.labels)
            request.annotations.update(response.annotations)
            if response.cgroup_parent:
                request.cgroup_parent = response.cgroup_parent
            request.resources.update(response.resources)
            request.envs.update(response.envs)
        return request


class FailoverStore:
    """Pod/container metadata persisted across proxy restarts (store/)."""

    def __init__(self):
        self._pods: dict[str, HookRequest] = {}
        self._containers: dict[str, HookRequest] = {}
        self._pod_containers: dict[str, set[str]] = {}
        self._lock = threading.Lock()

    def save_pod(self, pod_id: str, request: HookRequest) -> None:
        with self._lock:
            self._pods[pod_id] = request

    def save_container(self, container_id: str, request: HookRequest,
                       pod_id: str = "") -> None:
        with self._lock:
            self._containers[container_id] = request
            if pod_id:
                self._pod_containers.setdefault(pod_id, set()).add(container_id)

    def get_pod(self, pod_id: str) -> Optional[HookRequest]:
        with self._lock:
            return self._pods.get(pod_id)

    def get_container(self, container_id: str) -> Optional[HookRequest]:
        with self._lock:
            return self._containers.get(container_id)

    def delete_pod(self, pod_id: str) -> None:
        with self._lock:
            self._pods.pop(pod_id, None)
            for cid in self._pod_containers.pop(pod_id, set()):
                self._containers.pop(cid, None)

    def delete_container(self, container_id: str) -> None:
        with self._lock:
            self._containers.pop(container_id, None)
            for containers in self._pod_containers.values():
                containers.discard(container_id)


class CRIProxy:
    """The interposer: hook -> forward -> hook for each CRI call
    (server/cri/criserver.go). ``backend`` is the real runtime's method table:
    a dict of callables keyed by CRI method name."""

    def __init__(self, dispatcher: Dispatcher, store: FailoverStore,
                 backend: dict[str, Callable]):
        self.dispatcher = dispatcher
        self.store = store
        self.backend = backend

    def _forward(self, method: str, request: HookRequest):
        fn = self.backend.get(method)
        return fn(request) if fn else None

    def run_pod_sandbox(self, pod_id: str, request: HookRequest):
        request = self.dispatcher.dispatch(HookType.PRE_RUN_POD_SANDBOX, request)
        self.store.save_pod(pod_id, request)
        return self._forward("RunPodSandbox", request)

    def create_container(self, container_id: str, request: HookRequest,
                         pod_id: str = ""):
        request = self.dispatcher.dispatch(HookType.PRE_CREATE_CONTAINER, request)
        self.store.save_container(container_id, request,
                                  pod_id or request.pod_meta.get("uid", ""))
        return self._forward("CreateContainer", request)

    def start_container(self, container_id: str):
        request = self.store.get_container(container_id) or HookRequest()
        request = self.dispatcher.dispatch(HookType.PRE_START_CONTAINER, request)
        result = self._forward("StartContainer", request)
        self.dispatcher.dispatch(HookType.POST_START_CONTAINER, request)
        return result

    def update_container_resources(self, container_id: str, request: HookRequest):
        request = self.dispatcher.dispatch(
            HookType.PRE_UPDATE_CONTAINER_RESOURCES, request
        )
        self.store.save_container(container_id, request)
        return self._forward("UpdateContainerResources", request)

    def remove_container(self, container_id: str):
        request = self.store.get_container(container_id) or HookRequest()
        result = self._forward("RemoveContainer", request)
        self.store.delete_container(container_id)
        return result

    def stop_pod_sandbox(self, pod_id: str):
        request = self.store.get_pod(pod_id) or HookRequest()
        result = self._forward("StopPodSandbox", request)
        self.dispatcher.dispatch(HookType.POST_STOP_POD_SANDBOX, request)
        self.store.delete_pod(pod_id)
        return result
