"""Reactive-vs-predictive A/B: two stacks, one seeded diurnal trace.

The forecast plane's proof harness (the "Predictive Autoscaler"
methodology from PAPERS.md): generate ONE deterministic per-node
diurnal LS-usage trace from a seed, replay it through two control
stacks that differ ONLY in what they act on —

- **reactive**: the colocation formula sees observed HP usage, and the
  only defense against a hot node is the emergency eviction that fires
  AFTER the threshold is crossed (today's behavior);
- **predictive**: the same formula takes the forecast plane's predicted
  peaks (BE capacity shrinks before the ramp), and the proactive
  rebalancer pre-stages reservation-first migrations off nodes FORECAST
  to cross the high threshold —

and score both arms over identical enforcement: SLO-breach minutes
(node-ticks spent above the high threshold), reactive evictions
(emergency kills at crossings), BE occupancy (the colocation win the
whole exercise must not silently destroy), and the predictive arm's
forecast error (predicted vs realized peak).

Everything is seeded and tensorized on the repo's own kernels: the
batch formula is ``manager/noderesource.batch_allocatable``, victim
selection is ``descheduler/lownodeload.select_victims`` over the
forecast tensor, migrations run through the reservation-first
``MigrationController``, and the horizon follows the diurnal trend
slope via ``trend.fit_slope``.  ``tools/soak_report.py --forecast``
prints the scorecard and exits GREEN only when the predictive arm is
no worse on breaches and evictions.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import (
    ArbitrationLimits,
    MigrationController,
)
from koordinator_tpu.forecast.plane import ForecastPlane
from koordinator_tpu.forecast.rebalance import ProactiveRebalancer
from koordinator_tpu.manager import noderesource as formula
from koordinator_tpu.trend import fit_slope

#: padded victim-universe capacity: shape-stable select_victims scans
_POD_CAP = 256


@dataclasses.dataclass(frozen=True)
class ABConfig:
    """One A/B run's knobs — the seed expands everything."""

    seed: int = 0
    nodes: int = 16
    #: diurnal periods replayed (>= 2: the plane learns the first ramp,
    #: the arms diverge on the later ones)
    periods: int = 3
    period_s: float = 480.0
    tick_s: float = 24.0
    node_cpu_milli: int = 16_000
    node_memory_mib: int = 65_536
    be_pod_cpu_milli: int = 1_000
    be_pod_memory_mib: int = 512
    #: BE pods the placement loop admits per node (migrations may stack
    #: more): finite BE demand — a cluster where BE greedily fills
    #: every node to the reclaim line has no underutilized pool for
    #: rebalance to move anything INTO
    be_max_pods_per_node: int = 2
    #: per-node LS base / diurnal amplitude, as capacity fractions.
    #: The fleet is heterogeneous — half the nodes are SPIKY (full
    #: diurnal swing; base + amp stays under the high threshold, so
    #: breaches come from LS + BE, never LS alone) and half are FLAT
    #: (near-constant LS: the underutilized pool proactive rebalance
    #: migrates into)
    base_frac: tuple = (0.20, 0.26)
    amp_frac: tuple = (0.32, 0.38)
    flat_amp_frac: tuple = (0.03, 0.07)
    flat_fraction: float = 0.5
    noise_frac: float = 0.01
    #: LowNodeLoad thresholds (percent of capacity) for enforcement and
    #: the proactive classification
    low_threshold_pct: int = 45
    high_threshold_pct: int = 65
    #: consecutive forecast-overutilized ticks before pre-staging
    anomaly_rounds: int = 2
    #: plane knobs
    half_life_s: float = 240.0
    base_horizon_s: float = 120.0
    refresh_interval_s: float = 40.0

    @property
    def ticks(self) -> int:
        return int(self.periods * self.period_s / self.tick_s)

    @property
    def high_quant(self) -> int:
        return self.node_cpu_milli * self.high_threshold_pct // 100


def generate_ls_trace(cfg: ABConfig) -> np.ndarray:
    """(T, N) int32 per-node LS cpu usage (mcores): a phase-staggered
    diurnal sinusoid plus seeded noise — the SAME array feeds both
    arms, the replay-seed discipline loadgen established."""
    rng = np.random.RandomState(cfg.seed)
    n, t = cfg.nodes, cfg.ticks
    base = rng.uniform(*cfg.base_frac, size=n)
    amp = rng.uniform(*cfg.amp_frac, size=n)
    flat_amp = rng.uniform(*cfg.flat_amp_frac, size=n)
    flat = np.arange(n) < int(round(n * cfg.flat_fraction))
    amp = np.where(flat, flat_amp, amp)
    phase = rng.uniform(0.0, cfg.period_s, size=n)
    times = np.arange(t)[:, None] * cfg.tick_s          # (T, 1)
    ramp = 0.5 * (1.0 + np.sin(
        2.0 * math.pi * (times - phase[None, :]) / cfg.period_s))
    frac = base[None, :] + amp[None, :] * ramp
    frac = frac + rng.normal(0.0, cfg.noise_frac, size=(t, n))
    return np.clip(frac * cfg.node_cpu_milli, 0,
                   cfg.node_cpu_milli).astype(np.int32)


class _Arm:
    """One control stack (reactive or predictive) over the shared
    trace.  All mutable state is per-arm; the trace is read-only."""

    def __init__(self, cfg: ABConfig, predictive: bool):
        self.cfg = cfg
        self.predictive = predictive
        n = cfg.nodes
        self.capacity = np.zeros((n, NUM_RESOURCE_DIMS), np.int32)
        self.capacity[:, ResourceDim.CPU] = cfg.node_cpu_milli
        self.capacity[:, ResourceDim.MEMORY] = cfg.node_memory_mib
        self.valid = np.ones(n, bool)
        #: BE registry: pod name -> node row (usage == request, cpu dim)
        self.be_pods: dict[str, int] = {}
        self._be_seq = 0
        # scorecard accumulators
        self.breach_node_ticks = 0
        self.reactive_evictions = 0
        self.be_pod_ticks = 0
        self.prestaged = 0
        self.migrated = 0
        # the batched colocation formula, compiled once per arm
        self._strategy = formula.ColocationStrategy.default()
        self._batch_fn = jax.jit(formula.batch_allocatable)

        self.plane = None
        self.rebalancer = None
        self.controller = None
        self._move_dest: dict[str, int] = {}
        self._growth_samples: list[tuple[float, float]] = []
        if predictive:
            self.plane = ForecastPlane(
                n, half_life_s=cfg.half_life_s,
                base_horizon_s=cfg.base_horizon_s,
                refresh_interval_s=cfg.refresh_interval_s)
            args = LowNodeLoadArgs.default()
            args = args.replace(
                low_thresholds=args.low_thresholds.at[
                    ResourceDim.CPU].set(cfg.low_threshold_pct),
                high_thresholds=args.high_thresholds.at[
                    ResourceDim.CPU].set(cfg.high_threshold_pct),
                anomaly_rounds=jnp.int32(cfg.anomaly_rounds))
            self.controller = MigrationController(
                limits=ArbitrationLimits(max_migrating_per_node=4,
                                         max_migrating_per_namespace=256),
                reserve_fn=self._reserve, evict_fn=self._evict)
            self.rebalancer = ProactiveRebalancer(
                self.plane, self.controller,
                pods_fn=self._victim_universe,
                node_name_fn=lambda row: f"n{row}",
                args=args)

    # -- BE bookkeeping ------------------------------------------------------

    def be_used(self) -> np.ndarray:
        used = np.zeros(self.cfg.nodes, np.int64)
        for node in self.be_pods.values():
            used[node] += self.cfg.be_pod_cpu_milli
        return used

    def _victim_universe(self):
        names = list(self.be_pods)[:_POD_CAP]
        pod_node = np.full(_POD_CAP, -1, np.int32)
        pod_usage = np.zeros((_POD_CAP, NUM_RESOURCE_DIMS), np.int32)
        priority = np.zeros(_POD_CAP, np.int32)
        evictable = np.zeros(_POD_CAP, bool)
        for i, name in enumerate(names):
            pod_node[i] = self.be_pods[name]
            pod_usage[i, ResourceDim.CPU] = self.cfg.be_pod_cpu_milli
            pod_usage[i, ResourceDim.MEMORY] = self.cfg.be_pod_memory_mib
            evictable[i] = True
        return names, pod_node, pod_usage, priority, evictable

    # -- migration seams (reservation-first) ---------------------------------

    def _reserve(self, job) -> str | None:
        dest = self._move_dest.get(job.name)
        if dest is None:
            return None
        room = (self.cfg.high_quant - self._ls_now[dest]
                - int(self.be_used()[dest]))
        if room < self.cfg.be_pod_cpu_milli:
            return None          # destination filled up since staging
        return f"rsv-{job.name}"

    def _evict(self, job) -> bool:
        dest = self._move_dest.pop(job.name, None)
        if job.pod in self.be_pods and dest is not None:
            self.be_pods[job.pod] = dest
            self.migrated += 1
        if self.rebalancer is not None:
            self.rebalancer.release(job.pod)
        return True

    # -- one control tick ----------------------------------------------------

    def tick(self, t_idx: int, ls_row: np.ndarray) -> None:
        cfg = self.cfg
        n = cfg.nodes
        now = t_idx * cfg.tick_s
        self._ls_now = ls_row
        usage = np.zeros((n, NUM_RESOURCE_DIMS), np.int32)
        usage[:, ResourceDim.CPU] = ls_row

        hp_used_cpu = ls_row.astype(np.int64)
        if self.predictive:
            self.plane.observe(usage, self.valid, now=now)
            self.plane.maybe_refresh(
                now=now, growth_per_hour=self._growth(now, ls_row))
            peaks = self.plane.predicted_host()
            if peaks is not None:
                # predictive colocation: the batch solve takes the
                # PREDICTED peak (never below the observation)
                hp_used_cpu = np.maximum(
                    hp_used_cpu, peaks[:, ResourceDim.CPU].astype(np.int64))

        # -- colocation: batch allocatable from (observed | predicted) peaks
        zeros = jnp.zeros(n, jnp.int32)
        batch_cpu, _ = self._batch_fn(
            jnp.asarray(self.capacity[:, ResourceDim.CPU]),
            jnp.asarray(self.capacity[:, ResourceDim.MEMORY]),
            zeros, zeros, zeros, zeros,
            jnp.asarray(np.minimum(hp_used_cpu, 2**30).astype(np.int32)),
            zeros, zeros, zeros, zeros, zeros,
            self._strategy)
        batch_cpu = np.asarray(batch_cpu)

        # -- BE placement: fill the advertised batch capacity, up to
        # the finite per-node BE demand
        be_used = self.be_used()
        be_count = np.zeros(n, np.int64)
        for node in self.be_pods.values():
            be_count[node] += 1
        for node in range(n):
            while (be_count[node] < cfg.be_max_pods_per_node
                   and be_used[node] + cfg.be_pod_cpu_milli
                   <= int(batch_cpu[node])
                   and len(self.be_pods) < _POD_CAP):
                name = f"be-{self._be_seq}"
                self._be_seq += 1
                self.be_pods[name] = node
                be_used[node] += cfg.be_pod_cpu_milli
                be_count[node] += 1

        # -- proactive rebalance (predictive arm only): classify the
        # forecast total (BE rides observed; LS rides the prediction)
        if self.predictive and self.plane.ready:
            total = usage.copy()
            total[:, ResourceDim.CPU] += be_used.astype(np.int32)
            peaks = self.plane.predicted_host()
            forecast = total.copy()
            forecast[:, ResourceDim.CPU] = (
                be_used + np.maximum(ls_row.astype(np.int64),
                                     peaks[:, ResourceDim.CPU])
            ).clip(0, 2**30).astype(np.int32)
            moves = self.rebalancer.tick(
                total, self.capacity, self.valid,
                forecast=jnp.asarray(forecast))
            for move in moves:
                self._move_dest[move.job.name] = int(move.dest[1:])
            self.prestaged += len(moves)
            self.controller.reconcile()
            be_used = self.be_used()

        # -- enforcement (identical in both arms): a node over the high
        # threshold accrues breach time and emergency-evicts BE pods
        high = cfg.high_quant
        for node in range(n):
            total_cpu = int(ls_row[node]) + int(be_used[node])
            if total_cpu <= high:
                continue
            self.breach_node_ticks += 1
            victims = [p for p, r in self.be_pods.items() if r == node]
            while total_cpu > high and victims:
                victim = victims.pop()
                del self.be_pods[victim]
                if self.rebalancer is not None:
                    self.rebalancer.release(victim)
                total_cpu -= cfg.be_pod_cpu_milli
                self.reactive_evictions += 1
        self.be_pod_ticks += len(self.be_pods)

    def _growth(self, now: float, ls_row: np.ndarray) -> float:
        """Relative cluster-LS growth per hour from trend.fit_slope over
        the recent window — the horizon policy's input."""
        mean = float(ls_row.mean())
        self._growth_samples.append((now, mean))
        window = [s for s in self._growth_samples
                  if now - s[0] <= 4 * self.cfg.refresh_interval_s]
        self._growth_samples = window
        fit = fit_slope([s[0] for s in window], [s[1] for s in window])
        if fit is None or fit.mean <= 0:
            return 0.0
        return fit.slope * 3600.0 / fit.mean

    def scorecard(self) -> dict:
        cfg = self.cfg
        doc = {
            "arm": "predictive" if self.predictive else "reactive",
            "slo_breach_minutes": round(
                self.breach_node_ticks * cfg.tick_s / 60.0, 3),
            "reactive_evictions": self.reactive_evictions,
            "be_pod_ticks": self.be_pod_ticks,
            "prestaged_migrations": self.prestaged,
            "migrations_completed": self.migrated,
        }
        if self.plane is not None:
            doc["forecast_error_fraction"] = {
                k: round(v, 4) for k, v in self.plane.error_fraction.items()}
            doc["horizon_s"] = self.plane.horizon_s
            doc["refreshes"] = self.plane.refreshes
        return doc


def run_ab(cfg: ABConfig | None = None) -> dict:
    """Replay one seeded diurnal trace through both arms and score
    them.  Deterministic: the same config always yields the same
    scorecard (asserted in tests/test_forecast.py)."""
    cfg = cfg or ABConfig()
    trace = generate_ls_trace(cfg)
    reactive = _Arm(cfg, predictive=False)
    predictive = _Arm(cfg, predictive=True)
    for t in range(cfg.ticks):
        reactive.tick(t, trace[t])
        predictive.tick(t, trace[t])
    r, p = reactive.scorecard(), predictive.scorecard()
    return {
        "seed": cfg.seed,
        "nodes": cfg.nodes,
        "ticks": cfg.ticks,
        "period_s": cfg.period_s,
        "reactive": r,
        "predictive": p,
        # GREEN bar: the predictive arm may not be WORSE on either
        # operational metric (soak_report --forecast exits on this)
        "predictive_no_worse": (
            p["slo_breach_minutes"] <= r["slo_breach_minutes"]
            and p["reactive_evictions"] <= r["reactive_evictions"]),
        "predictive_strictly_better": (
            p["slo_breach_minutes"] < r["slo_breach_minutes"]
            and p["reactive_evictions"] < r["reactive_evictions"]),
    }
