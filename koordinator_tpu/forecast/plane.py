"""The cluster-wide forecast plane: one device-resident predictor bank.

Where the koordlet's ``prediction/`` models one NODE's pods in
isolation, the :class:`ForecastPlane` holds EVERY node's decaying usage
histogram as one ``(N, B)`` bank per prod dimension — the same
fixed-capacity, power-of-two-bucketed, validity-masked layout as the
cluster state, pinned under the same NamedSharding when the solver
meshes — and answers all N predictions in one batched percentile pass
(:func:`~koordinator_tpu.forecast.kernels.predicted_peaks`).

Cadence contract:

- :meth:`observe` scatters one usage sample per node into the banks
  (called from the scheduler's round prelude under the round lock, or
  a harness tick) and keeps the running realized peak;
- :meth:`refresh` recomputes the ``(N, R)`` predicted-peak tensor at
  the current horizon, scores the PREVIOUS prediction against the
  realized peak (``forecast_error_fraction{dim}``), and resets the
  realized window.  The horizon stretches with the diurnal trend
  slope (:meth:`horizon_for`): a cluster trending up looks further
  ahead — "A Predictive Autoscaler for Elastic Batch Jobs" (PAPERS.md)
  is the template.

Thread-safety mirrors the SLO monitor: host fields swap under one lock
(``/debug/forecast`` reads arrive on gateway threads); device arrays
are immutable values, so readers see a consistent (predicted, horizon)
pair or the previous one, never a torn mix.
"""

from __future__ import annotations

import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu import metrics
from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.forecast import kernels
from koordinator_tpu.prediction.histogram import (
    HistogramBank,
    add_samples,
    default_cpu_buckets,
    default_memory_buckets,
)


class ForecastPlane:
    """N node-usage predictors as one device-resident bank."""

    def __init__(self, capacity: int, *,
                 half_life_s: float = 300.0,
                 base_horizon_s: float = 120.0,
                 max_horizon_scale: float = 4.0,
                 horizon_gain: float = 2.0,
                 safety_margin_pct: float = 10.0,
                 refresh_interval_s: float = 30.0,
                 mesh=None,
                 clock=time.time):
        self.capacity = int(capacity)
        self.half_life_s = float(half_life_s)
        self.base_horizon_s = float(base_horizon_s)
        self.max_horizon_scale = float(max_horizon_scale)
        self.horizon_gain = float(horizon_gain)
        self.safety_margin_pct = float(safety_margin_pct)
        self.refresh_interval_s = float(refresh_interval_s)
        self.clock = clock
        self.mesh = mesh
        self._sharding = None
        self._lock = threading.Lock()
        self._cpu_buckets = default_cpu_buckets()
        self._mem_buckets = default_memory_buckets()
        self._t0: float | None = None
        self.cpu_bank = HistogramBank.zeros(
            self.capacity, self._cpu_buckets, self.half_life_s)
        self.mem_bank = HistogramBank.zeros(
            self.capacity, self._mem_buckets, self.half_life_s)
        #: (N, R) int32 predicted peaks at the current horizon; None
        #: until the first refresh (``ready`` gates every consumer)
        self.predicted = None
        self._predicted_host: np.ndarray | None = None
        self._realized = jnp.zeros((self.capacity, NUM_RESOURCE_DIMS),
                                   jnp.int32)
        self._valid = jnp.zeros((self.capacity,), bool)
        self.horizon_s = self.base_horizon_s
        self.growth_per_hour = 0.0
        self.refreshed_at: float | None = None
        self.observations = 0
        self.refreshes = 0
        #: last refresh's |predicted - realized| / realized per dim
        #: (None before two refreshes bracket a realized window)
        self.error_fraction: dict[str, float] = {}
        #: extra labels on every gauge this plane publishes (the
        #: scheduler stamps its tenant here at attach, so per-tenant
        #: planes never overwrite each other's telemetry)
        self.metric_labels: dict[str, str] = {}
        #: the scheduler's last published admission-reserve fraction
        #: (reserve_fraction stores it so /debug/forecast reads THIS
        #: plane's number, not a shared global gauge)
        self.last_admission_reserved_fraction: float = 0.0
        #: (refresh time, mean realized CPU) window the auto horizon
        #: policy fits trend.fit_slope over when the caller supplies no
        #: growth rate
        self._growth_window: list[tuple[float, float]] = []

        # -- jitted entries (buckets are hashable static args) --
        self._observe_fn = jax.jit(partial(
            self._observe_kernel,
            cpu_buckets=self._cpu_buckets, mem_buckets=self._mem_buckets))
        self._peaks_fn = jax.jit(partial(
            kernels.predicted_peaks,
            cpu_buckets=self._cpu_buckets, mem_buckets=self._mem_buckets,
            safety_margin_pct=self.safety_margin_pct))
        self._peaks_fn_sh = None
        if mesh is not None:
            self._peaks_fn_sh = jax.jit(partial(
                kernels.sharded_predicted_peaks, mesh,
                cpu_buckets=self._cpu_buckets,
                mem_buckets=self._mem_buckets,
                safety_margin_pct=self.safety_margin_pct))
        self._reserve_fn = jax.jit(kernels.admission_reserve)
        self._error_fn = jax.jit(kernels.forecast_error_sums)
        self._reserve_sums_fn = jax.jit(kernels.reserve_fraction_sums)
        self._realized_mean_fn = jax.jit(
            lambda realized, valid: (
                jnp.sum(jnp.where(
                    valid, realized[:, ResourceDim.CPU], 0
                ).astype(jnp.float32)),
                jnp.sum(valid.astype(jnp.float32))))

    @staticmethod
    def _observe_kernel(cpu_bank, mem_bank, realized, usage, valid, t,
                        *, cpu_buckets, mem_buckets):
        uids = jnp.arange(usage.shape[0], dtype=jnp.int32)
        cpu_bank = add_samples(
            cpu_bank, cpu_buckets, uids,
            usage[:, ResourceDim.CPU].astype(jnp.float32), t, mask=valid)
        mem_bank = add_samples(
            mem_bank, mem_buckets, uids,
            usage[:, ResourceDim.MEMORY].astype(jnp.float32), t, mask=valid)
        realized = kernels.realized_peak_update(realized, usage, valid)
        # the retained valid mask must be a FRESH buffer, never the
        # caller's: the scheduler feeds this from snapshot state whose
        # buffers the round's donating solve consumes minutes later —
        # holding the input would leave refresh()/report() reading a
        # deleted array (computing it inside the jit guarantees a new
        # executable output buffer)
        valid_copy = jnp.where(valid, True, False)
        return cpu_bank, mem_bank, realized, valid_copy

    # -- placement -----------------------------------------------------------

    def set_sharding(self, sharding) -> None:
        """Pin the bank (and any predictions) node-axis-sharded — the
        same placement the snapshot pins its state under, so the
        admission reserve and the charged solve never reshard."""
        self._sharding = sharding
        if sharding is None:
            return
        put = lambda x: jax.device_put(x, sharding)  # noqa: E731
        with self._lock:
            self.cpu_bank = self.cpu_bank.replace(
                weights=put(self.cpu_bank.weights),
                total=put(self.cpu_bank.total))
            self.mem_bank = self.mem_bank.replace(
                weights=put(self.mem_bank.weights),
                total=put(self.mem_bank.total))
            self._realized = put(self._realized)
            self._valid = put(self._valid)
            if self.predicted is not None:
                self.predicted = put(self.predicted)

    def grow(self, capacity: int) -> None:
        """Re-bucket to a larger node capacity (snapshot growth): pad
        every per-node tensor; existing rows keep their history."""
        if capacity <= self.capacity:
            return
        old = self.capacity
        self.capacity = int(capacity)

        def pad(a):
            out = np.zeros((capacity,) + a.shape[1:], np.asarray(a).dtype)
            out[:old] = np.asarray(a)
            return jnp.asarray(out)

        with self._lock:
            self.cpu_bank = self.cpu_bank.replace(
                weights=pad(self.cpu_bank.weights),
                total=pad(self.cpu_bank.total))
            self.mem_bank = self.mem_bank.replace(
                weights=pad(self.mem_bank.weights),
                total=pad(self.mem_bank.total))
            self._realized = pad(self._realized)
            self._valid = pad(self._valid)
            self.predicted = (pad(self.predicted)
                              if self.predicted is not None else None)
            self._predicted_host = None
        if self._sharding is not None:
            self.set_sharding(self._sharding)

    # -- ingestion -----------------------------------------------------------

    def observe(self, usage, valid, now: float | None = None) -> None:
        """Scatter one usage sample per node into the banks.

        ``usage`` is (N, R) int32, ``valid`` (N,) bool — numpy or
        device arrays; the whole batch lands in ONE jitted scatter.
        Timestamps are plane-relative so decay stays within float32.
        """
        now = self.clock() if now is None else now
        if self._t0 is None:
            self._t0 = now
        t = jnp.float32(max(now - self._t0, 0.0))
        usage = jnp.asarray(usage)
        valid = jnp.asarray(valid)
        if usage.shape[0] > self.capacity:
            self.grow(usage.shape[0])
        elif usage.shape[0] < self.capacity:
            # a plane sized ahead of its snapshot: pad the sample up to
            # the bank (missing rows are invalid, contributing nothing)
            pad = self.capacity - usage.shape[0]
            usage = jnp.pad(usage, ((0, pad), (0, 0)))
            valid = jnp.pad(valid, (0, pad))
        with self._lock:
            (self.cpu_bank, self.mem_bank, self._realized,
             self._valid) = self._observe_fn(
                self.cpu_bank, self.mem_bank, self._realized, usage, valid,
                t)
            self.observations += 1

    def observe_state(self, state, now: float | None = None) -> None:
        """Observe a ClusterState's usage tensor (the scheduler's round
        prelude path — called under the round lock, pre-dispatch, so
        the state buffers are live)."""
        self.observe(state.node_usage, state.node_valid, now)

    # -- prediction ----------------------------------------------------------

    def horizon_for(self, growth_per_hour: float | None) -> float:
        """Horizon policy: stretch the base horizon with the diurnal
        trend slope — a cluster whose usage is ramping deserves a
        longer look-ahead; a flat or falling trend keeps the base.
        ``growth_per_hour`` is a RELATIVE rate (fraction of current
        level per hour), e.g. a trend.py slope over a usage series
        divided by its mean."""
        g = max(float(growth_per_hour or 0.0), 0.0)
        return self.base_horizon_s * min(1.0 + g * self.horizon_gain,
                                         self.max_horizon_scale)

    def _auto_growth(self, now: float) -> float:
        """Relative realized-CPU growth per hour, fitted with
        ``trend.fit_slope`` over the recent refresh window — the
        horizon policy's default input when the caller wires no
        external trend signal.  One tiny device reduction per refresh.
        """
        from koordinator_tpu.trend import fit_slope

        total, count = self._realized_mean_fn(self._realized, self._valid)
        count = float(count)
        mean = float(total) / count if count > 0 else 0.0
        self._growth_window.append((now, mean))
        self._growth_window = self._growth_window[-8:]
        fit = fit_slope([s[0] for s in self._growth_window],
                        [s[1] for s in self._growth_window])
        if fit is None or fit.mean <= 0:
            return 0.0
        return fit.slope * 3600.0 / fit.mean

    def refresh(self, now: float | None = None,
                growth_per_hour: float | None = None) -> None:
        """Recompute the (N, R) predicted-peak tensor, score the
        previous prediction against the realized window, publish the
        forecast gauges, and reset the realized window.

        ``growth_per_hour`` None means self-derived: the plane fits the
        trend slope over its own realized-usage window
        (:meth:`_auto_growth`), so the documented horizon stretch works
        without any external wiring."""
        now = self.clock() if now is None else now
        with self._lock:
            if growth_per_hour is None:
                growth_per_hour = self._auto_growth(now)
            self.growth_per_hour = float(growth_per_hour)
            self.horizon_s = self.horizon_for(growth_per_hour)
            if self.predicted is not None:
                err, base = self._error_fn(self.predicted, self._realized,
                                           self._valid)
                err, base = np.asarray(err), np.asarray(base)
                for dim in (ResourceDim.CPU, ResourceDim.MEMORY):
                    if base[dim] > 0:
                        frac = float(err[dim]) / float(base[dim])
                        self.error_fraction[dim.name.lower()] = frac
                        metrics.forecast_error_fraction.set(
                            frac, labels={"dim": dim.name.lower(),
                                          **self.metric_labels})
            fn = self._peaks_fn_sh or self._peaks_fn
            self.predicted = fn(
                self.cpu_bank.weights, self.cpu_bank.total,
                self.mem_bank.weights, self.mem_bank.total,
                jnp.float32(self.horizon_s),
                jnp.float32(self.growth_per_hour))
            if self._sharding is not None:
                self.predicted = jax.device_put(self.predicted,
                                                self._sharding)
            self._predicted_host = None
            self._realized = jnp.zeros_like(self._realized)
            if self._sharding is not None:
                self._realized = jax.device_put(self._realized,
                                                self._sharding)
            self.refreshed_at = now
            self.refreshes += 1
        metrics.forecast_horizon_seconds.set(
            self.horizon_s, labels=self.metric_labels or None)

    def maybe_refresh(self, now: float | None = None,
                      growth_per_hour: float | None = None) -> bool:
        """Refresh on the configured cadence; True when one ran."""
        now = self.clock() if now is None else now
        if (self.refreshed_at is not None
                and now - self.refreshed_at < self.refresh_interval_s):
            return False
        self.refresh(now, growth_per_hour)
        return True

    @property
    def ready(self) -> bool:
        """Consumers may act on the forecast (first refresh landed)."""
        return self.predicted is not None

    # -- consumers -----------------------------------------------------------

    def admission_reserve(self, state):
        """(N, R) int32 forecast-headroom reserve against this state,
        or None while the plane is not ready / capacities diverge (a
        snapshot that grew past the plane waits for the next observe
        to re-bucket)."""
        if self.predicted is None:
            return None
        if state.capacity != self.capacity:
            return None
        return self._reserve_fn(self.predicted, state.node_usage,
                                state.node_valid)

    def reserve_fraction(self, reserve, state) -> float:
        """Cluster-wide reserved fraction of allocatable (the
        ``forecast_admission_reserved_fraction`` value) — one small
        (R,) device reduction, host-read by the caller's cadence."""
        res, alloc = self._reserve_sums_fn(reserve, state)
        res, alloc = np.asarray(res), np.asarray(alloc)
        total = float(alloc.sum())
        frac = float(res.sum()) / total if total > 0 else 0.0
        self.last_admission_reserved_fraction = frac
        return frac

    def predicted_host(self) -> np.ndarray | None:
        """Host copy of the predicted-peak tensor (cached per refresh)
        — the predictive-colocation driver's read path."""
        with self._lock:
            if self.predicted is None:
                return None
            if self._predicted_host is None:
                self._predicted_host = np.asarray(self.predicted)
            return self._predicted_host

    def forecast_usage(self, node_usage):
        """(N, R) int32 max(observed, predicted) — the forecast usage
        tensor proactive rebalance classifies over (a forecast must
        never make a node look EMPTIER than it observably is)."""
        if self.predicted is None:
            return jnp.asarray(node_usage)
        return jnp.maximum(jnp.asarray(node_usage), self.predicted)

    # -- surfaces ------------------------------------------------------------

    def report(self, max_nodes: int = 64,
               row_names: dict[int, str] | None = None) -> dict:
        """The /debug/forecast body fragment: horizon policy, error
        stats, and the top ``max_nodes`` nodes by predicted CPU peak."""
        with self._lock:
            horizon = self.horizon_s
            growth = self.growth_per_hour
            refreshed = self.refreshed_at
            refreshes = self.refreshes
            observations = self.observations
            error = dict(self.error_fraction)
            # peaks and the valid mask must come from ONE lock scope: a
            # concurrent grow() between the two reads would hand back a
            # valid mask longer than the peaks array
            if self.predicted is not None and self._predicted_host is None:
                self._predicted_host = np.asarray(self.predicted)
            peaks = self._predicted_host
            valid = (np.asarray(self._valid)[: peaks.shape[0]]
                     if peaks is not None else None)
        doc = {
            "ready": self.ready,
            "capacity": self.capacity,
            "horizon_s": horizon,
            "growth_per_hour": growth,
            "base_horizon_s": self.base_horizon_s,
            "refreshed_at": refreshed,
            "refreshes": refreshes,
            "observations": observations,
            "error_fraction": error,
            "admission_reserved_fraction":
                self.last_admission_reserved_fraction,
            "sharded": self._sharding is not None,
            "nodes": [],
        }
        if peaks is None:
            return doc
        rows = np.flatnonzero(valid)
        order = rows[np.argsort(peaks[rows, ResourceDim.CPU])[::-1]]
        for row in order[:max(int(max_nodes), 0)]:
            entry = {
                "row": int(row),
                "predicted_cpu_milli": int(peaks[row, ResourceDim.CPU]),
                "predicted_memory_mib": int(peaks[row, ResourceDim.MEMORY]),
            }
            if row_names:
                name = row_names.get(int(row))
                if name is not None:
                    entry["node"] = name
            doc["nodes"].append(entry)
        return doc
