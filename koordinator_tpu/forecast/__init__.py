"""Forecast plane: device-resident cluster forecasting (ISSUE 15).

The predictive loop ROADMAP item 3 names, closed: the koordlet's
decaying-histogram peak predictors (``prediction/``) lift into ONE
cluster-wide ``(N, R)`` predicted-peak tensor (a batched percentile over
a node-sharded histogram bank, pinned under the same NamedSharding as
the cluster state), and three consumers act on the forecast instead of
the observation:

- **predictive colocation** — the manager's batch/mid allocatable solve
  takes predicted instead of observed HP peaks, so BE capacity shrinks
  *before* the forecast LS demand arrives
  (:mod:`~koordinator_tpu.forecast.colocation`, wired through
  ``ColocationLoop``'s existing node_allocatable push path);
- **predictive admission** — a forecast-headroom reserve charged into
  the solve's filter/score accounting for the round
  (``Scheduler(forecast_mode=...)`` + the ``forecast_gang_assign``
  SolverKit entry and its sharded twin; ``off`` is bit-identical to
  today);
- **proactive rebalance** — LowNodeLoad classification over the
  *forecast* usage tensor pre-stages reservation-first migrations off
  nodes predicted to cross the high threshold, each move gated on a
  migration-cost evaluation over the resident cluster-state tensors
  (:mod:`~koordinator_tpu.forecast.rebalance`).

Proof is the reactive-vs-predictive A/B harness
(:mod:`~koordinator_tpu.forecast.ab`): two stacks replay the same
seeded diurnal trace and a scorer reports evictions avoided, SLO-breach
minutes, and forecast error per arm (``tools/soak_report.py
--forecast``).  See docs/forecast.md.
"""

from __future__ import annotations

#: Scheduler(forecast_mode=...) values — gauge-encoded in order, like
#: QUALITY_MODES: off = no forecast anywhere (bit-identical to a
#: scheduler without the plane); admit = the admission reserve only;
#: full = admission + the colocation/rebalance drivers armed at
#: assembly.
FORECAST_MODES = ("off", "admit", "full")

from koordinator_tpu.forecast.plane import ForecastPlane  # noqa: E402,F401
