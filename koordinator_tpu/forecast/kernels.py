"""Jitted kernels of the forecast plane.

Everything here is device math over the same ``(N, R)`` tensor layout
the solver owns (state/cluster_state.py):

- :func:`predicted_peaks` — the batched percentile over the node
  histogram bank, horizon-extrapolated by the diurnal trend slope, as
  one ``(N, R)`` int32 predicted-peak tensor.  The horizon and growth
  rate ride as DEVICE scalars end to end: a host cast of either inside
  the jitted flow is the jit-host-sync bug class the seeded forecast
  corpus (tools/koordlint/fixtures/forecast) pins.
- :func:`sharded_predicted_peaks` — the explicit shard_map twin over
  the 2-D mesh's nodes axis.  The percentile is per-row elementwise, so
  the program needs no collectives; every spec is explicit
  (mesh-discipline).
- :func:`admission_reserve` — the forecast-headroom term: the part of
  the predicted peak NOT yet visible in observed usage, as an
  ``(N, R)`` reserve the solve charges for the round.
- :func:`forecast_gang_assign` — the SolverKit entry: charge the
  reserve into ``node_requested``, run the standard gang/greedy solve,
  release the reserve from the returned state.  One jitted program, so
  no host-visible intermediate state ever carries the charge and a
  solve failure recovers exactly like today's entries.
- :func:`migration_cost_gate` — the proactive-rebalance move gate over
  the resident cluster-state tensors: a pre-staged migration is allowed
  only while an underutilized destination can absorb the pod on every
  configured dimension WITHOUT crossing its own high threshold
  (sequential capacity feedback, like ``select_victims``).

Empty histograms predict 0 (the sentinel — never NaN); predictions clip
to ``MAX_QUANTITY`` so the int32 invariant every downstream percent and
score kernel relies on survives extrapolation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS, ResourceDim
from koordinator_tpu.parallel.mesh import NODES_AXIS
from koordinator_tpu.prediction.histogram import (
    ExponentialBuckets,
    HistogramBank,
    percentile,
)
from koordinator_tpu.state.cluster_state import MAX_QUANTITY

#: percentiles per dimension, matching the koordlet's per-pod peak
#: predictors (prediction/predictor.py: p95 cpu / p98 memory)
CPU_PERCENTILE = 0.95
MEM_PERCENTILE = 0.98


def _peak_one_dim(weights, total, buckets: ExponentialBuckets, p: float,
                  horizon_s, growth_per_hour, safety_margin_pct: float):
    """(N,) float32 horizon-extrapolated peak of one resource dim.

    ``horizon_s`` / ``growth_per_hour`` are () device scalars; the
    extrapolation is multiplicative (the trend slope arrives as a
    RELATIVE growth rate per hour), clamped to growth — a downward
    trend never shrinks the peak below the histogram's own percentile,
    the conservative direction for admission.
    """
    bank = HistogramBank(weights=weights, total=total,
                         ref_time=jnp.float32(0.0),
                         half_life=jnp.float32(1.0))
    peak = percentile(bank, buckets, p)
    peak = peak * (100.0 + safety_margin_pct) / 100.0
    growth = jnp.maximum(growth_per_hour, 0.0) * (horizon_s / 3600.0)
    return peak * (1.0 + growth)


def predicted_peaks(
    cpu_weights: jax.Array,   # (N, Bc) float32 decayed bucket weights
    cpu_total: jax.Array,     # (N,) float32
    mem_weights: jax.Array,   # (N, Bm) float32
    mem_total: jax.Array,     # (N,) float32
    horizon_s: jax.Array,     # () float32 — device scalar, never host-cast
    growth_per_hour: jax.Array,  # () float32 relative growth rate
    *,
    cpu_buckets: ExponentialBuckets,
    mem_buckets: ExponentialBuckets,
    safety_margin_pct: float = 10.0,
) -> jax.Array:
    """(N, R) int32 predicted peak usage at the horizon.

    Only the prod dims (CPU/MEMORY) carry predictions — the
    overcommitted batch/mid dims are DERIVED from these peaks by the
    colocation formula, not forecast independently.  Empty histograms
    predict 0.
    """
    n = cpu_weights.shape[0]
    cpu = _peak_one_dim(cpu_weights, cpu_total, cpu_buckets, CPU_PERCENTILE,
                        horizon_s, growth_per_hour, safety_margin_pct)
    mem = _peak_one_dim(mem_weights, mem_total, mem_buckets, MEM_PERCENTILE,
                        horizon_s, growth_per_hour, safety_margin_pct)
    out = jnp.zeros((n, NUM_RESOURCE_DIMS), jnp.float32)
    out = out.at[:, ResourceDim.CPU].set(cpu)
    out = out.at[:, ResourceDim.MEMORY].set(mem)
    return jnp.clip(out, 0.0, float(MAX_QUANTITY)).astype(jnp.int32)


def sharded_predicted_peaks(
    mesh,
    cpu_weights: jax.Array,
    cpu_total: jax.Array,
    mem_weights: jax.Array,
    mem_total: jax.Array,
    horizon_s: jax.Array,
    growth_per_hour: jax.Array,
    *,
    cpu_buckets: ExponentialBuckets,
    mem_buckets: ExponentialBuckets,
    safety_margin_pct: float = 10.0,
) -> jax.Array:
    """The explicit shard_map twin of :func:`predicted_peaks`: the bank
    shards its node axis over the mesh's nodes axis (the same placement
    the cluster state pins), the percentile runs per-shard (per-row
    math, no collectives), and the (N, R) result comes back
    node-sharded — bit-identical to the single-device kernel."""
    if cpu_weights.shape[0] % int(mesh.shape[NODES_AXIS]):
        raise ValueError(
            f"bank capacity {cpu_weights.shape[0]} does not divide over "
            f"the {int(mesh.shape[NODES_AXIS])}-way nodes axis")

    def local(cw, ct, mw, mt, h, g):
        return predicted_peaks(
            cw, ct, mw, mt, h, g,
            cpu_buckets=cpu_buckets, mem_buckets=mem_buckets,
            safety_margin_pct=safety_margin_pct)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS),
                  P(NODES_AXIS), P(), P()),
        out_specs=P(NODES_AXIS))
    return fn(cpu_weights, cpu_total, mem_weights, mem_total,
              horizon_s, growth_per_hour)


# koordlint: shape[predicted: NxR i32 nodes, ret0: NxR i32 nodes]
def admission_reserve(
    predicted: jax.Array,      # (N, R) int32 predicted peaks
    node_usage: jax.Array,     # (N, R) int32 observed usage
    node_valid: jax.Array,     # (N,) bool
) -> jax.Array:
    """(N, R) int32 forecast-headroom reserve: the forecast GROWTH —
    the part of the predicted peak observed usage does not cover yet.
    Charged into ``node_requested`` for the round by
    :func:`forecast_gang_assign`, so filter and score both see the
    node as that much fuller before the LS ramp arrives."""
    grow = jnp.clip(predicted - node_usage, 0, MAX_QUANTITY)
    return jnp.where(node_valid[:, None], grow, 0).astype(jnp.int32)


# koordlint: shape[state: NxR i32 nodes, reserve: NxR i32 nodes]
def forecast_gang_assign(state, reserve, pods, cfg, gangs, quota=None,
                         passes: int = 2, solver: str = "greedy"):
    """``gang_assign`` with the forecast-headroom reserve charged for
    the duration of the solve — the predictive-admission SolverKit
    entry.

    One jitted program: charge -> solve -> release, so the charge never
    escapes into host-visible state (an execution failure recovers
    through the same donation path as the plain entry), and the
    returned state carries exactly the round's placements — quota
    charges and accounting are bit-identical to the unforecast solve
    for any pod both would place."""
    from koordinator_tpu.ops.gang import gang_assign

    charged = state.replace(node_requested=state.node_requested + reserve)
    a, new_state, new_quota = gang_assign(
        charged, pods, cfg, gangs, quota, passes=passes, solver=solver)
    return a, new_state.replace(
        node_requested=new_state.node_requested - reserve), new_quota


def reserve_fraction_sums(reserve: jax.Array, state) -> tuple[jax.Array,
                                                              jax.Array]:
    """((R,), (R,)) float32 sums of (reserve, allocatable) over valid
    nodes — the ``forecast_admission_reserved_fraction`` inputs (float32
    accumulation: summed int32 quantities overflow at 10k nodes)."""
    valid = state.node_valid[:, None]
    return (
        jnp.sum(jnp.where(valid, reserve, 0).astype(jnp.float32), axis=0),
        jnp.sum(jnp.where(valid, state.node_allocatable, 0
                          ).astype(jnp.float32), axis=0),
    )


def realized_peak_update(realized: jax.Array, node_usage: jax.Array,
                         node_valid: jax.Array) -> jax.Array:
    """(N, R) int32 running max of observed usage since the last
    refresh — the ground truth the NEXT refresh scores its previous
    prediction against."""
    return jnp.where(node_valid[:, None],
                     jnp.maximum(realized, node_usage), 0)


def forecast_error_sums(predicted: jax.Array, realized: jax.Array,
                        node_valid: jax.Array) -> tuple[jax.Array,
                                                        jax.Array]:
    """((R,), (R,)) float32 sums of |predicted - realized| and realized
    over valid nodes with any realized signal — the
    ``forecast_error_fraction{dim}`` inputs.  Nodes that saw no usage
    in the window contribute to neither sum (a 0/0 must read as "no
    signal", not 100% error)."""
    seen = node_valid[:, None] & (realized > 0)
    err = jnp.abs(predicted - realized)
    return (
        jnp.sum(jnp.where(seen, err, 0).astype(jnp.float32), axis=0),
        jnp.sum(jnp.where(seen, realized, 0).astype(jnp.float32), axis=0),
    )


def migration_cost_gate(
    pod_usage: jax.Array,       # (K, R) int32 candidate pods' usage
    node_usage: jax.Array,      # (N, R) int32 observed node usage
    capacity: jax.Array,        # (N, R) int32 node capacity
    under: jax.Array,           # (N,) bool underutilized destinations
    high_thresholds: jax.Array, # (R,) int32 percent, -1 unconfigured
) -> tuple[jax.Array, jax.Array]:
    """((K,) bool gate, (K,) int32 destination rows) for pre-staged
    migrations.

    A move passes the cost gate only while some underutilized node can
    absorb the pod on EVERY configured dimension without crossing its
    own high threshold; accepted moves charge their destination before
    the next candidate evaluates (sequential capacity feedback — two
    pods cannot both claim the last slot).  Destination is the
    feasible node with the most post-move slack; gate False returns
    destination -1."""
    configured = high_thresholds >= 0
    high_quant = jnp.where(
        configured[None, :],
        capacity * jnp.maximum(high_thresholds, 0)[None, :] // 100,
        jnp.int32(2**30))

    def step(usage, pod):
        room = high_quant - usage                      # (N, R)
        fits = under & jnp.all(
            (~configured[None, :]) | (pod[None, :] <= room), axis=1)
        # slack score: the tightest configured dim's post-move headroom
        slack = jnp.min(jnp.where(configured[None, :], room - pod[None, :],
                                  jnp.int32(2**30)), axis=1)
        ok = jnp.any(fits)
        dest = jnp.argmax(jnp.where(fits, slack, jnp.int32(-2**30)))
        delta = jnp.where(ok, pod, 0)
        usage = usage.at[dest].add(delta)
        return usage, (ok, jnp.where(ok, dest, -1).astype(jnp.int32))

    _, (gate, dest) = jax.lax.scan(step, node_usage, pod_usage)
    return gate, dest
