"""Proactive rebalance: pre-staged, reservation-first migrations off
nodes FORECAST to run hot.

Today's descheduler reacts: LowNodeLoad classifies observed usage, and
a node must be observed overutilized for ``anomaly_rounds`` consecutive
rounds before anything moves (descheduler/lownodeload.py) — by which
time the LS spike landed and the eviction is an emergency.  This driver
runs the SAME classification kernels over the forecast usage tensor
(``max(observed, predicted)`` — a forecast never makes a node look
emptier than it is), so the anomaly counters start ticking BEFORE the
spike and the moves happen while they are still cheap:

- victims come from :func:`~koordinator_tpu.descheduler.lownodeload.
  select_victims` over the forecast tensor (priority-ordered, budgeted
  against the underutilized pool — the exact semantics the reactive
  path has, just on predicted state);
- every move passes the migration-cost gate
  (:func:`~koordinator_tpu.forecast.kernels.migration_cost_gate`) over
  the resident cluster-state tensors: an underutilized destination must
  absorb the pod on every configured dim without crossing its own high
  threshold, with sequential capacity feedback;
- gated moves become reservation-first
  :class:`~koordinator_tpu.descheduler.migration.MigrationJob`\\ s: the
  controller reserves replacement capacity (``reserve_fn``) before any
  eviction fires, so a pre-staged pod is never left homeless.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu import metrics
from koordinator_tpu.descheduler.lownodeload import (
    LowNodeLoadArgs,
    classify_nodes,
    select_victims,
    update_anomaly_counters,
)
from koordinator_tpu.descheduler.migration import (
    MigrationController,
    MigrationJob,
)
from koordinator_tpu.forecast import kernels


@dataclasses.dataclass
class StagedMove:
    """One pre-staged migration the tick produced."""

    pod: str
    node: str
    dest: str
    job: MigrationJob


class ProactiveRebalancer:
    """Forecast-classified LowNodeLoad + cost-gated pre-staging.

    ``pods_fn()`` returns the victim universe as parallel arrays
    ``(names, pod_node (P,), pod_usage (P, R), pod_priority (P,),
    pod_evictable (P,))`` — the same shape ``select_victims`` takes;
    ``node_name_fn(row)`` resolves destination rows.  The controller's
    ``reserve_fn``/``evict_fn`` stay the caller's seams (a real stack
    wires the scheduler's reservation API; the A/B harness books its
    simulated capacity).
    """

    def __init__(self, plane,
                 controller: MigrationController,
                 pods_fn: Callable[[], tuple],
                 node_name_fn: Callable[[int], Optional[str]],
                 args: LowNodeLoadArgs | None = None,
                 prestage_cap: int = 64):
        self.plane = plane
        self.controller = controller
        self.pods_fn = pods_fn
        self.node_name_fn = node_name_fn
        self.args = args if args is not None else LowNodeLoadArgs.default()
        #: at most this many moves stage per tick — proactive rebalance
        #: is a trickle ahead of the ramp, not a mass drain
        self.prestage_cap = prestage_cap
        self._anomaly = None
        self._staged: set[str] = set()
        self.ticks = 0
        self.staged_total = 0

    def tick(self, usage, capacity, node_valid,
             forecast=None) -> list[StagedMove]:
        """One proactive round over the (N, R) node tensors.  Returns
        the moves staged this tick (already submitted to the
        controller, which the caller reconciles on its own cadence).

        ``forecast`` overrides the classified tensor for callers whose
        plane predicts only a COMPONENT of node usage (the A/B harness
        forecasts LS and adds observed BE on top); the default is the
        plane's ``max(observed, predicted)``."""
        self.ticks += 1
        usage = jnp.asarray(usage)
        capacity = jnp.asarray(capacity)
        node_valid = jnp.asarray(node_valid)
        if forecast is None:
            forecast = self.plane.forecast_usage(usage)

        n = forecast.shape[0]
        if self._anomaly is None or self._anomaly.shape[0] != n:
            self._anomaly = jnp.zeros((n,), jnp.int32)
        under, over = classify_nodes(forecast, capacity, node_valid,
                                     self.args)
        self._anomaly = update_anomaly_counters(self._anomaly, over)

        names, pod_node, pod_usage, pod_priority, pod_evictable = (
            self.pods_fn())
        if len(names) == 0:
            return []
        # pods already staged must not stage again while their job runs
        evictable = np.asarray(pod_evictable, bool).copy()
        for i, name in enumerate(names):
            if name in self._staged:
                evictable[i] = False
        victims = np.asarray(select_victims(
            forecast, capacity, node_valid,
            jnp.asarray(pod_node), jnp.asarray(pod_usage),
            jnp.asarray(pod_priority), jnp.asarray(evictable),
            self._anomaly, self.args))
        rows = np.flatnonzero(victims)[: self.prestage_cap]
        if len(rows) == 0:
            return []

        # cost gate over the OBSERVED state: destinations must absorb
        # the pod today, not just in the forecast (a move into a node
        # that is presently full trades one hot node for another).
        # Candidates pad to the prestage cap so the sequential scan
        # compiles once per cap, not once per candidate count.
        padded = np.zeros((self.prestage_cap, np.asarray(pod_usage).shape[1]),
                          np.int32)
        padded[: len(rows)] = np.asarray(pod_usage)[rows]
        gate, dest = kernels.migration_cost_gate(
            jnp.asarray(padded), usage, capacity, under,
            self.args.high_thresholds)
        gate, dest = np.asarray(gate), np.asarray(dest)

        moves: list[StagedMove] = []
        pod_node_np = np.asarray(pod_node)
        for j, i in enumerate(rows):
            if not gate[j]:
                continue
            pod = names[int(i)]
            src = self.node_name_fn(int(pod_node_np[i])) or str(
                int(pod_node_np[i]))
            dst = self.node_name_fn(int(dest[j])) or str(int(dest[j]))
            job = MigrationJob(
                name=f"forecast-{pod}-t{self.ticks}",
                pod=pod, node=src, priority=int(
                    np.asarray(pod_priority)[i]))
            try:
                self.controller.submit(job)
            except ValueError:
                continue      # an identically-named job is still live
            self._staged.add(pod)
            self.staged_total += 1
            metrics.forecast_evictions_prestaged.inc()
            moves.append(StagedMove(pod=pod, node=src, dest=dst, job=job))
        return moves

    def release(self, pod: str) -> None:
        """A staged pod finished migrating (or died): it may stage
        again in a later tick."""
        self._staged.discard(pod)
