"""Predictive colocation: predicted peaks into the batch/mid solve.

The manager's colocation loop computes Batch/Mid allocatable from
OBSERVED HP usage (manager/noderesource.py byUsage policy): BE capacity
only shrinks after the LS spike already happened — the reactive lag the
whole forecast plane exists to close.  This driver swaps the observed
peak for the forecast plane's predicted peak (never below the
observation — a forecast must not hallucinate capacity back), so the
very next ``node_allocatable`` push down the EXISTING transport path
advertises the shrunk BE capacity before the forecast LS demand
arrives.

``ColocationLoop`` takes the driver as an optional ``forecast`` seam:
``None`` (the default) is byte-identical to today's reconcile.
"""

from __future__ import annotations

from typing import Callable, Optional

from koordinator_tpu.api.resources import ResourceDim


class PredictiveColocation:
    """NodeRecord overrides from the forecast plane.

    ``row_fn(name) -> row | None`` maps a node name into the plane's
    row space (the scheduler snapshot's rows when the plane is fed from
    it; a harness's own index otherwise).  Rows the plane does not know
    keep their observed values — prediction is an override, never a
    gate on participating in colocation at all.
    """

    def __init__(self, plane, row_fn: Callable[[str], Optional[int]]):
        self.plane = plane
        self.row_fn = row_fn
        self.overridden = 0

    def apply(self, record) -> None:
        """Raise one NodeRecord's HP peak to the predicted peak and
        re-derive the prod-reclaimable input from it — the two fields
        the batch and mid formulas consume
        (CalculateBatchResourceByPolicy / CalculateMidResourceByPolicy).
        Called per record inside ``ColocationLoop._build_records``
        while the binding lock is NOT held (the record is host-local).
        """
        peaks = self.plane.predicted_host()
        if peaks is None:
            return
        row = self.row_fn(record.name)
        if row is None or not (0 <= row < peaks.shape[0]):
            return
        pred_cpu = int(peaks[row, ResourceDim.CPU])
        pred_mem = int(peaks[row, ResourceDim.MEMORY])
        if pred_cpu <= 0 and pred_mem <= 0:
            return
        # predicted-vs-observed HP peak: the batch formula's hpUsed term
        record.hp_used_cpu_milli = max(record.hp_used_cpu_milli or 0,
                                       pred_cpu)
        record.hp_used_mem_mib = max(record.hp_used_mem_mib or 0,
                                     pred_mem)
        # prod reclaimable re-derives from the SAME (maxed) peak
        # (peak_predictor semantics: reclaimable = request - peak,
        # clamped) — NOT from the raw prediction: a prediction running
        # below a fresh usage spike must not hand the mid tier capacity
        # the node is actively using
        record.prod_reclaimable_cpu_milli = max(
            record.hp_request_cpu_milli - record.hp_used_cpu_milli, 0)
        record.prod_reclaimable_mem_mib = max(
            record.hp_request_mem_mib - record.hp_used_mem_mib, 0)
        self.overridden += 1
