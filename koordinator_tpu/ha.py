"""Leader election / HA for the control-plane components.

The reference leader-elects koord-manager, koord-scheduler and
koord-descheduler with client-go Lease locks (``cmd/koord-manager/main.go``
``--enable-leader-election`` / ``--leader-elect-resource-lock=leases``;
equivalent flags in the scheduler and descheduler commands). The control
plane is stateless — on failover the new leader rebuilds everything from
informers, gated by the startup sync barrier
(``cmd/koord-scheduler/app/sync_barrier.go``, scheduler/barrier.py here).

This module is the client-go ``leaderelection`` semantic rebuilt over a
pluggable lease store: acquire when the lease is free or expired, renew
while holding, release on stop, fire OnStartedLeading / OnStoppedLeading /
OnNewLeader transitions. The in-process :class:`InMemoryLeaseStore` stands
in for the apiserver Lease object (compare-and-swap under a lock, the same
atomicity a Lease update gives).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional, Protocol


@dataclasses.dataclass
class LeaseRecord:
    """coordination.k8s.io/v1 Lease essentials."""

    holder: str = ""
    duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    transitions: int = 0

    def expired(self, now: float) -> bool:
        return (not self.holder
                or now >= self.renew_time + self.duration_seconds)


class LeaseStore(Protocol):
    """The lock-object seam (a k8s Lease in the real deployment)."""

    def get(self, name: str) -> LeaseRecord: ...

    def update(self, name: str, expect_holder: str,
               record: LeaseRecord) -> bool: ...


class InMemoryLeaseStore:
    """Compare-and-swap lease store; ``expect_holder`` mismatches fail the
    update the way a stale resourceVersion fails a Lease PUT."""

    #: in-process contenders share the process's monotonic clock
    preferred_clock = staticmethod(time.monotonic)

    def __init__(self) -> None:
        self._leases: dict[str, LeaseRecord] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> LeaseRecord:
        with self._lock:
            return dataclasses.replace(
                self._leases.get(name) or LeaseRecord())

    def update(self, name: str, expect_holder: str,
               record: LeaseRecord) -> bool:
        with self._lock:
            current = self._leases.get(name) or LeaseRecord()
            if current.holder != expect_holder:
                return False
            self._leases[name] = dataclasses.replace(record)
            return True


class LeaderElector:
    """client-go leaderelection.LeaderElector semantics, tick-driven.

    Call :meth:`tick` on the component's cadence (or :meth:`run` in a
    thread): it acquires the lease when free/expired, renews while leading,
    and demotes itself if a renew fails or another holder appears.

    Clock domains: lease timestamps are compared across ALL contenders, so
    every process contending one lease must share a clock domain.  The
    default clock is taken from the store's ``preferred_clock``
    (``time.monotonic`` for the in-process store; ``time.time`` wall clock
    for :class:`RemoteLeaseStore`, whose contenders live in different
    processes where each process's monotonic epoch is meaningless).  An
    explicit ``clock=`` argument always wins — but passing a per-process
    monotonic clock with a cross-process store invites split-brain.
    """

    def __init__(
        self,
        store: LeaseStore,
        lease_name: str,
        identity: str,
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        on_new_leader: Optional[Callable[[str], None]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if clock is None:
            clock = getattr(store, "preferred_clock", time.monotonic)
        self.store = store
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.on_new_leader = on_new_leader
        self.clock = clock
        self._leading = False
        self._observed_leader = ""
        self._stopped = False

    def is_leader(self) -> bool:
        return self._leading

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def _observe(self, holder: str) -> None:
        if holder and holder != self._observed_leader:
            self._observed_leader = holder
            if self.on_new_leader:
                self.on_new_leader(holder)

    def tick(self) -> bool:
        """One tryAcquireOrRenew; returns is_leader afterwards."""
        if self._stopped:
            return False
        now = self.clock()
        lease = self.store.get(self.lease_name)
        if lease.holder == self.identity:
            # renew
            renewed = self.store.update(
                self.lease_name, self.identity, dataclasses.replace(
                    lease, renew_time=now))
            self._set_leading(renewed)
            self._observe(self.identity if renewed else lease.holder)
            return self._leading
        if lease.expired(now):
            acquired = self.store.update(
                self.lease_name, lease.holder, LeaseRecord(
                    holder=self.identity,
                    duration_seconds=self.lease_duration,
                    acquire_time=now, renew_time=now,
                    transitions=lease.transitions + 1))
            self._set_leading(acquired)
            if acquired:
                self._observe(self.identity)
            return self._leading
        # someone else holds a live lease
        self._set_leading(False)
        self._observe(lease.holder)
        return False

    def release(self) -> None:
        """Voluntary hand-off on clean shutdown (client-go ReleaseOnCancel):
        clear the holder so a follower acquires without waiting out the
        lease."""
        self._stopped = True
        if self._leading:
            lease = self.store.get(self.lease_name)
            if lease.holder == self.identity:
                self.store.update(
                    self.lease_name, self.identity, LeaseRecord(
                        duration_seconds=lease.duration_seconds,
                        transitions=lease.transitions))
        self._set_leading(False)

    def run(self, stop: threading.Event,
            sleep: Callable[[float], None] = time.sleep) -> None:
        """Loop tick() every retry_period until stop is set."""
        while not stop.is_set():
            self.tick()
            sleep(self.retry_period)
        self.release()


def leader_gated(elector: Optional[LeaderElector],
                 fn: Callable, *args, **kwargs):
    """Run a control-loop step only while leading (controller-runtime
    managers simply don't start controllers on non-leaders); None elector
    means leader election is disabled (--enable-leader-election=false)."""
    if elector is not None and not elector.tick():
        return None
    return fn(*args, **kwargs)


# ---------------------------------------------------------------------------
# Cross-process leases over the wire transport
# ---------------------------------------------------------------------------
#
# The reference's Leases are apiserver objects precisely so that two
# scheduler PROCESSES on different hosts can contend one lock.  Here the
# state server plays the apiserver role: it owns the authoritative
# InMemoryLeaseStore and serves LEASE_GET / LEASE_UPDATE frames; each
# contender runs a LeaderElector over a RemoteLeaseStore.  CAS atomicity
# lives server-side (one store, one lock), exactly like a Lease PUT with a
# resourceVersion precondition.


def _record_doc(name: str, expect_holder: str, rec: LeaseRecord) -> dict:
    return {
        "name": name, "expect_holder": expect_holder,
        "holder": rec.holder,
        "duration_seconds": float(rec.duration_seconds),
        "acquire_time": float(rec.acquire_time),
        "renew_time": float(rec.renew_time),
        "transitions": int(rec.transitions),
    }


class LeaseService:
    """Server side: expose a LeaseStore on the framed transport
    (cmd/koord-manager/main.go --leader-elect-resource-lock=leases)."""

    def __init__(self, store: Optional[LeaseStore] = None):
        self.store: LeaseStore = store or InMemoryLeaseStore()

    def attach(self, server) -> None:
        from koordinator_tpu.transport.wire import FrameType

        server.register(FrameType.LEASE_GET, self._get)
        server.register(FrameType.LEASE_UPDATE, self._update)

    def _get(self, doc: dict, arrays):
        rec = self.store.get(doc["name"])
        out = _record_doc(doc["name"], "", rec)
        out.pop("expect_holder")
        return out, None

    def _update(self, doc: dict, arrays):
        rec = LeaseRecord(
            holder=doc["holder"],
            duration_seconds=float(doc["duration_seconds"]),
            acquire_time=float(doc["acquire_time"]),
            renew_time=float(doc["renew_time"]),
            transitions=int(doc["transitions"]),
        )
        ok = self.store.update(doc["name"], doc["expect_holder"], rec)
        return {"ok": bool(ok)}, None


class RemoteLeaseStore:
    """Client-side LeaseStore over an RpcClient.

    Failure posture is fail-closed for leadership: a transport error on
    ``update`` returns False (a leader that cannot renew demotes itself —
    client-go's renew-deadline behavior), and on ``get`` returns an empty
    record, which is safe because acquiring still requires a successful
    CAS against the server-side store.
    """

    #: contenders are separate PROCESSES: they must evaluate lease expiry
    #: on a shared clock, and a per-process monotonic epoch is not one —
    #: a host up 30 days would see every other host's renews as ancient
    #: and steal a live lease (split-brain).  Wall clock is the same
    #: domain the reference's apiserver Lease timestamps live in.
    preferred_clock = staticmethod(time.time)

    def __init__(self, client):
        self.client = client

    def get(self, name: str) -> LeaseRecord:
        from koordinator_tpu.transport.channel import RpcError
        from koordinator_tpu.transport.wire import FrameType

        try:
            _, doc, _ = self.client.call(
                FrameType.LEASE_GET, {"name": name})
        except RpcError:
            return LeaseRecord()
        return LeaseRecord(
            holder=doc.get("holder", ""),
            duration_seconds=float(doc.get("duration_seconds", 15.0)),
            acquire_time=float(doc.get("acquire_time", 0.0)),
            renew_time=float(doc.get("renew_time", 0.0)),
            transitions=int(doc.get("transitions", 0)),
        )

    def update(self, name: str, expect_holder: str,
               record: LeaseRecord) -> bool:
        from koordinator_tpu.transport.channel import RpcError
        from koordinator_tpu.transport.wire import FrameType

        try:
            _, doc, _ = self.client.call(
                FrameType.LEASE_UPDATE,
                _record_doc(name, expect_holder, record))
        except RpcError:
            return False
        return bool(doc.get("ok"))
