"""The wire-protocol layer: framed RPC + incremental cluster-state sync.

The reference's deployment seams are all remote-procedure boundaries:
CRI/NRI hook gRPC (``apis/runtime/v1alpha1/api.proto:148``, ``pkg/koordlet/
runtimehooks/nri/server.go``), the kubelet HTTPS stub, and apiserver watch
streams feeding informers (SURVEY.md §5 "distributed communication
backend"). The TPU rebuild's equivalent (SURVEY.md §7 step 4) is the
sidecar bridge between the protocol shell and the device-resident solver:
a snapshot + resource-version'd delta stream so the solver's device
buffers are updated by scatter, never rebuilt, plus solve/hook RPCs over
the same framed transport.
"""

from koordinator_tpu.transport.wire import (  # noqa: F401
    Frame,
    FrameType,
    decode_payload,
    encode_payload,
)
from koordinator_tpu.transport.channel import (  # noqa: F401
    DeadlineExpired,
    RpcClient,
    RpcDeadlineError,
    RpcError,
    RpcRemoteError,
    RpcServer,
)
from koordinator_tpu.transport.deltasync import (  # noqa: F401
    DeltaLog,
    ResyncRequired,
    StateSyncClient,
    StateSyncService,
    UnknownNodeError,
)
from koordinator_tpu.transport.faults import (  # noqa: F401
    ASYM_SEND,
    PARTITION,
    REFUSE,
    FaultConfig,
    FaultInjector,
    FaultSchedule,
    StormWindow,
    domains_from_labels,
)
from koordinator_tpu.transport.retry import (  # noqa: F401
    CircuitBreaker,
    RetryPolicy,
    RetrySchedule,
)
