"""Frame + payload encoding.

One frame on the wire:

    magic   u16  0x4B54 ("KT")
    version u8   wire version (1)
    type    u8   FrameType
    req_id  u32  request/response correlation id
    length  u32  payload byte length
    payload

The payload is a control document plus an array blob:

    json_len u32 | json utf-8 | raw array section

The json document carries small structured fields; numpy arrays ride in
the raw section, referenced from ``doc["__arrays__"]`` manifest entries
``{key, dtype, shape, offset, nbytes}`` — so the hot path (node/pod
resource tensors) moves as raw little-endian bytes, not text. This is the
same split gRPC+proto gives the reference: tiny schema-ed control data,
binary tensors.

Two version numbers govern the wire:

- ``VERSION`` (header byte) is the FRAMING version — header layout +
  payload packing.  A mismatch is unrecoverable and fails at read_frame.
- ``PROTOCOL_VERSION`` is the MESSAGE protocol — the set of frame types
  and their document schemas (the role ``apis/runtime/v1alpha1/api.proto``
  plays for the reference).  It is negotiated in HELLO: a client
  advertises its protocol and the server replies with
  ``min(peer, local)`` when the peer is inside
  ``[MIN_PROTOCOL_VERSION, PROTOCOL_VERSION]``, rejecting anything
  outside the window with an ERROR instead of silently mis-decoding
  (history: v1 ad-hoc docs; v2 adds typed REQUEST_SCHEMAS, the
  ``proto`` field in HELLO, and lease frames; v3 adds STATE_PUSH —
  client-originated state events, the direction a non-Python scheduler
  plugin feeds its informer view into the sidecar; v4 adds the columnar
  event codec for the hot frame types — deltasync DELTA/SNAPSHOT event
  lists ride as columnar numpy blocks instead of per-event JSON docs,
  see docs/wire_protocol.md).

``REQUEST_SCHEMAS`` types each schema'd frame's json document;
``validate_doc`` is enforced server-side on every request frame, so a
peer built against a different protocol fails loud at the boundary.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import struct
import time

import numpy as np

MAGIC = 0x4B54
VERSION = 1
PROTOCOL_VERSION = 4
#: oldest message protocol this build still speaks.  HELLO negotiates
#: the session protocol to ``min(peer, PROTOCOL_VERSION)`` as long as
#: the peer advertises at least this; below it (or above
#: PROTOCOL_VERSION) the server rejects with "incompatible".  v3 peers
#: keep the per-event JSON event lists; v4 peers get the columnar
#: event codec on DELTA/SNAPSHOT.
MIN_PROTOCOL_VERSION = 3
_HEADER = struct.Struct("<HBBII")
MAX_PAYLOAD = 256 << 20  # 256 MiB guard against corrupt length words

#: zero-copy decode policy (ISSUE 19 satellite): a decoded array may
#: alias the frame payload (np.frombuffer view) ONLY when it is both
#: big enough that the copy would cost real time AND a large share of
#: the payload — otherwise the view pins the whole payload buffer for
#: the lifetime of a tiny array (a 4-byte rv field keeping a multi-MB
#: snapshot alive).  Small or minority arrays are copied; the payload
#: buffer is then released as soon as decode returns.
ZERO_COPY_MIN_BYTES = 64 << 10
ZERO_COPY_MIN_SHARE = 0.5


class FrameType(enum.IntEnum):
    HELLO = 1           # client: {last_rv, proto}; reply SNAPSHOT or ACK
    SNAPSHOT = 2        # full state dump @ rv
    DELTA = 3           # incremental changes (rv-ordered)
    ACK = 4             # generic ok, {rv} for sync acks
    ERROR = 5           # {message, resync: bool}
    SOLVE_REQUEST = 6   # run a scheduling round
    SOLVE_RESPONSE = 7  # assignments/failures
    HOOK_REQUEST = 8    # runtime hook dispatch (api.proto:148 shapes)
    HOOK_RESPONSE = 9
    PING = 10
    LEASE_GET = 11      # {name} -> lease record fields
    LEASE_UPDATE = 12   # CAS write: {name, expect_holder, <record>} -> {ok}
    STATE_PUSH = 13     # client-originated state event -> {rv}; the
                        # Go-plugin/informer -> sidecar feed direction


class WireSchemaError(ValueError):
    """A request document does not match its frame's schema — the loud
    failure mode for protocol skew between peers."""


#: REQUEST document schemas: field -> (allowed type(s), required).
#: Unknown extra fields are allowed (minor additions stay compatible);
#: a missing required field or a type mismatch is a WireSchemaError.
REQUEST_SCHEMAS: dict[FrameType, dict[str, tuple]] = {
    FrameType.HELLO: {
        "last_rv": (int, True),
        "proto": (int, True),
        # service boot-epoch the client last synced from; absent on
        # first contact and from older peers (rv-only resync semantics)
        "instance": (str, False),
    },
    FrameType.SOLVE_REQUEST: {},
    FrameType.HOOK_REQUEST: {
        "hook": (str, True),
        "pod_meta": (dict, False),
        "container_meta": (dict, False),
        "labels": (dict, False),
        "annotations": (dict, False),
        "cgroup_parent": (str, False),
        "resources": (dict, False),
        "envs": (dict, False),
    },
    FrameType.LEASE_GET: {
        "name": (str, True),
    },
    FrameType.LEASE_UPDATE: {
        "name": (str, True),
        "expect_holder": (str, True),
        "holder": (str, True),
        "duration_seconds": ((int, float), True),
        "acquire_time": ((int, float), True),
        "renew_time": ((int, float), True),
        "transitions": (int, True),
    },
    FrameType.STATE_PUSH: {
        "kind": (str, True),
        "name": (str, True),
        # event-kind-specific fields (labels, priority, quota, ...) ride
        # as extras; resource vectors ride the raw array section
    },
}


#: every array key any STATE_PUSH kind accepts (deltasync
#: _handle_state_push's require_vector calls) — ONE set shared with the
#: HTTP gateway's JSON-to-array lift, so a new kind's array field cannot
#: be accepted by the framed path while the HTTP path silently drops it
#: (the sys_usage/hp_usage drift the r5 review caught)
STATE_PUSH_ARRAY_KEYS = ("allocatable", "usage", "agg_usage",
                         "prod_usage", "sys_usage", "hp_usage",
                         "hp_request", "hp_max_used_req",
                         "requests")


def check_field_type(val, types) -> bool:
    """isinstance with the wire rule that bool (an int subclass) never
    satisfies a numeric field unless bool is listed explicitly — one
    copy of the rule for frame validation and state-push field checks."""
    if isinstance(val, bool) and bool not in (
            types if isinstance(types, tuple) else (types,)):
        return False
    return isinstance(val, types)


def validate_doc(ftype: FrameType, doc: dict) -> None:
    """Check a request document against REQUEST_SCHEMAS (no-op for
    unschema'd frame types)."""
    schema = REQUEST_SCHEMAS.get(ftype)
    if schema is None:
        return
    for field, (types, required) in schema.items():
        if field not in doc:
            if required:
                raise WireSchemaError(
                    f"{ftype.name}: missing required field {field!r} "
                    f"(peer protocol skew? local proto="
                    f"{PROTOCOL_VERSION})")
            continue
        val = doc[field]
        if not check_field_type(val, types):
            raise WireSchemaError(
                f"{ftype.name}: field {field!r} has type "
                f"{type(val).__name__}, expected {types}")


@dataclasses.dataclass(frozen=True)
class Frame:
    type: FrameType
    request_id: int
    payload: bytes

    def encode(self) -> bytes:
        return _HEADER.pack(MAGIC, VERSION, int(self.type),
                            self.request_id, len(self.payload)) + self.payload


def encode_payload(doc: dict, arrays: dict[str, np.ndarray] | None = None) -> bytes:
    """Pack a json-able doc + named numpy arrays into one payload.

    Instrumented (ISSUE 18): codec wall + payload bytes feed the
    ``wire_codec_duration_seconds`` / ``wire_payload_bytes``
    histograms and, when the timeline recorder is armed, a
    ``json_codec`` segment — the codec's slice of the host-wait
    attribution."""
    t0 = time.perf_counter()
    blobs = []
    manifest = []
    offset = 0
    for key, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        raw = a.tobytes()
        manifest.append({
            "key": key, "dtype": a.dtype.str, "shape": list(a.shape),
            "offset": offset, "nbytes": len(raw),
        })
        blobs.append(raw)
        offset += len(raw)
    out = dict(doc)
    if manifest:
        out["__arrays__"] = manifest
    j = json.dumps(out, separators=(",", ":")).encode()
    payload = struct.pack("<I", len(j)) + j + b"".join(blobs)
    _observe_codec("encode", t0, len(payload))
    return payload


def decode_payload(payload: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    t0 = time.perf_counter()
    (json_len,) = struct.unpack_from("<I", payload, 0)
    doc = json.loads(payload[4:4 + json_len].decode())
    arrays: dict[str, np.ndarray] = {}
    base = 4 + json_len
    manifest = doc.pop("__arrays__", [])
    if not isinstance(manifest, list):
        raise WireSchemaError(
            f"__arrays__ manifest must be a list, got "
            f"{type(manifest).__name__}")
    for entry in manifest:
        try:
            start = base + int(entry["offset"])
            nbytes = int(entry["nbytes"])
            dtype = np.dtype(entry["dtype"])
            shape = entry["shape"]
            count = (int(np.prod(shape, dtype=np.int64)) if shape else 1)
            if (start < 4 + json_len or start + nbytes > len(payload)
                    or count * dtype.itemsize != nbytes):
                raise WireSchemaError(
                    f"array manifest entry {entry.get('key')!r} points "
                    f"outside the payload (offset={entry['offset']}, "
                    f"nbytes={nbytes}, payload={len(payload)})")
            arr = np.frombuffer(payload, dtype=dtype, count=count,
                                offset=start).reshape(shape)
        except WireSchemaError:
            raise
        except (KeyError, TypeError, ValueError, OverflowError) as e:
            raise WireSchemaError(
                f"corrupt array manifest entry {entry!r}: {e}") from e
        if (nbytes < ZERO_COPY_MIN_BYTES
                or nbytes < ZERO_COPY_MIN_SHARE * len(payload)):
            # copy-above-threshold: don't let a small view pin the
            # whole payload buffer (see ZERO_COPY_MIN_BYTES)
            arr = arr.copy()
        arrays[entry["key"]] = arr
    _observe_codec("decode", t0, len(payload))
    return doc, arrays


def _observe_codec(op: str, t0: float, nbytes: int) -> None:
    from koordinator_tpu import metrics, timeline

    t1 = time.perf_counter()
    metrics.wire_codec_seconds.observe(t1 - t0, labels={"op": op})
    metrics.wire_payload_bytes.observe(float(nbytes), labels={"op": op})
    if timeline.RECORDER.enabled:
        timeline.RECORDER.add(t0, t1, "json_codec", f"wire.{op}")


def pack_str_column(values: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """Columnar string packing for the v2 event codec: a list of
    strings becomes ``(lengths int32, utf-8 blob uint8)`` — two numpy
    arrays that ride the raw array section instead of N JSON string
    fields.  The inverse is :func:`unpack_str_column`."""
    encoded = [v.encode() for v in values]
    lens = np.asarray([len(b) for b in encoded], dtype=np.int32)
    blob = (np.frombuffer(b"".join(encoded), dtype=np.uint8)
            if encoded else np.zeros(0, dtype=np.uint8))
    return lens, blob


def unpack_str_column(lens: np.ndarray, blob: np.ndarray) -> list[str]:
    """Inverse of :func:`pack_str_column`."""
    raw = blob.tobytes()
    ends = np.cumsum(lens.astype(np.int64)) if len(lens) else lens
    if len(lens) and int(ends[-1]) != len(raw):
        raise WireSchemaError(
            f"string column blob is {len(raw)} bytes but lengths sum "
            f"to {int(ends[-1])}")
    out: list[str] = []
    pos = 0
    for end in ends.tolist():
        out.append(raw[pos:end].decode())
        pos = end
    return out


def read_frame(recv_exact) -> Frame:
    """Read one frame via a recv_exact(n)->bytes callable. Raises
    ConnectionError on short reads / bad magic."""
    header = recv_exact(_HEADER.size)
    magic, version, ftype, req_id, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ConnectionError(f"bad frame magic {magic:#x}")
    if version != VERSION:
        raise ConnectionError(f"unsupported wire version {version}")
    if length > MAX_PAYLOAD:
        raise ConnectionError(f"oversized frame ({length} bytes)")
    payload = recv_exact(length) if length else b""
    return Frame(FrameType(ftype), req_id, payload)
