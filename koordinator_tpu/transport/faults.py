"""Deterministic, seeded fault injection at the frame/socket seams.

The transport's failure modes in production — sidecars crashing mid-
write, sockets black-holing, watch feeds stalling — are all reproduced
here as *scheduled* faults: a :class:`FaultInjector` owns one seeded
``random.Random`` and every fault decision is a draw from it, so a
failing chaos run replays exactly from its seed (tools/soak.sh prints
the seed on failure).

Injection points (all off by default — a ``None`` injector costs one
attribute check):

- ``RpcClient.connect``      -> :meth:`FaultInjector.on_connect`
  (connect refusal)
- ``RpcClient.call`` send    -> :meth:`FaultInjector.outbound_cut`
  (mid-write truncation; the socket is severed after the partial write)
- reader ``recv`` loops      -> :meth:`FaultInjector.on_read`
  (slow-drip reads)
- server ``_Conn`` sends     -> :meth:`FaultInjector.outbound_action`
  (connection sever, mid-write truncation on any frame; drop / delay /
  duplication / reordering on PUSH frames only — responses stay
  correlated, matching the issue's "frame delay/duplication/reordering
  on pushes")

``heal()`` flips the injector off atomically — the chaos soak's
"faults heal, system reconverges" phase.  ``injected`` counts every
fault actually fired, by kind, so tests can assert the schedule was
exercised at all.

Beyond the independent per-connection probabilities, the injector also
models **correlated** faults (the drill engine's storms): connections
carry a *fault domain* tag (a rack/zone group derived from the network
topology — see :func:`domains_from_labels`), and one storm event severs
or refuses every connection in the domain together.  Storms come in
three modes:

- ``partition`` — live connections in the domain are severed at storm
  start and new connects are refused (full network cut);
- ``refuse``    — only new connects fail; established connections drain
  (the half-dead switch that still forwards existing flows);
- ``asym_send`` — outbound *calls* from the domain fail but inbound
  pushes still arrive (the asymmetric partition: the peer can talk to
  you, you cannot talk to the peer).

Storms are driven either manually (:meth:`FaultInjector.start_storm` /
:meth:`FaultInjector.end_storm`) or by a time-phased
:class:`FaultSchedule` of :class:`StormWindow` entries evaluated against
an explicit virtual clock (:meth:`FaultInjector.advance_to`) — no
wall-clock reads, so a drill replays its exact storm membership and
timing from one seed under a fake clock.

``heal()`` ends every storm, detaches the schedule, and resets any
registered circuit breakers (:meth:`FaultInjector.register_breaker`) so
healed peers are probed immediately instead of waiting out a full open
window.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import threading
import time

#: storm modes, in increasing severity (the merge when a domain sits in
#: overlapping windows keeps the severest)
REFUSE = "refuse"
ASYM_SEND = "asym_send"
PARTITION = "partition"

_MODE_SEVERITY = {REFUSE: 1, ASYM_SEND: 2, PARTITION: 3}


def domains_from_labels(labels_by_node: dict[str, dict[str, str]],
                        key: str = "rack") -> dict[str, list[str]]:
    """Group nodes into fault domains by a topology label.

    ``{"n0": {"rack": "r1"}, "n1": {"rack": "r1"}}`` with ``key="rack"``
    yields ``{"rack:r1": ["n0", "n1"]}`` — the domain names are what
    connection owners tag their clients with (``RpcClient(...,
    fault_domain="rack:r1")``) and what storm windows name.  Nodes
    missing the label are skipped (they sit outside the topology and no
    correlated event can take them out together)."""
    out: dict[str, list[str]] = {}
    for name, labels in labels_by_node.items():
        value = (labels or {}).get(key)
        if value is None:
            continue
        out.setdefault(f"{key}:{value}", []).append(name)
    for members in out.values():
        members.sort()
    return out


@dataclasses.dataclass(frozen=True)
class StormWindow:
    """One scheduled correlated-fault event: every connection tagged
    with one of ``domains`` is blocked with ``mode`` for virtual time
    ``[start, end)``."""

    start: float
    end: float
    domains: frozenset[str]
    mode: str = PARTITION

    def __post_init__(self):
        if self.mode not in _MODE_SEVERITY:
            raise ValueError(f"unknown storm mode {self.mode!r}")
        if not self.end > self.start:
            raise ValueError(
                f"empty storm window [{self.start}, {self.end})")
        object.__setattr__(self, "domains", frozenset(self.domains))

    def active_at(self, t: float) -> bool:
        return self.start <= t < self.end


class FaultSchedule:
    """A time-phased list of storm windows, evaluated on a virtual
    clock.  Windows may overlap (a zone partition spanning a rack flap);
    per domain the severest active mode wins."""

    def __init__(self, windows=()):
        self.windows: tuple[StormWindow, ...] = tuple(
            sorted(windows, key=lambda w: (w.start, w.end)))

    def active(self, t: float) -> list[StormWindow]:
        return [w for w in self.windows if w.active_at(t)]

    def blocked(self, t: float) -> dict[str, str]:
        """domain -> mode for every domain inside a window at ``t``."""
        out: dict[str, str] = {}
        for w in self.active(t):
            for d in w.domains:
                cur = out.get(d)
                if cur is None or (_MODE_SEVERITY[w.mode]
                                   > _MODE_SEVERITY[cur]):
                    out[d] = w.mode
        return out

    def horizon(self) -> float:
        return max((w.end for w in self.windows), default=0.0)

    def boundaries(self) -> list[float]:
        """Sorted distinct start/end times — fake-clock tests step the
        injector exactly through these."""
        ts = {w.start for w in self.windows} | {w.end for w in self.windows}
        return sorted(ts)

    @staticmethod
    def flap_train(domains, start: float, up_s: float, down_s: float,
                   flaps: int, mode: str = PARTITION
                   ) -> tuple[StormWindow, ...]:
        """``flaps`` repeated storms of ``up_s`` seconds separated by
        ``down_s`` healthy gaps — the flapping-ToR pattern that breaker
        pacing and rv-gap resync must both survive."""
        out = []
        t = start
        for _ in range(max(0, flaps)):
            out.append(StormWindow(t, t + up_s, frozenset(domains), mode))
            t += up_s + down_s
        return tuple(out)

    @classmethod
    def generate(cls, seed: int, domains, horizon_s: float,
                 storms: int = 3, mean_gap_s: float = 2.0,
                 mean_hold_s: float = 1.0, max_width: int = 1,
                 modes=(PARTITION,)) -> "FaultSchedule":
        """Seeded storm schedule: every draw (timing, membership, mode)
        comes from one ``random.Random(seed)``, so the exact storm
        membership and timing replay from the seed alone."""
        domains = sorted(domains)
        rng = random.Random(seed)
        out: list[StormWindow] = []
        t = 0.0
        for _ in range(max(0, storms)):
            t += rng.expovariate(1.0 / mean_gap_s)
            hold = rng.expovariate(1.0 / mean_hold_s)
            if t >= horizon_s:
                break
            end = min(t + hold, horizon_s)
            if not end > t:
                break
            width = rng.randint(1, max(1, min(max_width, len(domains))))
            members = rng.sample(domains, width)
            mode = rng.choice(list(modes))
            out.append(StormWindow(t, end, frozenset(members), mode))
            t = end
        return cls(out)


@dataclasses.dataclass
class FaultConfig:
    """Per-decision probabilities.  All default 0.0 (= never)."""

    #: client connect() raises ConnectionRefusedError
    connect_refuse_p: float = 0.0
    #: outbound frame (any): sever the connection before writing
    send_sever_p: float = 0.0
    #: outbound frame (any): write a partial prefix, then sever —
    #: the peer's framing desyncs and its read loop dies
    send_truncate_p: float = 0.0
    #: push frame: silently drop (the black-holed watch event — the
    #: client's rv-gap detection is what recovers from this)
    push_drop_p: float = 0.0
    #: push frame: delay before writing
    push_delay_p: float = 0.0
    push_delay_ms: float = 10.0
    #: push frame: write twice (the client's rv guard must dedup)
    push_duplicate_p: float = 0.0
    #: push frame: hold, and emit after the NEXT outbound frame
    #: (rv-order inversion on the wire)
    push_reorder_p: float = 0.0
    #: each recv() chunk: sleep first (slow-drip read)
    read_drip_p: float = 0.0
    read_drip_ms: float = 2.0
    #: scheduler Solve phase: sleep before dispatching the solve (the
    #: synthetic latency regression the SLO burn-rate engine must
    #: detect — a real one would be a recompile storm or device
    #: contention; the injected delay is indistinguishable to the
    #: scheduling_duration_seconds observer)
    solve_delay_p: float = 0.0
    solve_delay_ms: float = 0.0


class FaultInjector:
    """Seeded fault scheduler shared by any number of connections.

    Thread-safe: the rng is guarded so concurrent sender/reader threads
    draw a single deterministic sequence (the *schedule* is reproducible
    per seed; which thread consumes which draw still depends on timing,
    which is exactly the nondeterminism chaos testing wants to shake)."""

    def __init__(self, seed: int = 0, config: FaultConfig | None = None,
                 sleep=time.sleep, schedule: "FaultSchedule | None" = None):
        self.seed = seed
        self.config = config or FaultConfig()
        self.enabled = True
        self.injected: collections.Counter = collections.Counter()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sleep = sleep
        #: correlated-fault state (all guarded by _lock): manual storms,
        #: schedule-driven storms, and the registries the storms act on
        self.schedule = schedule
        self.virtual_time = 0.0
        self._manual_blocked: dict[str, str] = {}     # domain -> mode
        self._sched_blocked: dict[str, str] = {}      # domain -> mode
        self._active_windows: set[int] = set()        # indices into schedule
        self._conns: dict[str, list] = {}             # domain -> [sever_fn]
        self._breakers: list = []
        self._heal_listeners: list = []

    # -- correlated fault domains -------------------------------------------

    def register_conn(self, domain: str, sever_fn) -> None:
        """A live connection in ``domain`` registers how to sever it; a
        partition storm over the domain invokes every registered fn.  A
        connection created INTO an already-stormed partition is severed
        immediately (it raced past on_connect before the storm began, or
        the owner dialed a still-listening peer across the cut)."""
        if not domain:
            return
        sever_now = False
        with self._lock:
            self._conns.setdefault(domain, []).append(sever_fn)
            sever_now = self._domain_mode_locked(domain) == PARTITION
        if sever_now:
            try:
                sever_fn()
            except Exception:
                pass

    def unregister_conn(self, domain: str, sever_fn) -> None:
        with self._lock:
            fns = self._conns.get(domain)
            if fns and sever_fn in fns:
                fns.remove(sever_fn)
            if not fns and domain in self._conns:
                del self._conns[domain]

    def register_breaker(self, breaker) -> None:
        """heal() resets registered breakers so a healed peer is probed
        immediately instead of waiting out the remaining open window."""
        with self._lock:
            if breaker not in self._breakers:
                self._breakers.append(breaker)

    def add_heal_listener(self, fn) -> None:
        with self._lock:
            self._heal_listeners.append(fn)

    # koordlint: guarded-by(self._lock)
    def _domain_mode_locked(self, domain: str) -> str | None:
        a = self._manual_blocked.get(domain)
        b = self._sched_blocked.get(domain)
        if a is None:
            return b
        if b is None:
            return a
        return a if _MODE_SEVERITY[a] >= _MODE_SEVERITY[b] else b

    def domain_mode(self, domain: str) -> str | None:
        """Active storm mode blocking ``domain``, or None (healthy)."""
        with self._lock:
            return self._domain_mode_locked(domain)

    def start_storm(self, domains, mode: str = PARTITION) -> None:
        """Begin a manual correlated storm over ``domains``.  Partition
        mode severs every registered connection in the domains NOW —
        deterministically, not probabilistically."""
        if mode not in _MODE_SEVERITY:
            raise ValueError(f"unknown storm mode {mode!r}")
        to_sever = []
        with self._lock:
            for d in domains:
                cur = self._manual_blocked.get(d)
                if cur is None or _MODE_SEVERITY[mode] > _MODE_SEVERITY[cur]:
                    self._manual_blocked[d] = mode
                if mode == PARTITION:
                    to_sever.extend(self._conns.get(d, ()))
        self._count(f"storm_{mode}")
        for fn in to_sever:
            try:
                fn()
            except Exception:
                pass

    def end_storm(self, domains=None) -> None:
        """End manual storms for ``domains`` (None = all)."""
        with self._lock:
            if domains is None:
                self._manual_blocked.clear()
            else:
                for d in domains:
                    self._manual_blocked.pop(d, None)

    def advance_to(self, t: float) -> None:
        """Advance the schedule's virtual clock to ``t`` and apply any
        window transitions: domains entering a partition window get
        their live connections severed; domains whose windows all closed
        are unblocked.  Drives nothing when no schedule is attached."""
        to_sever = []
        started_kinds = []
        with self._lock:
            self.virtual_time = t
            if self.schedule is None:
                return
            now = self.schedule.blocked(t)
            active = {i for i, w in enumerate(self.schedule.windows)
                      if w.active_at(t)}
            for i in active - self._active_windows:
                w = self.schedule.windows[i]
                self.injected[f"storm_{w.mode}"] += 1
                started_kinds.append(f"storm_{w.mode}")
                if w.mode == PARTITION:
                    for d in w.domains:
                        to_sever.extend(self._conns.get(d, ()))
            self._active_windows = active
            self._sched_blocked = now
        if started_kinds:
            # metric outside the lock (the registry takes its own)
            from koordinator_tpu import metrics
            for kind in started_kinds:
                metrics.faults_injected_total.inc(labels={"kind": kind})
        for fn in to_sever:
            try:
                fn()
            except Exception:
                pass

    def heal(self) -> None:
        """Stop injecting (the soak's recovery phase).  Already-held
        reordered frames still flush through their connections.  Ends
        every storm (manual and scheduled), detaches the schedule, and
        resets registered breakers so healed peers are probed NOW."""
        self.enabled = False
        with self._lock:
            self._manual_blocked.clear()
            self._sched_blocked.clear()
            self._active_windows.clear()
            self.schedule = None
            breakers = list(self._breakers)
            listeners = list(self._heal_listeners)
        for b in breakers:
            reset = getattr(b, "reset", None)
            if reset is not None:
                reset()
        for fn in listeners:
            try:
                fn()
            except Exception:
                pass

    def _hit(self, p: float) -> bool:
        if not self.enabled or p <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < p

    def _count(self, kind: str) -> None:
        from koordinator_tpu import metrics

        self.injected[kind] += 1
        metrics.faults_injected_total.inc(labels={"kind": kind})

    # -- client seams --------------------------------------------------------

    def on_connect(self, domain: str = "") -> None:
        if domain:
            mode = self.domain_mode(domain)
            if mode in (PARTITION, REFUSE):
                self._count("domain_refuse")
                raise ConnectionRefusedError(
                    f"fault injection: domain {domain!r} stormed ({mode})")
        if self._hit(self.config.connect_refuse_p):
            self._count("connect_refuse")
            raise ConnectionRefusedError("fault injection: connect refused")

    def outbound_domain(self, domain: str) -> str | None:
        """Correlated-fault action for a client's outbound call from
        ``domain``: "sever" (partition — tear the connection down),
        "block" (asym_send — fail the call, keep the stream so inbound
        pushes still arrive), or None."""
        if not domain:
            return None
        mode = self.domain_mode(domain)
        if mode == PARTITION:
            self._count("domain_sever")
            return "sever"
        if mode == ASYM_SEND:
            self._count("domain_block")
            return "block"
        return None

    def outbound_cut(self, nbytes: int) -> int | None:
        """Byte count to truncate a client write at, or None (no fault)."""
        if self._hit(self.config.send_truncate_p):
            self._count("client_truncate")
            with self._lock:
                return self._rng.randrange(1, max(nbytes, 2))
        return None

    def on_read(self) -> None:
        if self._hit(self.config.read_drip_p):
            self._count("read_drip")
            self._sleep(self.config.read_drip_ms / 1000.0)

    # -- scheduler seam ------------------------------------------------------

    def on_solve(self) -> None:
        """Called at the top of the scheduler's Solve phase when an
        injector is attached (``Scheduler(faults=...)``): a hit sleeps
        ``solve_delay_ms``, landing squarely in the round's
        ``scheduling_duration_seconds{phase="Solve"}`` observation."""
        if self._hit(self.config.solve_delay_p):
            self._count("solve_delay")
            self._sleep(self.config.solve_delay_ms / 1000.0)

    # -- server _Conn seam ---------------------------------------------------

    def outbound_action(self, is_push: bool) -> str | None:
        """One of None / "sever" / "truncate" / "drop" / "delay" /
        "duplicate" / "reorder" for a server-side outbound frame.
        Evaluated in severity order; at most one fault per frame."""
        if self._hit(self.config.send_sever_p):
            self._count("sever")
            return "sever"
        if self._hit(self.config.send_truncate_p):
            self._count("truncate")
            return "truncate"
        if is_push:
            if self._hit(self.config.push_drop_p):
                self._count("push_drop")
                return "drop"
            if self._hit(self.config.push_delay_p):
                self._count("push_delay")
                return "delay"
            if self._hit(self.config.push_duplicate_p):
                self._count("push_duplicate")
                return "duplicate"
            if self._hit(self.config.push_reorder_p):
                self._count("push_reorder")
                return "reorder"
        return None

    def truncate_at(self, nbytes: int) -> int:
        with self._lock:
            return self._rng.randrange(1, max(nbytes, 2))

    def delay(self) -> None:
        self._sleep(self.config.push_delay_ms / 1000.0)
