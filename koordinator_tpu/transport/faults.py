"""Deterministic, seeded fault injection at the frame/socket seams.

The transport's failure modes in production — sidecars crashing mid-
write, sockets black-holing, watch feeds stalling — are all reproduced
here as *scheduled* faults: a :class:`FaultInjector` owns one seeded
``random.Random`` and every fault decision is a draw from it, so a
failing chaos run replays exactly from its seed (tools/soak.sh prints
the seed on failure).

Injection points (all off by default — a ``None`` injector costs one
attribute check):

- ``RpcClient.connect``      -> :meth:`FaultInjector.on_connect`
  (connect refusal)
- ``RpcClient.call`` send    -> :meth:`FaultInjector.outbound_cut`
  (mid-write truncation; the socket is severed after the partial write)
- reader ``recv`` loops      -> :meth:`FaultInjector.on_read`
  (slow-drip reads)
- server ``_Conn`` sends     -> :meth:`FaultInjector.outbound_action`
  (connection sever, mid-write truncation on any frame; drop / delay /
  duplication / reordering on PUSH frames only — responses stay
  correlated, matching the issue's "frame delay/duplication/reordering
  on pushes")

``heal()`` flips the injector off atomically — the chaos soak's
"faults heal, system reconverges" phase.  ``injected`` counts every
fault actually fired, by kind, so tests can assert the schedule was
exercised at all.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import threading
import time


@dataclasses.dataclass
class FaultConfig:
    """Per-decision probabilities.  All default 0.0 (= never)."""

    #: client connect() raises ConnectionRefusedError
    connect_refuse_p: float = 0.0
    #: outbound frame (any): sever the connection before writing
    send_sever_p: float = 0.0
    #: outbound frame (any): write a partial prefix, then sever —
    #: the peer's framing desyncs and its read loop dies
    send_truncate_p: float = 0.0
    #: push frame: silently drop (the black-holed watch event — the
    #: client's rv-gap detection is what recovers from this)
    push_drop_p: float = 0.0
    #: push frame: delay before writing
    push_delay_p: float = 0.0
    push_delay_ms: float = 10.0
    #: push frame: write twice (the client's rv guard must dedup)
    push_duplicate_p: float = 0.0
    #: push frame: hold, and emit after the NEXT outbound frame
    #: (rv-order inversion on the wire)
    push_reorder_p: float = 0.0
    #: each recv() chunk: sleep first (slow-drip read)
    read_drip_p: float = 0.0
    read_drip_ms: float = 2.0
    #: scheduler Solve phase: sleep before dispatching the solve (the
    #: synthetic latency regression the SLO burn-rate engine must
    #: detect — a real one would be a recompile storm or device
    #: contention; the injected delay is indistinguishable to the
    #: scheduling_duration_seconds observer)
    solve_delay_p: float = 0.0
    solve_delay_ms: float = 0.0


class FaultInjector:
    """Seeded fault scheduler shared by any number of connections.

    Thread-safe: the rng is guarded so concurrent sender/reader threads
    draw a single deterministic sequence (the *schedule* is reproducible
    per seed; which thread consumes which draw still depends on timing,
    which is exactly the nondeterminism chaos testing wants to shake)."""

    def __init__(self, seed: int = 0, config: FaultConfig | None = None,
                 sleep=time.sleep):
        self.seed = seed
        self.config = config or FaultConfig()
        self.enabled = True
        self.injected: collections.Counter = collections.Counter()
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sleep = sleep

    def heal(self) -> None:
        """Stop injecting (the soak's recovery phase).  Already-held
        reordered frames still flush through their connections."""
        self.enabled = False

    def _hit(self, p: float) -> bool:
        if not self.enabled or p <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < p

    def _count(self, kind: str) -> None:
        from koordinator_tpu import metrics

        self.injected[kind] += 1
        metrics.faults_injected_total.inc(labels={"kind": kind})

    # -- client seams --------------------------------------------------------

    def on_connect(self) -> None:
        if self._hit(self.config.connect_refuse_p):
            self._count("connect_refuse")
            raise ConnectionRefusedError("fault injection: connect refused")

    def outbound_cut(self, nbytes: int) -> int | None:
        """Byte count to truncate a client write at, or None (no fault)."""
        if self._hit(self.config.send_truncate_p):
            self._count("client_truncate")
            with self._lock:
                return self._rng.randrange(1, max(nbytes, 2))
        return None

    def on_read(self) -> None:
        if self._hit(self.config.read_drip_p):
            self._count("read_drip")
            self._sleep(self.config.read_drip_ms / 1000.0)

    # -- scheduler seam ------------------------------------------------------

    def on_solve(self) -> None:
        """Called at the top of the scheduler's Solve phase when an
        injector is attached (``Scheduler(faults=...)``): a hit sleeps
        ``solve_delay_ms``, landing squarely in the round's
        ``scheduling_duration_seconds{phase="Solve"}`` observation."""
        if self._hit(self.config.solve_delay_p):
            self._count("solve_delay")
            self._sleep(self.config.solve_delay_ms / 1000.0)

    # -- server _Conn seam ---------------------------------------------------

    def outbound_action(self, is_push: bool) -> str | None:
        """One of None / "sever" / "truncate" / "drop" / "delay" /
        "duplicate" / "reorder" for a server-side outbound frame.
        Evaluated in severity order; at most one fault per frame."""
        if self._hit(self.config.send_sever_p):
            self._count("sever")
            return "sever"
        if self._hit(self.config.send_truncate_p):
            self._count("truncate")
            return "truncate"
        if is_push:
            if self._hit(self.config.push_drop_p):
                self._count("push_drop")
                return "drop"
            if self._hit(self.config.push_delay_p):
                self._count("push_delay")
                return "delay"
            if self._hit(self.config.push_duplicate_p):
                self._count("push_duplicate")
                return "duplicate"
            if self._hit(self.config.push_reorder_p):
                self._count("push_reorder")
                return "reorder"
        return None

    def truncate_at(self, nbytes: int) -> int:
        with self._lock:
            return self._rng.randrange(1, max(nbytes, 2))

    def delay(self) -> None:
        self._sleep(self.config.push_delay_ms / 1000.0)
