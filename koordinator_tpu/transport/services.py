"""RPC services over the framed transport.

- :class:`SolveService` is the ``framework.Plugin`` extension seam
  (SURVEY.md §2.11 / §7 step 4): the protocol shell asks the solver
  sidecar for a scheduling round and gets assignments + failure reasons
  back. In the reference this boundary is the upstream scheduler calling
  plugin Filter/Score/Reserve in-process; here the whole batched round is
  one RPC, so the wire crossing is per-round, not per-pod-per-node.
- :class:`HookService` carries the runtime-hook dispatch
  (``apis/runtime/v1alpha1/api.proto:148`` PreRunPodSandboxHook et al)
  over the same frames, fail-open like the runtime proxy.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from koordinator_tpu.transport.wire import FrameType


class SolveService:
    """Server side: schedule_round over the wire.

    Honors per-call deadlines: solve rounds serialize on the scheduler
    lock, so a request can spend its whole budget just WAITING — once it
    has, the caller's RpcClient has already timed out and running the
    solve computes assignments nobody will read (worse: it burns the
    round lock the NEXT caller is queued behind).  The expiry check runs
    after the lock is acquired, which is exactly where the time went."""

    def __init__(self, scheduler):
        self.scheduler = scheduler
        self.sheds = 0

    def attach(self, server) -> None:
        server.register(FrameType.SOLVE_REQUEST, self._handle)

    def _handle(self, doc: dict, arrays):
        import time

        from koordinator_tpu import metrics
        from koordinator_tpu.transport.channel import DeadlineExpired

        expires = doc.get("__expires_at__")
        with self.scheduler.lock:
            if expires is not None and time.monotonic() >= expires:
                self.sheds += 1
                metrics.solve_deadline_shed_total.inc()
                raise DeadlineExpired(
                    "solve deadline expired while waiting for the round "
                    "lock; request shed without solving")
            result = self.scheduler.schedule_round()
        return {
            "assignments": dict(result.assignments),
            "failures": {name: diag.message()
                         for name, diag in result.failures.items()},
            "nominations": {p: [n, v] for p, (n, v)
                            in result.nominations.items()},
            "round_pods": result.round_pods,
        }, None


def solve_remote(client, deadline_ms: float | None = None) -> dict:
    """Client side: one scheduling round on the remote solver.
    ``deadline_ms`` bounds the wait AND lets the server shed the round
    if it cannot start before the budget is gone."""
    _, doc, _ = client.call(FrameType.SOLVE_REQUEST, {},
                            deadline_ms=deadline_ms)
    return doc


class HookService:
    """Server side: runtime-hook dispatch (NRI/proxy seam)."""

    def __init__(self, dispatcher):
        self.dispatcher = dispatcher

    def attach(self, server) -> None:
        server.register(FrameType.HOOK_REQUEST, self._handle)

    def _handle(self, doc: dict, arrays):
        from koordinator_tpu.runtimeproxy import HookRequest, HookType

        hook = HookType(doc["hook"])
        request = HookRequest(
            pod_meta=doc.get("pod_meta", {}),
            container_meta=doc.get("container_meta", {}),
            labels=doc.get("labels", {}),
            annotations=doc.get("annotations", {}),
            cgroup_parent=doc.get("cgroup_parent", ""),
            resources=doc.get("resources", {}),
            envs=doc.get("envs", {}),
        )
        merged = self.dispatcher.dispatch(hook, request)
        return {
            "labels": merged.labels,
            "annotations": merged.annotations,
            "cgroup_parent": merged.cgroup_parent,
            "resources": merged.resources,
            "envs": merged.envs,
        }, None


def hook_remote(client, hook, request, fail_open: bool = True) -> Optional[dict]:
    """Client side: dispatch one hook remotely. Fail-open returns None on
    transport errors (the proxy must never wedge the CRI path —
    dispatcher.go fail-open semantics)."""
    from koordinator_tpu.transport.channel import RpcError

    doc = {
        "hook": hook.value,
        "pod_meta": request.pod_meta,
        "container_meta": request.container_meta,
        "labels": request.labels,
        "annotations": request.annotations,
        "cgroup_parent": request.cgroup_parent,
        "resources": request.resources,
        "envs": request.envs,
    }
    try:
        _, out, _ = client.call(FrameType.HOOK_REQUEST, doc)
        return out
    except RpcError:
        if fail_open:
            return None
        raise
