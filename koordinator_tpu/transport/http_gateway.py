"""HTTP/JSON gateway: the externally-speakable boundary of the sidecar.

The north-star deployment has the reference's Go scheduler plugins calling
into this framework as a sidecar (BASELINE.json: "the Go plugins calling
into a Python sidecar via the existing framework.Plugin extension point").
The framed unix/TCP transport (channel.py) is the efficient Python<->
Python path; THIS module is the language-neutral one — plain HTTP + JSON,
callable from Go's net/http (or curl) with no codegen and no client
library, the interop role gRPC's JSON transcoding plays for the
reference's api.proto surface.

Routes (all JSON bodies/responses unless noted):

    GET  /healthz                      -> {"ok": true}
    GET  /version                      -> {"protocol": N}
    GET  /metrics                      -> text exposition over ALL
                                          component registries
                                          (metrics.expose_all) so every
                                          binary scrapes uniformly;
                                          ?openmetrics=1 (or Accept:
                                          application/openmetrics-text)
                                          adds histogram exemplars
    GET  /debug/rounds?size=N          -> the scheduler's round flight
                                          recorder, newest first
    GET  /debug/trace/<pod>            -> recent spans of the pod's
                                          trace (scheduler binaries);
                                          typed 404 for unknown pods
    GET  /debug/explain/<pod>          -> the pod's placement
                                          explanation: reject-reason
                                          node counts joined to its
                                          trace_id/round, plus per-term
                                          score decomposition of its
                                          winning/top-k candidates;
                                          typed 404 for unknown pods
                                          and rsv:: reserve-pods
    GET  /debug/slo                    -> the SLO burn-rate engine's
                                          evaluation (specs, windows,
                                          burn rates, breach state)
    GET  /debug/steady?window=N        -> the trend engine's long-
                                          horizon steady/drifting/
                                          leaking verdicts per watched
                                          series, joined to SLO breach
                                          state (scheduler binaries)
    GET  /debug/forecast?nodes=N       -> the forecast plane's horizon
                                          policy, prediction-error
                                          stats, and per-node predicted
                                          peaks (501 without a plane —
                                          forecast mode off)
    GET  /debug/tenants                -> multi-tenant rollup: per-
                                          tenant weight/share/credit,
                                          queue depth, degraded state,
                                          cycle dispatch mode (501
                                          without a tenancy front-end)
    GET  /debug/timeline?cycles=N      -> the critical-path
                                          observatory's reconstructed
                                          cycle gantts: typed segments,
                                          host-wait attribution,
                                          device-idle intervals, and
                                          the critical-path chain +
                                          dominant cause per cycle
    GET  /debug/latency?tenant=        -> the pod-journey ledger's
                                          per-(tenant, qos, stage)
                                          e2e latency quantile table
                                          from mergeable sketches (501
                                          when the ledger is off; typed
                                          400 on an unknown tenant)
    GET  /debug/profile?seconds=N      -> on-demand jax.profiler
                                          capture; 403 unless enabled
                                          at assembly (gated off by
                                          default)
    POST /v1/state                     -> one state event (the STATE_PUSH
                                          frame's JSON form: {"kind",
                                          "name", resource vectors as
                                          arrays, ...}) -> {"rv": N}
    POST /v1/solve                     -> one scheduling round
    POST /v1/hooks/<HookType>          -> runtime-hook dispatch
    GET  /v1/leases/<name>             -> lease record
    PUT  /v1/leases/<name>             -> CAS update {ok}; 409 on conflict
    GET  /v1/diagnosis                 -> last round's schedule diagnosis
    GET  /v1/podresources              -> kubelet pod-resources listing
                                          enriched with koord allocations
    GET  /v1/audit?size=N&group=G      -> recent audit events, newest first
                                          (AuditEventsHTTPHandler's role)

Handlers delegate to the same objects the framed services use
(transport/services.py SolveService/HookService, ha.LeaseService's store),
so both boundaries stay behaviorally identical.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from koordinator_tpu.transport.wire import PROTOCOL_VERSION


class HttpGateway:
    """Threaded HTTP server over the sidecar's services.

    Any of ``scheduler``, ``dispatcher``, ``lease_store`` may be None —
    the matching routes then answer 501, so a koordlet-only or
    scheduler-only binary exposes exactly its own surface.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        scheduler=None,
        dispatcher=None,
        lease_store=None,
        pod_resources=None,
        auditor=None,
        state_sync=None,
    ):
        self.scheduler = scheduler
        self.dispatcher = dispatcher
        self.lease_store = lease_store
        self.pod_resources = pod_resources
        self.auditor = auditor
        self.state_sync = state_sync
        gateway = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: no stderr spam
                pass

            def _reply(self, code: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, code: int, text: str,
                            content_type: str = "text/plain; "
                            "version=0.0.4; charset=utf-8") -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length") or 0)
                if not length:
                    return {}
                return json.loads(self.rfile.read(length).decode())

            def do_GET(self):
                try:
                    gateway._route(self, "GET")
                except Exception as e:  # route bug: fail the call
                    self._reply(500, {"error": repr(e)})

            def do_POST(self):
                try:
                    gateway._route(self, "POST")
                except Exception as e:
                    self._reply(500, {"error": repr(e)})

            def do_PUT(self):
                try:
                    gateway._route(self, "PUT")
                except Exception as e:
                    self._reply(500, {"error": repr(e)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        # tight poll interval, matching channel.RpcServer: shutdown()
        # blocks until serve_forever's select loop notices, and the 0.5s
        # stdlib default stalls every gateway stop/restart
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- routing ------------------------------------------------------------

    _LEASE = re.compile(r"^/v1/leases/([A-Za-z0-9._-]+)$")
    _HOOK = re.compile(r"^/v1/hooks/([A-Za-z0-9._-]+)$")
    _TRACE = re.compile(r"^/debug/trace/(.+)$")
    _EXPLAIN = re.compile(r"^/debug/explain/(.+)$")

    def _route(self, req, method: str) -> None:
        path = req.path.split("?", 1)[0]
        if method == "GET" and path == "/healthz":
            return req._reply(200, {"ok": True})
        if method == "GET" and path == "/version":
            return req._reply(200, {"protocol": PROTOCOL_VERSION})
        if method == "GET" and path == "/metrics":
            return self._metrics(req)
        if method == "GET" and path == "/debug/rounds":
            return self._debug_rounds(req)
        if method == "GET" and path == "/debug/slo":
            return self._debug_slo(req)
        if method == "GET" and path == "/debug/steady":
            return self._debug_steady(req)
        if method == "GET" and path == "/debug/forecast":
            return self._debug_forecast(req)
        if method == "GET" and path == "/debug/tenants":
            return self._debug_tenants(req)
        if method == "GET" and path == "/debug/timeline":
            return self._debug_timeline(req)
        if method == "GET" and path == "/debug/latency":
            return self._debug_latency(req)
        if method == "GET" and path == "/debug/profile":
            return self._debug_profile(req)
        m = self._TRACE.match(path)
        if m and method == "GET":
            return self._debug_trace(req, m.group(1))
        m = self._EXPLAIN.match(path)
        if m and method == "GET":
            return self._debug_explain(req, m.group(1))
        if method == "POST" and path == "/v1/state":
            return self._state_push(req)
        if method == "POST" and path == "/v1/solve":
            return self._solve(req)
        if method == "GET" and path == "/v1/diagnosis":
            return self._diagnosis(req)
        if method == "GET" and path == "/v1/podresources":
            if self.pod_resources is None:
                return req._reply(501,
                                  {"error": "no pod-resources proxy"})
            return req._reply(200, self.pod_resources.list())
        if method == "GET" and path == "/v1/audit":
            if self.auditor is None:
                return req._reply(501, {"error": "no auditor attached"})
            from urllib.parse import parse_qs

            query = parse_qs(req.path.partition("?")[2])
            try:
                size = int(query.get("size", ["100"])[0])
            except ValueError:
                return req._reply(400, {"error": "size must be an int"})
            group = query.get("group", [None])[0]
            return req._reply(200, {"events": self.auditor.query(
                limit=size, group=group)})
        m = self._HOOK.match(path)
        if m and method == "POST":
            return self._hook(req, m.group(1))
        m = self._LEASE.match(path)
        if m:
            if method == "GET":
                return self._lease_get(req, m.group(1))
            if method == "PUT":
                return self._lease_put(req, m.group(1))
        req._reply(404, {"error": f"no route {method} {path}"})

    def _state_push(self, req) -> None:
        """One state event, the STATE_PUSH frame's JSON form: resource
        vectors ride as JSON int arrays (fine for the interop path; the
        hot path uses the framed transport's raw array section).  Rides
        the same validated handler, so a malformed HTTP push fails with
        400 instead of poisoning the replay log."""
        if self.state_sync is None:
            return req._reply(501, {"error": "no state-sync service"})
        import numpy as np

        from koordinator_tpu.transport.wire import (
            STATE_PUSH_ARRAY_KEYS,
            WireSchemaError,
        )

        doc = req._body()
        if not isinstance(doc, dict):
            return req._reply(400, {"error": "body must be a JSON object"})
        arrays = {}
        for key in STATE_PUSH_ARRAY_KEYS:
            if key in doc:
                value = doc.pop(key)
                if (not isinstance(value, list)
                        or not all(isinstance(v, int)
                                   and not isinstance(v, bool)
                                   for v in value)):
                    return req._reply(400, {
                        "error": f"{key} must be a JSON array of ints"})
                try:
                    arrays[key] = np.asarray(value, np.int64)
                except OverflowError:
                    return req._reply(400, {
                        "error": f"{key} has values beyond int64"})
        try:
            # the handler owns schema validation (incl. kind/name)
            out, _ = self.state_sync._handle_state_push(doc, arrays)
        except WireSchemaError as e:
            body = {"error": str(e)}
            if getattr(e, "resync", False):
                # same resync hint the framed ERROR carries: the
                # pusher's view of this service is stale, not just this
                # one request (docs/robustness.md)
                body["resync"] = True
            return req._reply(400, body)
        req._reply(200, out)

    def _metrics(self, req) -> None:
        """Aggregate scrape surface: every component registry, so the
        same scrape config works against any of the five binaries."""
        from urllib.parse import parse_qs

        from koordinator_tpu import metrics

        query = parse_qs(req.path.partition("?")[2])
        openmetrics = (metrics.parse_openmetrics_flag(
            query.get("openmetrics", ["0"])[0])
            or "application/openmetrics-text"
            in (req.headers.get("Accept") or ""))
        content_type = ("application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8" if openmetrics
                        else "text/plain; version=0.0.4; charset=utf-8")
        req._reply_text(200, metrics.expose_all(openmetrics=openmetrics),
                        content_type=content_type)

    def _debug_rounds(self, req) -> None:
        if getattr(self.scheduler, "flight_recorder", None) is None:
            return req._reply(501, {"error": "no flight recorder "
                                    "(scheduler binaries only)"})
        from urllib.parse import parse_qs

        from koordinator_tpu.scheduler.services import debug_rounds_body

        query = parse_qs(req.path.partition("?")[2])
        try:
            size = int(query.get("size", ["32"])[0])
        except ValueError:
            return req._reply(400, {"error": "size must be an int"})
        return req._reply(200, debug_rounds_body(self.scheduler, size))

    def _debug_slo(self, req) -> None:
        """The SLO burn-rate engine's evaluation — same body the
        DebugService serves (shared builder)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_slo_body,
        )

        try:
            return req._reply(200, debug_slo_body(self.scheduler))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_steady(self, req) -> None:
        """The trend engine's steady/drifting/leaking verdicts — same
        body the DebugService serves (shared builder; ?window=N
        overrides the evaluation window)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qsl

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_steady_body,
        )

        params = dict(parse_qsl(req.path.partition("?")[2]))
        try:
            return req._reply(200, debug_steady_body(self.scheduler,
                                                     params))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_forecast(self, req) -> None:
        """The forecast plane's horizon/error/per-node-peak document —
        same body the DebugService serves (shared builder; ?nodes=N
        bounds the node section, typed 501 without a plane)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qsl

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_forecast_body,
        )

        params = dict(parse_qsl(req.path.partition("?")[2]))
        try:
            return req._reply(200, debug_forecast_body(self.scheduler,
                                                       params))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_tenants(self, req) -> None:
        """The multi-tenant rollup — same body the DebugService serves
        (shared builder; typed 501 without a tenancy front-end)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_tenants_body,
        )

        try:
            return req._reply(200, debug_tenants_body(self.scheduler))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_timeline(self, req) -> None:
        """The critical-path observatory's cycle gantts — same body the
        DebugService serves (shared builder; ?cycles=N bounds the ring
        slice, 400 on a malformed bound)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qsl

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_timeline_body,
        )

        params = dict(parse_qsl(req.path.partition("?")[2]))
        try:
            return req._reply(200, debug_timeline_body(self.scheduler,
                                                       params))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_latency(self, req) -> None:
        """The pod-journey ledger's latency quantile table — same body
        the DebugService serves (shared builder; ?tenant= filters, typed
        400 on an unknown tenant, 501 while the ledger is off)."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qsl

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_latency_body,
        )

        params = dict(parse_qsl(req.path.partition("?")[2]))
        try:
            return req._reply(200, debug_latency_body(self.scheduler,
                                                      params))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_profile(self, req) -> None:
        """On-demand jax.profiler capture (?seconds=N), 403 while the
        assembly-time gate is off — the default."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qs

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_profile_body,
        )

        query = parse_qs(req.path.partition("?")[2])
        seconds = query.get("seconds", ["1.0"])[0]
        try:
            return req._reply(200,
                              debug_profile_body(self.scheduler, seconds))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_trace(self, req, pod: str) -> None:
        """Typed statuses ride the shared builder's DebugApiError (404
        for unknown pods) — the same mapping the DebugService applies,
        so the two surfaces cannot drift."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_trace_body,
        )

        try:
            return req._reply(200, debug_trace_body(self.scheduler, pod))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _debug_explain(self, req, pod: str) -> None:
        """One pod's placement explanation (reject-reason counts +
        candidate score decomposition; ?candidates=0 skips the
        decomposition for polling loops); 404s are typed via the shared
        builder for unknown pods and rsv:: reserve-pods."""
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from urllib.parse import parse_qsl

        from koordinator_tpu.scheduler.services import (
            DebugApiError,
            debug_explain_body,
        )

        params = dict(parse_qsl(req.path.partition("?")[2]))
        try:
            return req._reply(200, debug_explain_body(self.scheduler, pod,
                                                      params))
        except DebugApiError as e:
            return req._reply(e.status, {"error": e.message})

    def _solve(self, req) -> None:
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        from koordinator_tpu import tracing

        # a trace context in the body joins the round to the caller's
        # trace, same as the framed SOLVE_REQUEST path.  The body was
        # IGNORED before tracing existed, so a non-JSON body (curl -d
        # 'run-now') must keep triggering the round, not 500
        try:
            doc = req._body()
        except ValueError:
            doc = {}
        ctx = (tracing.TraceContext.from_doc(doc.get("trace"))
               if isinstance(doc, dict) else None)
        with tracing.activate(ctx):
            result = self.scheduler.schedule_round()
        req._reply(200, {
            "assignments": dict(result.assignments),
            "failures": {name: diag.message()
                         for name, diag in result.failures.items()},
            "nominations": {p: [n, v] for p, (n, v)
                            in result.nominations.items()},
            "round_pods": result.round_pods,
        })

    def _diagnosis(self, req) -> None:
        if self.scheduler is None:
            return req._reply(501, {"error": "no scheduler attached"})
        result = getattr(self.scheduler, "last_result", None)
        if result is None:
            return req._reply(200, {"failures": {}})
        req._reply(200, {
            "failures": {name: diag.message()
                         for name, diag in result.failures.items()},
        })

    def _hook(self, req, hook_name: str) -> None:
        if self.dispatcher is None:
            return req._reply(501, {"error": "no hook dispatcher attached"})
        from koordinator_tpu.runtimeproxy import HookRequest, HookType

        try:
            hook = HookType(hook_name)
        except ValueError:
            return req._reply(400, {"error": f"unknown hook {hook_name}"})
        doc = req._body()
        request = HookRequest(
            pod_meta=doc.get("pod_meta", {}),
            container_meta=doc.get("container_meta", {}),
            labels=doc.get("labels", {}),
            annotations=doc.get("annotations", {}),
            cgroup_parent=doc.get("cgroup_parent", ""),
            resources=doc.get("resources", {}),
            envs=doc.get("envs", {}),
        )
        merged = self.dispatcher.dispatch(hook, request)
        req._reply(200, {
            "labels": merged.labels,
            "annotations": merged.annotations,
            "cgroup_parent": merged.cgroup_parent,
            "resources": merged.resources,
            "envs": merged.envs,
        })

    def _lease_get(self, req, name: str) -> None:
        if self.lease_store is None:
            return req._reply(501, {"error": "no lease store attached"})
        rec = self.lease_store.get(name)
        req._reply(200, dataclasses.asdict(rec))

    def _lease_put(self, req, name: str) -> None:
        if self.lease_store is None:
            return req._reply(501, {"error": "no lease store attached"})
        from koordinator_tpu.ha import LeaseRecord

        doc = req._body()
        expect = doc.pop("expect_holder", "")
        fields = {f.name for f in dataclasses.fields(LeaseRecord)}
        rec = LeaseRecord(**{k: v for k, v in doc.items() if k in fields})
        if self.lease_store.update(name, expect, rec):
            return req._reply(200, {"ok": True})
        req._reply(409, {"ok": False, "error": "holder mismatch"})
