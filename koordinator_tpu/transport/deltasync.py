"""Incremental cluster-state sync: snapshot + resource-version'd deltas.

The reference keeps the solver-visible world current through apiserver
watch streams: informers replay a LIST (snapshot at a resourceVersion)
then stream WATCH events; a client that falls behind the retained event
window gets HTTP 410 Gone and must re-LIST. This module is that protocol
over the framed RPC layer, feeding the solver's device-resident tensors:

- :class:`StateSyncService` is the informer side: it owns the object
  cache (nodes/pods), stamps every mutation with a monotonically
  increasing resource version, retains a bounded delta log, serves HELLO
  as ACK (caught up) / DELTA (replay window) / SNAPSHOT (fell behind),
  and pushes DELTA frames to connected solvers (the WATCH stream).
- :class:`StateSyncClient` is the solver side: applies frames
  idempotently (events at or below its rv are skipped, so replays and
  reconnect overlaps are harmless), requests resync when told, and hands
  decoded objects to the snapshot/scheduler through a binding.

Deltas carry their resource vectors as raw (K, R) int32 blocks — the
host->device path stays a scatter of K rows, never a rebuild
(SURVEY.md §7 "hard parts (a)").
"""

from __future__ import annotations

import contextlib
import threading
from collections import deque
from typing import Optional

import numpy as np

from koordinator_tpu import metrics, timeline, tracing
from koordinator_tpu.transport import channel, wire
from koordinator_tpu.transport.wire import FrameType

NODE_UPSERT = "node_upsert"
NODE_USAGE = "node_usage"
NODE_ALLOC = "node_allocatable"
NODE_DEVICES = "node_devices"
NODE_REMOVE = "node_remove"
POD_ADD = "pod_add"
POD_REMOVE = "pod_remove"
RSV_UPSERT = "rsv_upsert"
RSV_REMOVE = "rsv_remove"


class ResyncRequired(Exception):
    """Client fell behind the retained window (HTTP 410 Gone analog)."""


class UnknownNodeError(wire.WireSchemaError):
    """A merge-style event (node_usage / node_allocatable / node_devices)
    named a node this service doesn't know.  For an in-process caller
    that is a peer bug (plain schema error); for a WIRE client it
    usually means the client's watch view predates a service restart
    that lost the node — the ERROR frame carries ``resync: true`` so the
    client re-HELLOs instead of failing the same push forever."""

    resync = True


class DeltaLog:
    """Bounded ordered log of (rv, event, arrays)."""

    def __init__(self, retention: int = 4096):
        self.retention = retention
        self._events: deque[tuple[int, dict, dict[str, np.ndarray]]] = deque()

    def append(self, rv: int, event: dict,
               arrays: dict[str, np.ndarray]) -> None:
        self._events.append((rv, event, arrays))
        while len(self._events) > self.retention:
            self._events.popleft()

    def oldest_rv(self) -> Optional[int]:
        return self._events[0][0] if self._events else None

    def since(self, rv: int) -> list[tuple[int, dict, dict[str, np.ndarray]]]:
        """All events with rv' > rv. Raises ResyncRequired when rv is
        before the retained window."""
        oldest = self.oldest_rv()
        if oldest is not None and rv < oldest - 1:
            raise ResyncRequired(f"rv {rv} < retained window start {oldest}")
        return [(v, e, a) for v, e, a in self._events if v > rv]


def _pack_events(
    events: list[tuple[int, dict, dict[str, np.ndarray]]]
) -> tuple[dict, dict[str, np.ndarray]]:
    """Stack per-event arrays into (K, R) blocks referenced by row index."""
    docs = []
    stacked: dict[str, list[np.ndarray]] = {}
    for rv, event, arrays in events:
        entry = dict(event, rv=rv)
        for key, arr in arrays.items():
            rows = stacked.setdefault(key, [])
            entry[f"__row_{key}__"] = len(rows)
            rows.append(np.asarray(arr))
        docs.append(entry)
    return ({"events": docs},
            {k: np.stack(v) for k, v in stacked.items()})


# -- columnar event codec (wire protocol v4, ISSUE 19) ----------------------
#
# The v1 packing above serializes one JSON document PER EVENT (name,
# kind, rv, and a __row_*__ manifest each) — at snapshot scale that is
# tens of thousands of json.dumps/loads round trips, the largest
# ``json_codec`` contributor in the PR 18 host-wait attribution.  The v2
# packing moves the per-event constants into columnar numpy arrays that
# ride the raw array section: kind codes (uint8), rvs (int64), names
# (length + utf-8 blob columns), and one int32 row-index column per
# stacked array key.  Event fields beyond the columns — labels, trace
# contexts, reservation owners — ride a SPARSE ``extras`` list holding
# only non-default fields, so the steady-state hot kinds (node_usage,
# pod_remove) carry zero JSON per event.  Decoding reconstructs the
# exact v1 entry list, so everything downstream of the codec (rv
# guards, bindings, replay) is byte-for-byte unchanged.

_KIND_CODES = {NODE_UPSERT: 0, NODE_USAGE: 1, NODE_ALLOC: 2,
               NODE_DEVICES: 3, NODE_REMOVE: 4, POD_ADD: 5,
               POD_REMOVE: 6, RSV_UPSERT: 7, RSV_REMOVE: 8}
_CODE_KINDS = {v: k for k, v in _KIND_CODES.items()}

#: per-kind default fields elided from the wire and reconstructed at
#: decode — MUST mirror the event docs the mutation methods build
#: (upsert_node / add_pod / upsert_reservation), or round-tripped
#: entries stop being equal to the originals
_V2_DEFAULTS: dict[str, dict] = {
    NODE_UPSERT: {"labels": {}, "taints": {}, "annotations": {},
                  "devices": {}},
    POD_ADD: {"priority": 0, "quota": None, "gang": None,
              "node_selector": {}, "labels": {}, "owner": None, "qos": 0},
    RSV_UPSERT: {"owners": [], "allocate_once": False, "ttl_sec": None,
                 "node": None, "node_selector": {}, "tolerations": {},
                 "restricted": False},
}


def _pack_events_v2(
    events: list[tuple[int, dict, dict[str, np.ndarray]]]
) -> tuple[dict, dict[str, np.ndarray]] | None:
    """Columnar packing (see above).  Returns None when any event's kind
    has no code — the caller falls back to the v1 packing so a new event
    kind degrades to JSON instead of breaking the stream."""
    # hot loop: list appends + one vectorized column fill per key beat
    # per-event numpy scalar stores by ~2x at snapshot scale
    k = len(events)
    kinds: list[int] = []
    rvs: list[int] = []
    names: list[str] = []
    extras: list[list] = []
    stacked: dict[str, list[np.ndarray]] = {}
    positions: dict[str, list[int]] = {}
    kind_codes = _KIND_CODES
    v2_defaults = _V2_DEFAULTS
    for i, (rv, event, arrays) in enumerate(events):
        kind = event.get("kind")
        code = kind_codes.get(kind)
        if code is None:
            return None
        kinds.append(code)
        rvs.append(rv)
        names.append(event["name"])
        if len(event) > 2:  # anything beyond kind+name rides extras
            defaults = v2_defaults.get(kind)
            if defaults is None:
                extra = {key: val for key, val in event.items()
                         if key != "kind" and key != "name"}
            else:
                extra = {key: val for key, val in event.items()
                         if key != "kind" and key != "name"
                         and not (key in defaults
                                  and val == defaults[key])}
            if extra:
                extras.append([i, extra])
        if arrays:
            for key, arr in arrays.items():
                rows = stacked.get(key)
                if rows is None:
                    rows = stacked[key] = []
                    positions[key] = []
                positions[key].append(i)
                rows.append(np.asarray(arr))
    out_arrays: dict[str, np.ndarray] = {
        "__kinds__": np.asarray(kinds, np.uint8),
        "__rvs__": np.asarray(rvs, np.int64)}
    name_lens, name_blob = wire.pack_str_column(names)
    out_arrays["__name_lens__"] = name_lens
    out_arrays["__name_blob__"] = name_blob
    for key, blocks in stacked.items():
        col = np.full(k, -1, np.int32)
        col[positions[key]] = np.arange(len(blocks), dtype=np.int32)
        out_arrays[f"__rows_{key}__"] = col
        out_arrays[key] = np.stack(blocks)
    doc: dict = {"events_v2": k}
    if extras:
        doc["extras"] = extras
    return doc, out_arrays


def _unpack_events_v2(doc: dict,
                      arrays: dict[str, np.ndarray]) -> list[dict]:
    """Inverse of :func:`_pack_events_v2`: reconstruct the ordered v1
    entry list (``__row_*__`` indices included, so
    :func:`_unpack_event_arrays` works unchanged on the result)."""
    k = int(doc["events_v2"])
    try:
        kinds = arrays["__kinds__"]
        rvs = arrays["__rvs__"]
        names = wire.unpack_str_column(arrays["__name_lens__"],
                                       arrays["__name_blob__"])
    except KeyError as e:
        raise wire.WireSchemaError(
            f"events_v2 frame missing column {e}") from e
    if len(kinds) != k or len(rvs) != k or len(names) != k:
        raise wire.WireSchemaError(
            f"events_v2 column lengths disagree with count {k}")
    extras = {int(i): e for i, e in doc.get("extras", [])}
    # numpy scalar indexing costs ~100ns a pop; one tolist() per column
    # up front makes the reconstruction loop pure-Python cheap
    kinds_l = kinds.tolist()
    rvs_l = rvs.tolist()
    row_cols: list[tuple[str, list]] = []
    for key in arrays:
        if key.startswith("__rows_") and key.endswith("__"):
            col = arrays[key].tolist()
            if len(col) != k:
                raise wire.WireSchemaError(
                    f"events_v2 row column {key} has {len(col)} rows, "
                    f"expected {k}")
            row_cols.append((f"__row_{key[len('__rows_'):-2]}__", col))
    entries: list[dict] = []
    code_kinds = _CODE_KINDS
    v2_defaults = _V2_DEFAULTS
    for i in range(k):
        kind = code_kinds.get(kinds_l[i])
        if kind is None:
            raise wire.WireSchemaError(
                f"events_v2 frame carries unknown kind code "
                f"{kinds_l[i]}")
        entry: dict = {"kind": kind, "name": names[i]}
        defaults = v2_defaults.get(kind)
        if defaults is not None:
            for key, val in defaults.items():
                # fresh containers per entry: binding handlers treat
                # entry values as read-only, but shared mutables across
                # entries would make any future slip a cross-event
                # corruption
                entry[key] = (dict(val) if isinstance(val, dict)
                              else list(val) if isinstance(val, list)
                              else val)
        ex = extras.get(i)
        if ex is not None:
            entry.update(ex)
        entry["rv"] = rvs_l[i]
        for row_key, col in row_cols:
            row = col[i]
            if row >= 0:
                entry[row_key] = row
        entries.append(entry)
    return entries


def _decode_events(doc: dict, arrays: dict[str, np.ndarray]) -> list[dict]:
    """Normalize a DELTA/SNAPSHOT payload to the v1 entry list,
    whichever codec produced it."""
    if "events_v2" in doc:
        return _unpack_events_v2(doc, arrays)
    return doc.get("events", [])


def _unpack_event_arrays(entry: dict,
                         arrays: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    out = {}
    for key, row in entry.items():
        if key.startswith("__row_") and key.endswith("__"):
            name = key[6:-2]
            out[name] = arrays[name][row]
    return out


def _validate_devices(devices: dict | None, context: str) -> None:
    """Reject malformed device inventories at EVERY entry point (wire
    push AND the direct upsert_node/update_node_devices API): a non-list
    type value would commit to the log and then silently skip
    registration on replay while `full_inventory` clearing sees the type
    as present — the exact live-vs-replay divergence the clearing
    exists to prevent."""
    if devices is None:
        return
    if not wire.check_field_type(devices, dict):
        raise wire.WireSchemaError(
            f"{context}: 'devices' must be an object, "
            f"got {type(devices).__name__}")
    for dev_type, inventory in devices.items():
        if not isinstance(inventory, list) or any(
                not isinstance(entry, dict) for entry in inventory):
            raise wire.WireSchemaError(
                f"{context}: devices[{dev_type!r}] must be a list "
                f"of objects")
        for entry in inventory:
            # entries feed DeviceState.build's int tensors on replay
            for field in ("core", "memory", "group"):
                if not wire.check_field_type(
                        entry.get(field, 0), int):
                    raise wire.WireSchemaError(
                        f"{context}: devices[{dev_type!r}] entry "
                        f"field {field!r} must be an integer")


class StateSyncService:
    """Informer-side state authority + wire handlers.

    Attach to an RpcServer:

        service = StateSyncService()
        service.attach(server)

    then mutate via upsert_node/remove_node/add_pod/remove_pod; every
    mutation bumps the rv, logs a delta, and pushes it to subscribers.
    """

    def __init__(self, retention: int = 4096):
        self._lock = threading.RLock()
        self.rv = 0
        #: boot-epoch id: a restarted service resets its rv counter, and
        #: a client whose last_rv happens to EQUAL the new service's rv
        #: would get a bare ACK and keep a permanently stale view (the
        #: r5 manager reconnect path depends on restart => resync).
        #: HELLO compares instances; a mismatch forces the full snapshot
        #: regardless of rv.
        import uuid

        self.instance = uuid.uuid4().hex
        self.log = DeltaLog(retention)
        self.nodes: dict[str, dict] = {}      # name -> {doc, arrays}
        self.pods: dict[str, dict] = {}       # name -> {doc, arrays}
        self.reservations: dict[str, dict] = {}
        self._server = None
        self._local_bindings: list = []
        #: committed events awaiting local-binding apply; populated under
        #: _lock (so it carries rv order), drained under _binding_lock
        #: only — binding applies block on scheduler.lock and must never
        #: hold the service lock while they do
        self._binding_queue: deque = deque()
        self._binding_lock = threading.Lock()
        #: high-water mark of the binding backlog (gauge shadow; only
        #: ever written under _lock alongside the append)
        self._backlog_peak = 0

    # -- mutations (informer event handlers) --------------------------------

    def attach_binding(self, binding) -> None:
        """Register an IN-PROCESS subscriber (e.g. a SchedulerBinding):
        every committed event is applied to it synchronously, so a
        sidecar binary whose solver lives in the same process as its
        sync service sees pushed state immediately — no socket loop, no
        eventual-consistency window.  Remote sync clients keep the
        broadcast path."""
        self._local_bindings.append(binding)

    def _store_and_commit(self, store_fn, event: dict,
                          arrays: dict[str, np.ndarray]) -> int:
        """Run a stored-state mutation AND append+broadcast its event
        under ONE lock acquisition, so rv order, wire order, and stored
        state always agree (the client's idempotency guard drops any rv
        it has already passed, so reordered broadcasts would lose
        events; a store released before the log append lets a racing
        mutator interleave — e.g. upsert_node(devices={}) vs
        update_node_devices(X) could log [devices=X, upsert={}] while
        storing devices=X, and the stale stored doc would then eat every
        subsequent identical heartbeat as 'unchanged').  Safe to hold:
        broadcast only enqueues to bounded per-connection queues — a
        stalled peer drops frames and gets poisoned, it cannot wedge the
        service (channel._Conn.send)."""
        with self._lock:
            store_fn()
            rv = self._commit_locked(event, arrays)
        # apply OUTSIDE the service lock: bindings block on the scheduler
        # lock (a long solve), and holding _lock through that would stall
        # every HELLO/push/broadcast behind it.  The queue was filled in
        # rv order under _lock; draining FIFO under _binding_lock keeps
        # that order even when two pushers race to drain.
        if self._local_bindings:
            self._drain_bindings()
        return rv

    def _commit_locked(self, event: dict,
                       arrays: dict[str, np.ndarray]) -> int:
        """The lock-held half of _commit, for mutations that must merge
        stored state and log the event ATOMICALLY (update_node_usage /
        update_node_devices: a racing pair must not leave the stored doc
        and the delta-log tail disagreeing).  Caller holds _lock and
        must call _drain_bindings() after releasing it."""
        # trace propagation: the mutation's originating context (a
        # traced STATE_PUSH dispatch, an instrumented in-process caller)
        # is stamped onto the event itself, so DELTA watchers, the
        # bounded replay log, AND bootstrap snapshots (the stored doc is
        # this same dict for upsert-style events) all carry it — a pod's
        # trace survives a client resync the same way its spec does
        ctx = tracing.current_context()
        if ctx is not None and tracing.TRACE_DOC_KEY not in event:
            event[tracing.TRACE_DOC_KEY] = ctx.to_doc()
        self.rv += 1
        rv = self.rv
        self.log.append(rv, event, arrays)
        if self._server is not None:
            batch = [(rv, event, arrays)]
            packed = _pack_events_v2(batch)
            if packed is None:
                # unknown kind: everyone gets the v1 JSON form
                doc, stacked = _pack_events(batch)
                self._server.broadcast(FrameType.DELTA, doc, stacked)
            else:
                # columnar frame to v4+ peers; legacy encodes the v1
                # frame lazily, ONLY if some negotiated-down peer is
                # actually connected (a pure-v4 fleet never pays it)
                doc, stacked = packed
                self._server.broadcast(
                    FrameType.DELTA, doc, stacked, min_proto=4,
                    legacy=lambda: _pack_events(batch))
        if self._local_bindings:
            self._binding_queue.append((event, arrays))
            # backlog watermark (ISSUE 9): depth sampled at append (the
            # only place it grows) plus a monotone high-water gauge —
            # the steady-state soak bounds the peak, the trend engine
            # watches it for leak-shaped growth
            depth = len(self._binding_queue)
            metrics.sync_binding_backlog.set(float(depth))
            if depth > self._backlog_peak:
                self._backlog_peak = depth
                metrics.sync_binding_backlog_peak.set(float(depth))
        return rv

    def _drain_bindings(self) -> None:
        # drain the WHOLE backlog, then route it as one ordered batch so
        # contiguous same-kind runs (a koordlet heartbeat sweep, a
        # loadgen pod burst) hit the binding's vectorized run apply —
        # one scheduler.lock round-trip per run, not per event
        with self._binding_lock:
            while True:
                items: list[tuple[dict, dict]] = []
                while True:
                    try:
                        items.append(self._binding_queue.popleft())
                    except IndexError:
                        break
                if not items:
                    metrics.sync_binding_backlog.set(0.0)
                    return
                for binding in self._local_bindings:
                    _dispatch_events(binding, items)

    def upsert_node(self, name: str, allocatable: np.ndarray,
                    usage: np.ndarray | None = None,
                    labels: dict | None = None,
                    taints: dict | None = None,
                    annotations: dict | None = None,
                    devices: dict | None = None) -> int:
        """``annotations`` carries the koordlet's NRT payload (cpu-topology
        etc.); ``devices`` carries the Device-CR inventory per type
        ({type: [{"core": c, "memory": b, "group": g}, ...]}) — both feed
        the scheduler's fine-grained allocators on the client side."""
        _validate_devices(devices, "upsert_node")
        arrays = {
            "allocatable": np.asarray(allocatable, np.int32),
            "usage": (np.asarray(usage, np.int32) if usage is not None
                      else np.zeros_like(allocatable, np.int32)),
        }
        doc = {"kind": NODE_UPSERT, "name": name,
               "labels": labels or {}, "taints": taints or {},
               "annotations": annotations or {}, "devices": devices or {}}
        def store():
            self.nodes[name] = {"doc": doc, "arrays": arrays}

        return self._store_and_commit(store, doc, arrays)

    def update_node_usage(self, name: str, usage: np.ndarray,
                          agg_usage: np.ndarray | None = None,
                          prod_usage: np.ndarray | None = None,
                          sys_usage: np.ndarray | None = None,
                          hp_usage: np.ndarray | None = None,
                          hp_request: np.ndarray | None = None,
                          hp_max_used_req: np.ndarray | None = None,
                          report_time: float | None = None) -> int:
        """The NodeMetric loop's wire form (SURVEY §3.2): refresh a
        node's USAGE without re-sending allocatable — what a koordlet's
        reporter knows.  The stored node entry merges the new usage so a
        later bootstrap snapshot carries it; live watchers get the
        NODE_USAGE delta.  Unknown node -> WireSchemaError (nothing
        enters the log: usage for a node nobody registered is a peer
        bug, and replaying it would apply to nothing).

        ``sys_usage`` (system daemons outside kube pods) and
        ``hp_usage`` (Prod+Mid pods: non-BE, priority >= mid band) are
        the colocation formula's inputs (slo-controller/noderesource
        plugins/util/util.go:55: Batch = Total - SafetyMargin -
        max(System, Reserved) - HP.Used) — a manager watch client
        consumes them; the scheduler binding ignores them.
        ``hp_request`` (sum of HP pods' REQUESTS) and ``hp_max_used_req``
        (per-pod max(request, usage) summed over HP pods) feed the
        ``request``/``maxUsageRequest`` calculate policies — without
        them a wire-fed manager silently over-advertises batch capacity
        under those policies.  ``report_time`` is the KOORDLET's report
        timestamp (NodeMetric update_time): consumers date the usage by
        it, not by their apply-time clock, so degrade windows survive a
        manager restart + bootstrap replay."""
        arrays: dict[str, np.ndarray] = {
            "usage": np.asarray(usage, np.int32)}
        if agg_usage is not None:
            arrays["agg_usage"] = np.asarray(agg_usage, np.int32)
        if prod_usage is not None:
            arrays["prod_usage"] = np.asarray(prod_usage, np.int32)
        if sys_usage is not None:
            arrays["sys_usage"] = np.asarray(sys_usage, np.int32)
        if hp_usage is not None:
            arrays["hp_usage"] = np.asarray(hp_usage, np.int32)
        if hp_request is not None:
            arrays["hp_request"] = np.asarray(hp_request, np.int32)
        if hp_max_used_req is not None:
            arrays["hp_max_used_req"] = np.asarray(hp_max_used_req,
                                                   np.int32)
        event: dict = {"kind": NODE_USAGE, "name": name}
        if report_time is not None:
            event["usage_time"] = float(report_time)
        with self._lock:
            entry = self.nodes.get(name)
            if entry is None:
                raise UnknownNodeError(
                    f"node_usage for unknown node {name!r}")
            entry["arrays"] = dict(entry["arrays"], **arrays)
            if report_time is not None:
                # merge into the stored doc so a bootstrap snapshot
                # replays the ORIGINAL report time, not the apply time
                entry["doc"] = dict(entry["doc"],
                                    usage_time=float(report_time))
            rv = self._commit_locked(event, arrays)
        if self._local_bindings:
            self._drain_bindings()
        return rv

    def update_node_allocatable(self, name: str,
                                allocatable: np.ndarray) -> int:
        """The noderesource controller's wire form (SURVEY §3.2's
        manager leg): replace a node's ALLOCATABLE vector without
        touching its usage, labels, taints, or device inventory — the
        tensor analog of the reference's node-status extended-resource
        patch (slo-controller/noderesource/noderesource_controller.go:71
        -> plugins/batchresource/plugin.go:188 -> PATCH node.status).  A
        full node_upsert from the manager would clobber the koordlet's
        device inventory (upsert replaces the stored doc wholesale);
        this event merges.  Unknown node -> WireSchemaError, same rule
        as node_usage."""
        arrays = {"allocatable": np.asarray(allocatable, np.int32)}
        with self._lock:
            entry = self.nodes.get(name)
            if entry is None:
                raise UnknownNodeError(
                    f"node_allocatable for unknown node {name!r}")
            entry["arrays"] = dict(entry["arrays"], **arrays)
            rv = self._commit_locked(
                {"kind": NODE_ALLOC, "name": name}, arrays)
        if self._local_bindings:
            self._drain_bindings()
        return rv

    def update_node_devices(self, name: str,
                            devices: dict[str, list[dict]]) -> int:
        """Device-CR refresh (the device daemon's report loop in wire
        form): replace a node's device inventory without re-sending
        allocatable.  Merges into the stored node doc so bootstrap
        replay carries it; same unknown-node posture as node_usage."""
        _validate_devices(devices, "update_node_devices")
        with self._lock:
            entry = self.nodes.get(name)
            if entry is None:
                raise UnknownNodeError(
                    f"node_devices for unknown node {name!r}")
            if entry["doc"].get("devices") == devices:
                # unchanged heartbeat (the koordlet sink re-pushes every
                # interval so a clearing re-upsert gets repaired): no
                # log append, no watcher wakeup — an N-node cluster
                # heartbeating would otherwise shrink the bounded
                # delta-log retention to ~4096/N intervals
                return self.rv
            entry["doc"] = dict(entry["doc"], devices=dict(devices))
            rv = self._commit_locked(
                {"kind": NODE_DEVICES, "name": name,
                 "devices": dict(devices)}, {})
        if self._local_bindings:
            self._drain_bindings()
        return rv

    def remove_node(self, name: str) -> int:
        return self._store_and_commit(
            lambda: self.nodes.pop(name, None),
            {"kind": NODE_REMOVE, "name": name}, {})

    def add_pod(self, name: str, requests: np.ndarray,
                priority: int = 0, quota: str | None = None,
                gang: str | None = None,
                node_selector: dict | None = None,
                labels: dict | None = None,
                owner: str | None = None,
                qos: int = 0,
                arrival_ts: float | None = None) -> int:
        arrays = {"requests": np.asarray(requests, np.int32)}
        doc = {"kind": POD_ADD, "name": name, "priority": priority,
               "quota": quota, "gang": gang,
               "node_selector": node_selector or {},
               "labels": labels or {}, "owner": owner, "qos": qos}
        if arrival_ts is not None:
            # journey-ledger ingest stamp (ISSUE 20): absent from
            # _V2_DEFAULTS on purpose so it rides v2 frames as a sparse
            # extras column only when present — v3 peers see a plain doc
            # key, and stamp-less producers ship zero extra bytes
            doc["arrival_ts"] = float(arrival_ts)
        def store():
            self.pods[name] = {"doc": doc, "arrays": arrays}

        return self._store_and_commit(store, doc, arrays)

    def remove_pod(self, name: str) -> int:
        return self._store_and_commit(
            lambda: self.pods.pop(name, None),
            {"kind": POD_REMOVE, "name": name}, {})

    def upsert_reservation(self, name: str, requests: np.ndarray,
                           owners: list[dict] | None = None,
                           allocate_once: bool = False,
                           ttl_sec: float | None = None,
                           node: str | None = None,
                           node_selector: dict | None = None,
                           tolerations: dict | None = None,
                           restricted: bool = False) -> int:
        """Reservation CR event.  ``owners`` is a list of matcher dicts:
        {"labels": {...}} and/or {"controller": "..."} per entry."""
        arrays = {"requests": np.asarray(requests, np.int64)}
        doc = {"kind": RSV_UPSERT, "name": name,
               "owners": owners or [], "allocate_once": bool(allocate_once),
               "ttl_sec": ttl_sec, "node": node,
               "node_selector": node_selector or {},
               "tolerations": tolerations or {},
               "restricted": bool(restricted)}
        def store():
            self.reservations[name] = {"doc": doc, "arrays": arrays}

        return self._store_and_commit(store, doc, arrays)

    def remove_reservation(self, name: str) -> int:
        return self._store_and_commit(
            lambda: self.reservations.pop(name, None),
            {"kind": RSV_REMOVE, "name": name}, {})

    # -- wire handlers -------------------------------------------------------

    def attach(self, server) -> None:
        self._server = server
        server.register(FrameType.HELLO, self._handle_hello)
        server.register(FrameType.STATE_PUSH, self._handle_state_push)

    def _handle_state_push(self, doc: dict, arrays):
        """Client-originated state event (wire v3): the direction a
        non-Python scheduler plugin feeds its informer view into the
        sidecar (the reference's Go plugin holds the informers; the
        sidecar only knows what it is told — frameworkext/interface.go:70
        passes cluster state INTO plugins the same way).  The event takes
        the normal commit path, so every sync client — including the
        pusher — sees it back as an rv-ordered DELTA."""
        # the channel layer validates before dispatch, but this handler is
        # also reachable directly (the HTTP gateway, embedders): validate
        # here too so a missing kind/name is always a schema error, never
        # a KeyError
        wire.validate_doc(FrameType.STATE_PUSH, doc)
        # same duality for the trace context: the channel already popped
        # and activated it for framed requests (extract returns None and
        # activate passes the ambient context through); the HTTP gateway
        # and direct embedders land here with it still in the doc
        with tracing.activate(tracing.extract(doc)):
            return self._handle_state_push_traced(doc, arrays)

    def _handle_state_push_traced(self, doc: dict, arrays):
        kind = doc.get("kind")
        name = doc["name"]

        def require_vector(key):
            """Validate a pushed resource vector BEFORE it is committed:
            a malformed array from a foreign client must fail ITS call,
            not enter the replay log where it would poison every sync
            client (including future bootstrappers) with a bad row."""
            from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS

            if key not in arrays:
                raise wire.WireSchemaError(
                    f"{kind} push requires a {key!r} array")
            arr = np.asarray(arrays[key])
            if arr.ndim != 1 or arr.shape[0] != NUM_RESOURCE_DIMS:
                raise wire.WireSchemaError(
                    f"{kind} push: {key!r} must have shape "
                    f"({NUM_RESOURCE_DIMS},), got {arr.shape}")
            if arr.dtype.kind not in "iu":
                raise wire.WireSchemaError(
                    f"{kind} push: {key!r} must be an integer vector, "
                    f"got dtype {arr.dtype}")
            if arr.size and (int(arr.max()) > 2**31 - 1
                             or int(arr.min()) < -(2**31)):
                # wider dtypes are fine as encodings, but values the
                # int32 state tensors cannot hold would wrap silently
                raise wire.WireSchemaError(
                    f"{kind} push: {key!r} has values outside int32 "
                    f"range (canonical units are milli-cores / MiB)")

        def require_doc(key, types, type_name):
            """Same poison-guard for the doc's typed fields: a string
            where a mapping belongs would commit fine and then crash
            every sync client's binding on replay (bool-vs-int per
            wire.check_field_type)."""
            val = doc.get(key)
            if val is not None and not wire.check_field_type(val, types):
                raise wire.WireSchemaError(
                    f"{kind} push: field {key!r} must be {type_name} "
                    f"or absent, got {type(val).__name__}")

        for mapping_field in ("labels", "taints", "annotations",
                              "devices", "node_selector", "tolerations"):
            require_doc(mapping_field, dict, "an object")
        require_doc("owners", list, "a list")
        # element shapes too: a string owner or a non-dict device entry
        # would commit fine and crash every sync client's binding on
        # replay — the same poisoning require_vector guards against
        for owner in doc.get("owners") or []:
            if not isinstance(owner, dict):
                raise wire.WireSchemaError(
                    f"{kind} push: every 'owners' entry must be an "
                    f"object, got {type(owner).__name__}")
            # nested matcher fields feed dict()/string handling on
            # replay (SchedulerBinding.reservation_upsert)
            if not wire.check_field_type(
                    owner.get("labels", {}), dict):
                raise wire.WireSchemaError(
                    f"{kind} push: owner 'labels' must be an object")
            if not wire.check_field_type(
                    owner.get("controller", ""), str):
                raise wire.WireSchemaError(
                    f"{kind} push: owner 'controller' must be a string")
        # device inventory shape is validated inside upsert_node /
        # update_node_devices (the consuming kinds route through them,
        # covering in-process callers too — see _validate_devices)
        for scalar_field in ("quota", "gang", "owner", "node"):
            require_doc(scalar_field, str, "a string")
        for int_field in ("priority", "qos"):
            require_doc(int_field, int, "an integer")
        require_doc("ttl_sec", (int, float), "a number")
        require_doc("usage_time", (int, float), "a number")
        require_doc("arrival_ts", (int, float), "a number")
        for bool_field in ("allocate_once", "restricted"):
            require_doc(bool_field, bool, "a boolean")

        if kind == NODE_UPSERT:
            require_vector("allocatable")
            if "usage" in arrays:
                require_vector("usage")
            rv = self.upsert_node(
                name, arrays["allocatable"], usage=arrays.get("usage"),
                labels=doc.get("labels"), taints=doc.get("taints"),
                annotations=doc.get("annotations"),
                devices=doc.get("devices"))
        elif kind == NODE_USAGE:
            require_vector("usage")
            for optional in ("agg_usage", "prod_usage", "sys_usage",
                             "hp_usage", "hp_request", "hp_max_used_req"):
                if optional in arrays:
                    require_vector(optional)
            rv = self.update_node_usage(
                name, arrays["usage"],
                agg_usage=arrays.get("agg_usage"),
                prod_usage=arrays.get("prod_usage"),
                sys_usage=arrays.get("sys_usage"),
                hp_usage=arrays.get("hp_usage"),
                hp_request=arrays.get("hp_request"),
                hp_max_used_req=arrays.get("hp_max_used_req"),
                report_time=doc.get("usage_time"))
        elif kind == NODE_ALLOC:
            require_vector("allocatable")
            rv = self.update_node_allocatable(name, arrays["allocatable"])
        elif kind == NODE_DEVICES:
            if not isinstance(doc.get("devices"), dict):
                raise wire.WireSchemaError(
                    "node_devices push requires a 'devices' object")
            rv = self.update_node_devices(name, doc["devices"])
        elif kind == NODE_REMOVE:
            rv = self.remove_node(name)
        elif kind == POD_ADD:
            require_vector("requests")
            rv = self.add_pod(
                name, arrays["requests"],
                priority=int(doc.get("priority") or 0),
                quota=doc.get("quota"), gang=doc.get("gang"),
                node_selector=doc.get("node_selector"),
                labels=doc.get("labels"), owner=doc.get("owner"),
                qos=int(doc.get("qos") or 0),
                arrival_ts=doc.get("arrival_ts"))
        elif kind == POD_REMOVE:
            rv = self.remove_pod(name)
        elif kind == RSV_UPSERT:
            require_vector("requests")
            rv = self.upsert_reservation(
                name, arrays["requests"], owners=doc.get("owners"),
                allocate_once=bool(doc.get("allocate_once", False)),
                ttl_sec=doc.get("ttl_sec"), node=doc.get("node"),
                node_selector=doc.get("node_selector"),
                tolerations=doc.get("tolerations"),
                restricted=bool(doc.get("restricted", False)))
        elif kind == RSV_REMOVE:
            rv = self.remove_reservation(name)
        else:
            raise wire.WireSchemaError(f"unknown state-push kind {kind!r}")
        return {"rv": rv}, None

    def _snapshot(self, pack=_pack_events
                  ) -> tuple[dict, dict[str, np.ndarray]]:
        events = []
        # replay order matters: nodes before reservations (placement needs
        # rows) before pods (owners need Available reservations)
        for entry in (list(self.nodes.values())
                      + list(self.reservations.values())
                      + list(self.pods.values())):
            events.append((self.rv, entry["doc"], entry["arrays"]))
        doc, arrays = pack(events)
        doc["rv"] = self.rv
        doc["snapshot"] = True
        return doc, arrays

    def _handle_hello(self, doc: dict, arrays):
        # protocol negotiation (ISSUE 19): speak min(peer, local) within
        # the supported window so one release of skew keeps working (a
        # v3 peer gets v1 JSON event lists, a v4 peer gets the columnar
        # codec); anything OUTSIDE the window is rejected loud instead
        # of mis-decoding frames later (api.proto's versioned-contract
        # role)
        peer_proto = int(doc.get("proto", 1))
        if not (wire.MIN_PROTOCOL_VERSION <= peer_proto
                <= wire.PROTOCOL_VERSION):
            raise wire.WireSchemaError(
                f"incompatible message protocol: peer {peer_proto}, "
                f"local {wire.PROTOCOL_VERSION} (supported "
                f"{wire.MIN_PROTOCOL_VERSION}..{wire.PROTOCOL_VERSION})")
        proto = min(peer_proto, wire.PROTOCOL_VERSION)
        # stamp the negotiated version on the live connection: broadcast
        # uses it to pick the columnar vs legacy frame per peer
        channel.set_conn_proto(proto)

        def pack(events):
            if proto >= 4:
                packed = _pack_events_v2(events)
                if packed is not None:
                    return packed
            return _pack_events(events)

        last_rv = int(doc.get("last_rv", -1))
        # instance-aware resync: a peer that last synced a DIFFERENT
        # service incarnation must take the full snapshot even when the
        # rv counters collide (restart resets rv; equal counters say
        # nothing about equal state).  Peers that don't send an instance
        # (older clients, the C conformance client) keep the rv-only
        # behavior.
        peer_instance = doc.get("instance")
        same_instance = peer_instance is None or peer_instance == self.instance
        with self._lock:
            if last_rv == self.rv and same_instance:
                return {"__type__": int(FrameType.ACK), "rv": self.rv,
                        "proto": proto, "instance": self.instance}, None
            if 0 <= last_rv < self.rv and same_instance:
                try:
                    events = self.log.since(last_rv)
                except ResyncRequired:
                    events = None
                if events is not None:
                    out, stacked = pack(events)
                    out["__type__"] = int(FrameType.DELTA)
                    out["rv"] = self.rv
                    out["proto"] = proto
                    out["instance"] = self.instance
                    return out, stacked
            # last_rv < 0 (fresh client), a different service incarnation,
            # ahead of us (rv counter reset), or behind the retained
            # window: full snapshot, client resets
            out, stacked = self._snapshot(pack)
            out["proto"] = proto
            out["instance"] = self.instance
            return out, stacked


class StateSyncClient:
    """Solver-side applier. Wire with an RpcClient:

        binding = SchedulerBinding(scheduler)
        sync = StateSyncClient(binding)
        client = RpcClient(path, on_push=sync.on_push)
        client.connect(); sync.bootstrap(client)

    Reconnect: call bootstrap() again — HELLO carries last_rv, overlap
    replays are dropped by the rv guard, and a ResyncRequired from the
    server falls back to a fresh snapshot apply.
    """

    def __init__(self, binding):
        self.binding = binding
        self.rv = -1
        #: message-protocol version negotiated at the last HELLO (0 =
        #: never bootstrapped); informational + test surface
        self.proto = 0
        #: service boot-epoch last synced from (HELLO echoes it); sent on
        #: reconnect so a restarted service with a colliding rv counter
        #: still forces the full snapshot
        self.instance: str | None = None
        self._lock = threading.RLock()
        self._bootstrapping = False
        self._buffer: list[tuple[dict, dict]] = []
        self.applied = 0
        self.skipped = 0
        #: rv-gap accounting: a DELTA push arriving with rv > self.rv + 1
        #: means an event was LOST on the wire (dropped/reordered frame).
        #: The rv guard makes replays idempotent but cannot conjure a
        #: missing event back — the only repair is a re-HELLO.
        self.gaps = 0
        self.needs_resync = False
        #: optional back-reference to the RpcClient this sync rides
        #: (bind_client): a detected gap severs it so the owner's
        #: reconnect machinery (ReconnectingSidecarClient.ensure ->
        #: on_connect=bootstrap) performs the re-HELLO
        self._client = None

    def bind_client(self, client) -> None:
        """Give the sync a handle to its transport so gap detection can
        self-heal by severing the stream (close() is reader-thread safe;
        the owner's next ensure() re-dials and re-bootstraps)."""
        self._client = client

    def bootstrap(self, client) -> int:
        """HELLO + apply. Pushes that race the HELLO response on the wire
        (a DELTA committed after the snapshot was built can be enqueued to
        this connection first) are buffered and replayed after the
        snapshot, where the rv guard keeps exactly the newer ones."""
        with self._lock:
            self._bootstrapping = True
            self._buffer = []
        try:
            # a detected rv gap advanced self.rv PAST the hole (the
            # fresher events were applied), so a delta re-HELLO from
            # last_rv would replay nothing and the lost event would
            # stay lost forever with the rv counters agreeing — the
            # only honest repair is the full snapshot
            last_rv = -1 if self.needs_resync else self.rv
            hello = {"last_rv": last_rv, "proto": wire.PROTOCOL_VERSION}
            if self.instance is not None:
                hello["instance"] = self.instance
            try:
                ftype, doc, arrays = client.call(FrameType.HELLO, hello)
            except channel.RpcRemoteError as e:
                # pre-negotiation server (its window tops out below
                # ours): re-HELLO once at our floor — min(peer, local)
                # on a negotiating server would land there anyway
                if "incompatible" not in str(e):
                    raise
                hello["proto"] = wire.MIN_PROTOCOL_VERSION
                ftype, doc, arrays = client.call(FrameType.HELLO, hello)
            with self._lock:
                self.proto = int(doc.get("proto", hello["proto"]))
                if doc.get("instance"):
                    self.instance = doc["instance"]
                n = 0
                if ftype is not FrameType.ACK:
                    n = self._apply(doc, arrays, from_bootstrap=True)
                # drain and exit buffering atomically — a push landing
                # after this block goes straight to _apply
                for bdoc, barrays in self._buffer:
                    n += self._apply(bdoc, barrays, from_bootstrap=True)
                self._bootstrapping = False
                self._buffer = []
                self.needs_resync = False
                # even a bare ACK is evidence the feed is alive and we
                # are caught up — the staleness watchdog counts it
                mark = getattr(self.binding, "note_sync_event", None)
                if mark is not None:
                    mark()
                return n
        finally:
            with self._lock:  # exception path (call failed): stop buffering
                self._bootstrapping = False
                self._buffer = []

    def on_push(self, frame) -> None:
        from koordinator_tpu.transport.wire import decode_payload

        if frame.type is not FrameType.DELTA:
            return
        doc, arrays = decode_payload(frame.payload)
        with self._lock:
            if self._bootstrapping:
                self._buffer.append((doc, arrays))
                return
        self._apply(doc, arrays)

    def _apply(self, doc: dict, arrays: dict[str, np.ndarray],
               from_bootstrap: bool = False) -> int:
        n = 0
        gap = False
        with self._lock:
            if doc.get("snapshot"):
                self.binding.reset()
                self.rv = -1  # snapshot events all carry the snapshot rv
            high = self.rv
            # rv-guard pass first, dispatch second: the survivors route
            # as ONE ordered batch so contiguous same-kind runs hit the
            # binding's vectorized apply.  Replay (HELLO DELTA) and
            # bootstrap snapshots decode through the same path.
            to_apply: list[tuple[dict, dict]] = []
            for entry in _decode_events(doc, arrays):
                rv = int(entry.get("rv", doc.get("rv", 0)))
                if not doc.get("snapshot") and rv <= self.rv:
                    self.skipped += 1  # replay overlap: idempotent skip
                    continue
                if (not doc.get("snapshot") and not from_bootstrap
                        and self.rv >= 0 and rv > high + 1):
                    # a WATCH push skipped ahead: every committed rv is
                    # broadcast in order, so a hole means an event was
                    # lost on the wire (drop/reorder).  Apply what we
                    # have (fresher than nothing) but flag the stream
                    # for resync — the rv guard would otherwise silently
                    # drop the missing event forever.  Bootstrap applies
                    # are exempt (the HELLO reply + buffered-push replay
                    # is the server's own contiguous answer).
                    gap = True
                to_apply.append((entry, _unpack_event_arrays(entry,
                                                             arrays)))
                high = max(high, rv)
                n += 1
            self._dispatch_run(to_apply)
            self.rv = max(high, int(doc.get("rv", high)))
            self.applied += n
            if gap:
                from koordinator_tpu import metrics

                self.gaps += 1
                self.needs_resync = True
                metrics.sync_gap_resyncs_total.inc()
        if gap and self._client is not None:
            # sever the stream (outside our lock; close is idempotent
            # and safe on the reader thread): the owner's reconnect path
            # re-dials and re-bootstraps; needs_resync makes that HELLO
            # ask for the full snapshot (last_rv=-1), repairing the hole
            self._client.close()
        return n

    def _dispatch(self, entry: dict, arrs: dict[str, np.ndarray]) -> None:
        _dispatch_event(self.binding, entry, arrs)

    def _dispatch_run(self, items: list[tuple[dict, dict]]) -> None:
        _dispatch_events(self.binding, items)


#: event kinds whose contiguous runs have a vectorized binding apply
#: (value = the batched method name; a binding without it falls back to
#: the per-event route)
_RUN_METHODS = {NODE_USAGE: "node_usage_run", POD_ADD: "pod_add_run"}


def _dispatch_events(binding, items: list[tuple[dict, dict]]) -> None:
    """Route an ORDERED event list, batching contiguous same-kind runs
    into one vectorized binding apply (ISSUE 19).

    Only untraced events coalesce: a trace-stamped event keeps its
    per-event ``sync.<kind>`` span (and its position relative to its
    neighbors — runs never cross it, so apply order is exactly the
    per-event order).  A run of K events costs one scheduler-lock
    round-trip and one ``deltasync_apply`` timeline segment instead of
    K of each; the batched appliers perform the same per-event mutation
    in the same order, so the resulting state is bit-identical."""
    i, n = 0, len(items)
    while i < n:
        entry, arrs = items[i]
        method = _RUN_METHODS.get(entry.get("kind"))
        run_fn = getattr(binding, method, None) if method else None
        if run_fn is None or entry.get(tracing.TRACE_DOC_KEY) is not None:
            _dispatch_event(binding, entry, arrs)
            i += 1
            continue
        j = i + 1
        while (j < n and items[j][0].get("kind") == entry["kind"]
               and items[j][0].get(tracing.TRACE_DOC_KEY) is None):
            j += 1
        if j - i == 1:
            _dispatch_event(binding, entry, arrs)
        else:
            run = items[i:j]
            tl = (timeline.RECORDER.section(
                      "deltasync_apply",
                      f"sync.{entry['kind']}_run[{j - i}]")
                  if timeline.RECORDER.enabled
                  else contextlib.nullcontext())
            with tl:
                run_fn(run)
            # staleness watchdog feed: one mark covers the run — the
            # watchdog reads only the latest timestamp
            mark = getattr(binding, "note_sync_event", None)
            if mark is not None:
                mark()
        i = j


def _dispatch_event(binding, entry: dict,
                    arrs: dict[str, np.ndarray]) -> None:
    """Route one sync event to a binding (shared by the remote client's
    watch stream and the service's in-process subscribers).

    An event stamped with a trace context applies inside a
    ``sync.<kind>`` span joined to that trace (service from the
    binding's ``service_name``), and the binding's handler runs with the
    context active — a pod_add reaching Scheduler.enqueue parents the
    pod's trace to the original submitter's span.  The entry is read,
    never mutated: the same dict may live in the service's stored state
    and replay log."""
    # timeline segment (ISSUE 18): one deltasync_apply span per routed
    # event — the binding holds scheduler.lock while it applies, so
    # this is exactly the host work that contends with solve rounds
    tl = (timeline.RECORDER.section(
              "deltasync_apply", f"sync.{entry['kind']}")
          if timeline.RECORDER.enabled else contextlib.nullcontext())
    ctx = tracing.TraceContext.from_doc(entry.get(tracing.TRACE_DOC_KEY))
    if ctx is None:
        with tl:
            _route_event(binding, entry, arrs)
        return
    with tracing.TRACER.span(
            f"sync.{entry['kind']}",
            service=getattr(binding, "service_name", None),
            parent=ctx,
            attributes={"name": entry.get("name"),
                        "rv": entry.get("rv")}):
        with tl:
            _route_event(binding, entry, arrs)


def _route_event(binding, entry: dict,
                 arrs: dict[str, np.ndarray]) -> None:
    kind = entry["kind"]
    if kind == NODE_UPSERT:
        binding.node_upsert(entry, arrs)
    elif kind == NODE_USAGE:
        binding.node_usage(entry, arrs)
    elif kind == NODE_ALLOC:
        binding.node_alloc(entry, arrs)
    elif kind == NODE_DEVICES:
        binding.node_devices(entry)
    elif kind == NODE_REMOVE:
        binding.node_remove(entry["name"])
    elif kind == POD_ADD:
        binding.pod_add(entry, arrs)
    elif kind == POD_REMOVE:
        binding.pod_remove(entry["name"])
    elif kind == RSV_UPSERT:
        binding.reservation_upsert(entry, arrs)
    elif kind == RSV_REMOVE:
        binding.reservation_remove(entry["name"])
    # staleness watchdog feed: every applied event — remote watch OR
    # in-process drain — is evidence the state feed is alive
    mark = getattr(binding, "note_sync_event", None)
    if mark is not None:
        mark()


class SchedulerBinding:
    """Applies sync events onto a Scheduler + its ClusterSnapshot.

    Every apply holds ``scheduler.lock`` — the sync client runs on the
    RpcClient reader thread while SolveService runs rounds on server
    connection threads; the lock is the single-scheduling-goroutine
    equivalent."""

    #: service attribution for sync-apply spans (_dispatch_event)
    service_name = "scheduler"

    def __init__(self, scheduler):
        self.scheduler = scheduler

    def note_sync_event(self) -> None:
        """Feed the scheduler's snapshot-staleness watchdog: called by
        the dispatch layer for every applied sync event (delta or
        bootstrap heartbeat)."""
        self.scheduler.note_sync_event()

    def reset(self) -> None:
        """Snapshot resync = restart semantics: release EVERYTHING (bound
        pods free their reservations + quota charges before their nodes
        go) and rebuild from the replayed snapshot."""
        with self.scheduler.lock:
            for name in list(self.scheduler.bound):
                self.scheduler.delete_pod(name)
            for name in list(self.scheduler.pending):
                self.scheduler.dequeue(name)
            for spec in self.scheduler.reservations.specs():
                self.scheduler.remove_reservation(spec.name)
            snap = self.scheduler.snapshot
            for name in list(snap.node_index):
                snap.remove_node(name)
            # fine-grained registries restart too: device tensors / CPU
            # topologies not re-registered by the snapshot replay must
            # not survive as live allocatable state
            if self.scheduler.device_manager is not None:
                self.scheduler.device_manager.clear()
            if self.scheduler.cpu_manager is not None:
                self.scheduler.cpu_manager.clear()

    def node_upsert(self, entry: dict, arrs: dict[str, np.ndarray]) -> None:
        from koordinator_tpu.scheduler.snapshot import NodeSpec

        with self.scheduler.lock:
            self.scheduler.snapshot.upsert_node(NodeSpec(
                name=entry["name"],
                allocatable=np.asarray(arrs["allocatable"], np.int32),
                usage=np.asarray(arrs["usage"], np.int32),
                # merged node_usage refreshes ride the stored entry, so a
                # bootstrap/resync replay must carry them too
                agg_usage=(np.asarray(arrs["agg_usage"], np.int32)
                           if "agg_usage" in arrs else None),
                prod_usage=(np.asarray(arrs["prod_usage"], np.int32)
                            if "prod_usage" in arrs else None),
                labels=dict(entry.get("labels", {})),
                taints=dict(entry.get("taints", {})),
            ))
            # fine-grained registries ride the node event: NRT annotations
            # register the CPU topology, the Device inventory registers
            # per-type device tensors.  BOTH follow the same replay-parity
            # rule: an upsert replaces the stored doc wholesale, so a
            # re-upsert without a (valid) NRT annotation must clear the
            # live topology just as an omitted device type clears its
            # tensors — otherwise this process keeps making placements a
            # bootstrap-replay client cannot see
            annotations = entry.get("annotations") or {}
            if self.scheduler.cpu_manager is not None:
                from koordinator_tpu.scheduler.cpu_manager import (
                    register_node_from_annotations,
                )

                if not register_node_from_annotations(
                        self.scheduler.cpu_manager, entry["name"],
                        annotations):
                    self.scheduler.cpu_manager.remove_node(entry["name"])
            # full inventory: upsert_node REPLACES the stored doc's
            # devices wholesale, so a re-upsert that omits a type must
            # clear its live tensors too — otherwise the in-process
            # scheduler and a bootstrap-replay client diverge
            self._register_devices(entry["name"],
                                   entry.get("devices") or {},
                                   full_inventory=True)

    def _register_devices(self, name: str, devices: dict,
                          full_inventory: bool) -> None:
        """Shared device registration (node_upsert + node_devices).
        ``full_inventory=True`` (both event kinds carry the node's whole
        inventory) also CLEARS types previously registered for this node
        but absent from the push — otherwise a disappeared collector
        leaves stale allocatable tensors live while bootstrap replay has
        none (divergence)."""
        manager = self.scheduler.device_manager
        if manager is None:
            return
        for dev_type, inventory in (devices or {}).items():
            if isinstance(inventory, list):
                manager.register_node_devices(dev_type, name, inventory)
        if full_inventory:
            for gone in manager.registered_types_for(name) - set(devices):
                manager.deregister_node_devices(gone, name)

    def node_devices(self, entry: dict) -> None:
        """Device-inventory refresh: re-register the node's per-type
        device tensors (the Device-CR sync path node_upsert also rides);
        unknown node: drop, same as node_usage."""
        with self.scheduler.lock:
            if entry["name"] not in self.scheduler.snapshot.node_index:
                return
            self._register_devices(entry["name"],
                                   entry.get("devices") or {},
                                   full_inventory=True)

    def node_usage(self, entry: dict, arrs: dict[str, np.ndarray]) -> None:
        """Usage-only refresh (the NodeMetric loop): keep the node's
        allocatable/labels, swap its usage rows.  Unknown node: drop —
        the delta may race a node_remove and usage for a gone node is
        moot."""
        import dataclasses as _dc

        with self.scheduler.lock:
            spec = self.scheduler.snapshot.node_specs.get(entry["name"])
            if spec is None:
                return
            usage = np.asarray(arrs["usage"], np.int32)
            self.scheduler.snapshot.upsert_node(_dc.replace(
                spec,
                usage=usage,
                agg_usage=(np.asarray(arrs["agg_usage"], np.int32)
                           if "agg_usage" in arrs else usage),
                prod_usage=(np.asarray(arrs["prod_usage"], np.int32)
                            if "prod_usage" in arrs else usage),
            ))

    def node_usage_run(self,
                       items: list[tuple[dict, dict[str, np.ndarray]]]
                       ) -> None:
        """Vectorized NODE_USAGE run (ISSUE 19): ONE scheduler-lock
        round-trip for K usage refreshes.  Per-event semantics are
        unchanged — same replace, same order, so a later event for the
        same node wins exactly as it would serially — and the snapshot's
        dirty-row set coalesces the K row writes into the next flush's
        single device scatter."""
        import dataclasses as _dc

        with self.scheduler.lock:
            snap = self.scheduler.snapshot
            for entry, arrs in items:
                spec = snap.node_specs.get(entry["name"])
                if spec is None:
                    continue
                usage = np.asarray(arrs["usage"], np.int32)
                snap.upsert_node(_dc.replace(
                    spec,
                    usage=usage,
                    agg_usage=(np.asarray(arrs["agg_usage"], np.int32)
                               if "agg_usage" in arrs else usage),
                    prod_usage=(np.asarray(arrs["prod_usage"], np.int32)
                                if "prod_usage" in arrs else usage),
                ))

    def node_alloc(self, entry: dict, arrs: dict[str, np.ndarray]) -> None:
        """Allocatable-only refresh (the manager's noderesource patch):
        keep the node's usage/labels/devices, swap its allocatable row.
        Unknown node: drop, same as node_usage."""
        import dataclasses as _dc

        with self.scheduler.lock:
            spec = self.scheduler.snapshot.node_specs.get(entry["name"])
            if spec is None:
                return
            self.scheduler.snapshot.upsert_node(_dc.replace(
                spec, allocatable=np.asarray(arrs["allocatable"],
                                             np.int32)))

    def node_remove(self, name: str) -> None:
        with self.scheduler.lock:
            self.scheduler.snapshot.remove_node(name)
            # replay parity: a removed node's fine-grained state goes
            # with it — a bootstrap-replay client has neither its device
            # tensors nor its CPU topology
            if self.scheduler.device_manager is not None:
                self.scheduler.device_manager.remove_node(name)
            if self.scheduler.cpu_manager is not None:
                self.scheduler.cpu_manager.remove_node(name)

    def pod_add(self, entry: dict, arrs: dict[str, np.ndarray]) -> None:
        from koordinator_tpu.scheduler.snapshot import PodSpec

        self.scheduler.enqueue(PodSpec(
            name=entry["name"],
            requests=np.asarray(arrs["requests"], np.int32),
            priority=int(entry.get("priority", 0)),
            quota=entry.get("quota"),
            gang=entry.get("gang"),
            node_selector=dict(entry.get("node_selector", {})),
            labels=dict(entry.get("labels", {})),
            owner=entry.get("owner"),
            qos=int(entry.get("qos", 0)),
            arrival_ts=float(entry.get("arrival_ts") or 0.0),
        ))

    def pod_add_run(self,
                    items: list[tuple[dict, dict[str, np.ndarray]]]
                    ) -> None:
        """Vectorized POD_ADD run (ISSUE 19): build the specs outside
        the scheduler lock, enqueue them under ONE acquisition."""
        from koordinator_tpu.scheduler.snapshot import PodSpec

        self.scheduler.enqueue_many([
            PodSpec(
                name=entry["name"],
                requests=np.asarray(arrs["requests"], np.int32),
                priority=int(entry.get("priority", 0)),
                quota=entry.get("quota"),
                gang=entry.get("gang"),
                node_selector=dict(entry.get("node_selector", {})),
                labels=dict(entry.get("labels", {})),
                owner=entry.get("owner"),
                qos=int(entry.get("qos", 0)),
                arrival_ts=float(entry.get("arrival_ts") or 0.0),
            )
            for entry, arrs in items
        ])

    def pod_remove(self, name: str) -> None:
        # pending, nominated, or bound — a bound delete releases its node
        # reservation and quota charge
        self.scheduler.delete_pod(name)

    def reservation_upsert(self, entry: dict,
                           arrs: dict[str, np.ndarray]) -> None:
        from koordinator_tpu.scheduler.reservations import (
            OwnerMatcher,
            ReservationSpec,
        )

        owners = [
            OwnerMatcher(labels=dict(m.get("labels", {})),
                         controller=m.get("controller"))
            for m in entry.get("owners", [])
        ]
        self.scheduler.add_reservation(ReservationSpec(
            name=entry["name"],
            requests=np.asarray(arrs["requests"], np.int64),
            owners=owners,
            allocate_once=bool(entry.get("allocate_once", False)),
            ttl_sec=entry.get("ttl_sec"),
            node=entry.get("node"),
            node_selector=dict(entry.get("node_selector", {})),
            tolerations=dict(entry.get("tolerations", {})),
            restricted=bool(entry.get("restricted", False)),
        ))

    def reservation_remove(self, name: str) -> None:
        self.scheduler.remove_reservation(name)
