"""Shared retry/backoff + circuit-breaker policy for control-plane dials.

One error-pacing policy for every component that re-dials a peer
(``cmd.binaries.ReconnectingSidecarClient`` for the koordlet's reporters
and the manager's colocation loop; any embedder wiring a
``StateSyncClient`` resync loop) — before this existed, a dead sidecar
was re-dialed with zero backoff every tick, so a 10k-node cluster's
agents would synchronously hammer a restarting scheduler.

- :class:`RetryPolicy` is the schedule: exponential backoff with
  jitter and an optional max-elapsed budget.  Frozen dataclass — share
  one instance freely.
- :class:`RetrySchedule` is one retry *session* over a policy
  (attempt counter + elapsed budget).
- :class:`CircuitBreaker` is the dial gate: CLOSED passes everything;
  a failure (threshold 1 by default — a refused dial is already a
  strong signal) OPENs it for one backoff window; the first caller
  after the window gets the HALF_OPEN probe; probe success re-CLOSEs,
  probe failure re-OPENs with the next (longer) window.  Over a
  T-second outage that is O(log T) dials until the backoff cap, then
  one dial per ``max_backoff_s`` — not one per tick.

State is observable via ``koord_transport_circuit_breaker_state``
(0=closed, 1=half-open, 2=open; label ``target``) and
``koord_transport_circuit_breaker_transitions_total``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_CODE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff schedule with jitter.

    ``jitter``: "full" draws uniform(0, raw) (AWS full-jitter — best
    for herd spread, can yield near-zero waits), "equal" draws
    uniform(raw/2, raw) (never degenerate — the breaker default),
    "none" is deterministic (tests)."""

    initial_backoff_s: float = 0.2
    max_backoff_s: float = 30.0
    multiplier: float = 2.0
    jitter: str = "equal"
    #: total budget for one RetrySchedule; None = unbounded
    max_elapsed_s: Optional[float] = None

    def backoff(self, attempt: int,
                rng: random.Random | None = None) -> float:
        """Delay before retry number ``attempt`` (0-based)."""
        raw = self.initial_backoff_s * (self.multiplier ** attempt)
        raw = min(raw, self.max_backoff_s)
        if self.jitter == "none" or rng is None:
            return raw
        if self.jitter == "full":
            return rng.uniform(0.0, raw)
        return rng.uniform(raw / 2.0, raw)


class RetrySchedule:
    """One retry session: next_delay() until None (budget exhausted)."""

    def __init__(self, policy: RetryPolicy,
                 rng: random.Random | None = None, clock=time.monotonic):
        self.policy = policy
        self.rng = rng if rng is not None else random.Random()
        self.clock = clock
        self.attempts = 0
        self._started = clock()

    def elapsed(self) -> float:
        return self.clock() - self._started

    def next_delay(self) -> Optional[float]:
        """Delay to sleep before the next attempt, or None when the
        max-elapsed budget is spent (fail for real)."""
        delay = self.policy.backoff(self.attempts, self.rng)
        self.attempts += 1
        budget = self.policy.max_elapsed_s
        if budget is not None and self.elapsed() + delay > budget:
            return None
        return delay


class CircuitBreaker:
    """Dial gate with backoff-driven open windows.

    Usage (single caller or under the owner's lock):

        if not breaker.allow():
            raise RpcError(f"circuit open: {breaker.describe()}")
        try:
            dial()
        except OSError:
            breaker.record_failure()
            raise
        breaker.record_success()
    """

    def __init__(self, target: str = "", policy: RetryPolicy | None = None,
                 failure_threshold: int = 1, clock=time.monotonic,
                 rng: random.Random | None = None):
        self.target = target
        self.policy = policy or RetryPolicy()
        self.failure_threshold = max(1, failure_threshold)
        self.clock = clock
        self.rng = rng if rng is not None else random.Random()
        self.state = CLOSED
        self.opens = 0            # consecutive open windows (backoff input)
        self.open_total = 0       # lifetime opens (observability)
        self._consecutive = 0
        self._open_until = 0.0
        self._lock = threading.Lock()
        self._publish()

    def _publish(self) -> None:
        from koordinator_tpu import metrics

        metrics.breaker_state.set(_STATE_CODE[self.state],
                                  labels={"target": self.target})

    def _transition(self, state: str) -> None:
        from koordinator_tpu import metrics

        if state == self.state:
            return
        self.state = state
        metrics.breaker_transitions_total.inc(
            labels={"target": self.target, "to": state})
        self._publish()

    def allow(self) -> bool:
        """May the caller dial now?  Transitions OPEN -> HALF_OPEN when
        the window has elapsed (the caller is the probe)."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN and self.clock() >= self._open_until:
                self._transition(HALF_OPEN)
                return True
            # OPEN within the window, or HALF_OPEN with a probe already
            # in flight: wait
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self.opens = 0
            self._transition(CLOSED)

    def reset(self) -> None:
        """Force-close, zeroing the backoff history.  The heal seam: a
        drill's ``FaultInjector.heal()`` resets registered breakers so
        callers probe the healed peer immediately instead of waiting out
        the remaining open window (which chaos backoff growth can have
        pushed far past the heal)."""
        with self._lock:
            self._consecutive = 0
            self.opens = 0
            self._open_until = 0.0
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if (self.state == HALF_OPEN
                    or self._consecutive >= self.failure_threshold):
                window = self.policy.backoff(self.opens, self.rng)
                self._open_until = self.clock() + window
                self.opens += 1
                self.open_total += 1
                self._transition(OPEN)

    def retry_in(self) -> float:
        """Seconds until the next probe is allowed (0 when dialable)."""
        with self._lock:
            if self.state == OPEN:
                return max(0.0, self._open_until - self.clock())
            return 0.0

    def describe(self) -> str:
        return (f"{self.state}, retry in {self.retry_in():.2f}s, "
                f"{self.open_total} opens")
