"""Socket RPC: a threaded server with per-type handlers and a blocking
client with request correlation and reconnect-with-resync.

Shape mirrors the reference's hook server plumbing
(``runtimeproxy/dispatcher`` + ``nri/server.go``): the server is a
registry of handlers keyed by call type; every handler gets the decoded
(doc, arrays) and returns (doc, arrays) — errors travel as ERROR frames
and surface client-side as :class:`RpcError` (fail-open decisions belong
to the caller, matching the proxy's fail-open dispatch).

Every connection writes through a bounded outbound queue drained by a
dedicated sender thread, so a stalled peer can never block a handler or a
broadcaster — it just starts dropping (and is reaped when its socket
dies), the same backpressure posture as an apiserver watch that a slow
client falls off of.
"""

from __future__ import annotations

import os
import queue
import socket
import socketserver
import threading
from typing import Callable, Optional

import numpy as np

from koordinator_tpu.transport.wire import (
    Frame,
    FrameType,
    WireSchemaError,
    decode_payload,
    encode_payload,
    read_frame,
    validate_doc,
)

Handler = Callable[[dict, dict[str, np.ndarray]],
                   tuple[dict, dict[str, np.ndarray] | None]]

#: Outbound frames buffered per connection before the peer is declared
#: stalled (poison + forced resync).  Sized to the DeltaLog retention
#: window (deltasync.DeltaLog, 4096): a burst the delta log could replay
#: WITHOUT a full-snapshot resync must not poison the wire first — with a
#: tight producer loop the sender thread drains in ~5ms GIL slices, and
#: the r5 deltasync bench measured a 1,024-event NodeMetric burst
#: overflowing the old 256-deep queue at event 256, killing the watch.
#: Poison now triggers exactly when falling behind means a resync is
#: unavoidable anyway.
SEND_QUEUE_DEPTH = 4096


class RpcError(RuntimeError):
    pass


class RpcRemoteError(RpcError):
    """The peer answered with an ERROR frame: the request was rejected
    (schema error, unknown node, ...) but the CONNECTION is healthy.
    Callers that manage connection lifecycle must not tear down a
    shared client on it — closing would kill other threads' in-flight
    calls on the same socket."""


def _recv_exact(sock: socket.socket):
    def recv(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)
    return recv


class _Conn:
    """One server-side connection: bounded outbound queue + sender thread."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.queue: "queue.Queue[Optional[Frame]]" = queue.Queue(
            SEND_QUEUE_DEPTH)
        self.alive = True
        self.dropped = 0
        self._sender = threading.Thread(target=self._drain, daemon=True)
        self._sender.start()

    def send(self, frame: Frame) -> None:
        """Enqueue; never blocks the caller. A full queue (stalled peer)
        drops the frame and poisons the connection so the peer resyncs on
        reconnect instead of silently missing one event."""
        if not self.alive:
            return
        try:
            self.queue.put_nowait(frame)
        except queue.Full:
            self.dropped += 1
            self.alive = False
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self) -> None:
        self.alive = False
        try:
            self.queue.put_nowait(None)
        except queue.Full:
            # cannot signal the sender through a full queue — sever
            # directly; queued frames are lost, but a full queue means
            # the peer stalled (poison semantics anyway)
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            # the sender may have drained the whole backlog between our
            # Full and the shutdown, in which case it is blocked on
            # queue.get() with no poison coming — a permanently leaked
            # thread.  Retry once: either the poison lands now (queue
            # has room) and the sender exits on it, or the queue is
            # still full, meaning frames remain and the sender will hit
            # the shut-down socket's OSError on its next send and exit.
            try:
                self.queue.put_nowait(None)
            except queue.Full:
                pass

    def _drain(self) -> None:
        while True:
            frame = self.queue.get()
            if frame is None:
                # poison AFTER the backlog: already-queued frames (e.g.
                # a response to an in-flight call whose side effect
                # already applied) still reach the peer, THEN the wire
                # is severed so the peer sees EOF and its reconnect
                # logic fires — without the shutdown a stopped server's
                # connections stay half-open and `connected` never
                # flips (r5 manager-reconnect test caught this)
                try:
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            try:
                self.sock.sendall(frame.encode())
            except OSError:
                self.alive = False
                return


class _ConnHandler(socketserver.BaseRequestHandler):
    def handle(self):
        server: RpcServer = self.server.rpc  # type: ignore[attr-defined]
        recv = _recv_exact(self.request)
        conn = _Conn(self.request)
        server._on_connect(conn)
        try:
            while True:
                try:
                    frame = read_frame(recv)
                except (ConnectionError, OSError):
                    return
                if frame.type is FrameType.PING:
                    conn.send(Frame(FrameType.ACK, frame.request_id,
                                    encode_payload({})))
                    continue
                handler = server.handlers.get(frame.type)
                if handler is None:
                    conn.send(Frame(FrameType.ERROR, frame.request_id,
                                    encode_payload(
                                        {"message":
                                         f"no handler for {frame.type}"})))
                    continue
                try:
                    doc, arrays = decode_payload(frame.payload)
                    # typed request schemas: version/shape skew between
                    # peers fails loud here, not deep inside a handler
                    validate_doc(frame.type, doc)
                    out_doc, out_arrays = handler(doc, arrays)
                    rtype = FrameType(out_doc.pop(
                        "__type__", int(_RESPONSE_TYPE.get(
                            frame.type, FrameType.ACK))))
                    conn.send(Frame(rtype, frame.request_id,
                                    encode_payload(out_doc, out_arrays)))
                except WireSchemaError as e:
                    conn.send(Frame(FrameType.ERROR, frame.request_id,
                                    encode_payload(
                                        {"message": str(e),
                                         "schema": True})))
                except Exception as e:  # handler bug: fail the call, not conn
                    conn.send(Frame(FrameType.ERROR, frame.request_id,
                                    encode_payload({"message": repr(e)})))
        finally:
            server._on_disconnect(conn)
            conn.close()


_RESPONSE_TYPE = {
    FrameType.HELLO: FrameType.SNAPSHOT,
    FrameType.SOLVE_REQUEST: FrameType.SOLVE_RESPONSE,
    FrameType.HOOK_REQUEST: FrameType.HOOK_RESPONSE,
}


class _UnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _TcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _parse_addr(addr: str):
    """"tcp://host:port" -> ("tcp", (host, port)); anything else is a
    unix-socket path.  IPv4 / hostnames only — an IPv6 literal would need
    AF_INET6 plumbing the transport doesn't have, so reject it loudly
    instead of failing later with an opaque gaierror."""
    if addr.startswith("tcp://"):
        host, _, port = addr[len("tcp://"):].rpartition(":")
        if "[" in host or "]" in host:
            raise ValueError(
                f"IPv6 literals are not supported by the framed "
                f"transport: {addr!r}")
        return "tcp", (host or "127.0.0.1", int(port))
    return "unix", addr


class RpcServer:
    """Framed RPC server; one receive thread + one send thread per
    connection.  ``path`` is a unix-socket path (same-host peers) or
    ``tcp://host:port`` (cross-host control plane — the reference's
    gRPC boundary listens on TCP the same way)."""

    def __init__(self, path: str):
        self.path = path
        self.kind, target = _parse_addr(path)
        self.handlers: dict[FrameType, Handler] = {}
        self._conns: list[_Conn] = []
        self._conn_lock = threading.Lock()
        if self.kind == "unix":
            if os.path.exists(target):
                os.unlink(target)
            self._server = _UnixServer(target, _ConnHandler)
        else:
            self._server = _TcpServer(target, _ConnHandler)
        self._server.rpc = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        """Resolved listen address (useful with tcp://host:0)."""
        if self.kind == "unix":
            return self.path
        host, port = self._server.server_address[:2]
        return f"tcp://{host}:{port}"

    def register(self, ftype: FrameType, handler: Handler) -> None:
        self.handlers[ftype] = handler

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._conn_lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close()
        if self.kind == "unix" and os.path.exists(self.path):
            os.unlink(self.path)

    # -- server push (watch-stream analog) ----------------------------------

    def _on_connect(self, conn: _Conn) -> None:
        with self._conn_lock:
            self._conns.append(conn)

    def _on_disconnect(self, conn: _Conn) -> None:
        with self._conn_lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def broadcast(self, ftype: FrameType, doc: dict,
                  arrays: dict[str, np.ndarray] | None = None) -> int:
        """Push a frame (request_id 0 = unsolicited) to all live
        connections — the informer watch-event fan-out. Never blocks:
        frames go through each connection's bounded queue."""
        frame = Frame(ftype, 0, encode_payload(doc, arrays))
        with self._conn_lock:
            conns = list(self._conns)
        sent = 0
        for conn in conns:
            if conn.alive:
                conn.send(frame)
                sent += 1
        return sent


class RpcClient:
    """Blocking request/response client. Unsolicited (request_id 0) frames
    are delivered to ``on_push`` — the watch stream."""

    def __init__(self, path: str, on_push=None, timeout: float = 10.0):
        self.path = path
        self.on_push = on_push
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._pending: dict[int, "_Waiter"] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 1
        self._reader: Optional[threading.Thread] = None
        self.connected = False
        self.push_errors = 0

    def connect(self) -> None:
        kind, target = _parse_addr(self.path)
        if kind == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(target)
            sock.settimeout(None)
        else:
            # bound by the client timeout: a black-holed TCP target must
            # fail in self.timeout, not the OS connect default (~2 min)
            sock = socket.create_connection(target, timeout=self.timeout)
            sock.settimeout(None)   # reader thread blocks indefinitely
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self.connected = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def close(self) -> None:
        self.connected = False
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()
            self._sock = None

    def _read_loop(self) -> None:
        assert self._sock is not None
        recv = _recv_exact(self._sock)
        try:
            while True:
                frame = read_frame(recv)
                if frame.request_id == 0:
                    if self.on_push is not None:
                        try:
                            self.on_push(frame)
                        except Exception:
                            # a bad push must not kill the stream: later
                            # frames still correlate calls and pushes
                            self.push_errors += 1
                    continue
                with self._pending_lock:
                    waiter = self._pending.pop(frame.request_id, None)
                if waiter is not None:
                    waiter.frame = frame
                    waiter.event.set()
        except (ConnectionError, OSError):
            pass
        finally:
            self.connected = False
            with self._pending_lock:
                waiters = list(self._pending.values())
                self._pending.clear()
            for w in waiters:
                w.event.set()  # fail fast with frame=None

    def call(self, ftype: FrameType, doc: dict,
             arrays: dict[str, np.ndarray] | None = None
             ) -> tuple[FrameType, dict, dict[str, np.ndarray]]:
        if self._sock is None:
            raise RpcError("not connected")
        waiter = _Waiter()
        with self._pending_lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = waiter
        frame = Frame(ftype, req_id, encode_payload(doc, arrays))
        try:
            with self._send_lock:
                self._sock.sendall(frame.encode())
        except OSError as e:
            self.connected = False
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcError(f"connection lost: {e}") from e
        if not waiter.event.wait(self.timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcError("rpc timeout")
        if waiter.frame is None:
            raise RpcError("connection lost")
        rdoc, rarrays = decode_payload(waiter.frame.payload)
        if waiter.frame.type is FrameType.ERROR:
            raise RpcRemoteError(rdoc.get("message", "remote error"))
        return waiter.frame.type, rdoc, rarrays


class _Waiter:
    def __init__(self):
        self.event = threading.Event()
        self.frame: Optional[Frame] = None
