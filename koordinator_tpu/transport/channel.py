"""Socket RPC: a threaded server with per-type handlers and a blocking
client with request correlation and reconnect-with-resync.

Shape mirrors the reference's hook server plumbing
(``runtimeproxy/dispatcher`` + ``nri/server.go``): the server is a
registry of handlers keyed by call type; every handler gets the decoded
(doc, arrays) and returns (doc, arrays) — errors travel as ERROR frames
and surface client-side as :class:`RpcError` (fail-open decisions belong
to the caller, matching the proxy's fail-open dispatch).

Every connection writes through a bounded outbound queue drained by a
dedicated sender thread, so a stalled peer can never block a handler or a
broadcaster — it just starts dropping (and is reaped when its socket
dies), the same backpressure posture as an apiserver watch that a slow
client falls off of.
"""

from __future__ import annotations

import os
import queue
import socket
import socketserver
import threading
from typing import Callable, Optional

import numpy as np

from koordinator_tpu import tracing
from koordinator_tpu.transport.wire import (
    Frame,
    FrameType,
    WireSchemaError,
    decode_payload,
    encode_payload,
    read_frame,
    validate_doc,
)

Handler = Callable[[dict, dict[str, np.ndarray]],
                   tuple[dict, dict[str, np.ndarray] | None]]

#: the connection whose frame is currently being dispatched on THIS
#: thread — handlers are (doc, arrays) -> (doc, arrays) with no
#: connection parameter, but protocol negotiation (HELLO) must stamp
#: the NEGOTIATED message protocol onto the connection so later
#: broadcasts pick the right event encoding per peer.  Dispatch workers
#: are per-connection threads, so a threadlocal is race-free.
_DISPATCH = threading.local()


def set_conn_proto(proto: int) -> None:
    """Stamp the negotiated message protocol on the connection whose
    request is currently being dispatched (no-op outside dispatch —
    e.g. a handler invoked directly in tests)."""
    conn = getattr(_DISPATCH, "conn", None)
    if conn is not None:
        conn.proto = int(proto)

#: Outbound frames buffered per connection before the peer is declared
#: stalled (poison + forced resync).  Sized to the DeltaLog retention
#: window (deltasync.DeltaLog, 4096): a burst the delta log could replay
#: WITHOUT a full-snapshot resync must not poison the wire first — with a
#: tight producer loop the sender thread drains in ~5ms GIL slices, and
#: the r5 deltasync bench measured a 1,024-event NodeMetric burst
#: overflowing the old 256-deep queue at event 256, killing the watch.
#: Poison now triggers exactly when falling behind means a resync is
#: unavoidable anyway.
SEND_QUEUE_DEPTH = 4096

#: Inbound frames buffered per connection between the read loop and the
#: dispatch worker.  The reader stays eager so every frame is stamped
#: with its TRUE arrival time — a request queued behind a slow handler
#: (a 6s solve on the same connection) must burn its deadline budget
#: while it waits, not get a fresh one when the handler finally returns.
#: Bounded so a fast pusher cannot balloon memory: a full inbox blocks
#: the reader and backpressure falls back to the socket, exactly the
#: pre-split behavior (frames past the window get stamped late, which
#: only makes deadlines LENIENT, never shed-happy).
RECV_QUEUE_DEPTH = 64


class RpcError(RuntimeError):
    pass


class RpcRemoteError(RpcError):
    """The peer answered with an ERROR frame: the request was rejected
    (schema error, unknown node, ...) but the CONNECTION is healthy.
    Callers that manage connection lifecycle must not tear down a
    shared client on it — closing would kill other threads' in-flight
    calls on the same socket.

    ``doc`` is the ERROR frame's decoded document; ``resync`` is True
    when the server asks the client to re-HELLO (e.g. a state push for
    a node a restarted service no longer knows — the client's watch
    view is stale, not just this one request)."""

    def __init__(self, message: str, doc: dict | None = None):
        super().__init__(message)
        self.doc = doc or {}
        self.resync = bool(self.doc.get("resync", False))


class RpcDeadlineError(RpcRemoteError):
    """The server shed the request because its ``deadline_ms`` expired
    before the handler could run (ERROR frame with ``expired: true``)."""


class DeadlineExpired(RuntimeError):
    """Raised by a handler that found its request's deadline already
    passed (``doc['__expires_at__']``) — the channel layer answers with
    an ERROR frame carrying ``expired: true`` instead of a generic
    handler failure."""


def _recv_exact(sock: socket.socket, faults=None):
    def recv(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            if faults is not None:
                faults.on_read()   # slow-drip read injection
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)
    return recv


class _Conn:
    """One server-side connection: bounded outbound queue + sender thread."""

    def __init__(self, sock: socket.socket, faults=None):
        self.sock = sock
        self.faults = faults
        self.queue: "queue.Queue[Optional[Frame]]" = queue.Queue(
            SEND_QUEUE_DEPTH)
        self.alive = True
        self.dropped = 0
        #: negotiated message protocol for this peer (stamped by the
        #: HELLO handler via set_conn_proto); 0 = never negotiated —
        #: broadcasts treat it as a legacy peer (JSON event lists)
        self.proto = 0
        #: reorder-fault hold slot: a push pulled out of order, emitted
        #: after the next outbound frame (or on poison)
        self._held: Optional[bytes] = None
        self._sender = threading.Thread(target=self._drain, daemon=True)
        self._sender.start()

    def send(self, frame: Frame) -> None:
        """Enqueue; never blocks the caller. A full queue (stalled peer)
        drops the frame and poisons the connection so the peer resyncs on
        reconnect instead of silently missing one event."""
        if not self.alive:
            return
        try:
            self.queue.put_nowait(frame)
        except queue.Full:
            self.dropped += 1
            self.alive = False
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self) -> None:
        self.alive = False
        try:
            self.queue.put_nowait(None)
        except queue.Full:
            # cannot signal the sender through a full queue — sever
            # directly; queued frames are lost, but a full queue means
            # the peer stalled (poison semantics anyway)
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            # the sender may have drained the whole backlog between our
            # Full and the shutdown, in which case it is blocked on
            # queue.get() with no poison coming — a permanently leaked
            # thread.  Retry once: either the poison lands now (queue
            # has room) and the sender exits on it, or the queue is
            # still full, meaning frames remain and the sender will hit
            # the shut-down socket's OSError on its next send and exit.
            try:
                self.queue.put_nowait(None)
            except queue.Full:
                pass

    def _drain(self) -> None:
        while True:
            frame = self.queue.get()
            if frame is None:
                # poison AFTER the backlog: already-queued frames (e.g.
                # a response to an in-flight call whose side effect
                # already applied) still reach the peer, THEN the wire
                # is severed so the peer sees EOF and its reconnect
                # logic fires — without the shutdown a stopped server's
                # connections stay half-open and `connected` never
                # flips (r5 manager-reconnect test caught this)
                try:
                    if self._held is not None:
                        self.sock.sendall(self._held)
                        self._held = None
                    self.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return
            try:
                if not self._send_one(frame):
                    self.alive = False
                    try:
                        self.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    return
            except OSError:
                self.alive = False
                return

    def _send_one(self, frame: Frame) -> bool:
        """Write one frame, applying any scheduled fault.  Returns False
        when the fault severed the connection (caller shuts down)."""
        data = frame.encode()
        inj = self.faults
        if inj is not None:
            action = inj.outbound_action(is_push=frame.request_id == 0)
            if action == "sever":
                return False
            if action == "truncate":
                self.sock.sendall(data[: inj.truncate_at(len(data))])
                return False
            if action == "drop":
                return True
            if action == "delay":
                inj.delay()
            elif action == "duplicate":
                self.sock.sendall(data)
            elif action == "reorder":
                if self._held is None:
                    self._held = data      # emit after the NEXT frame
                    return True
        self.sock.sendall(data)
        if self._held is not None:
            held, self._held = self._held, None
            self.sock.sendall(held)
        return True


class _ConnHandler(socketserver.BaseRequestHandler):
    """Per-connection: an EAGER read loop (this thread) feeding a
    bounded inbox consumed by one dispatch worker.  The split exists for
    deadline honesty: handlers are sequential per connection, so a
    request read lazily after a 6s solve would be stamped 6s late and
    granted a fresh budget its caller already burned.  The eager reader
    stamps true arrival; the worker keeps the sequential handler
    semantics."""

    def handle(self):
        import time as _time

        server: RpcServer = self.server.rpc  # type: ignore[attr-defined]
        recv = _recv_exact(self.request, faults=server.faults)
        conn = _Conn(self.request, faults=server.faults)
        server._on_connect(conn)
        inbox: "queue.Queue[Optional[tuple[Frame, float]]]" = queue.Queue(
            RECV_QUEUE_DEPTH)
        worker = threading.Thread(
            target=_dispatch_loop, args=(server, conn, inbox), daemon=True)
        worker.start()
        try:
            while True:
                try:
                    frame = read_frame(recv)
                except (ConnectionError, OSError):
                    return
                if frame.type is FrameType.PING:
                    # liveness probes answer at read time — a heartbeat
                    # must not queue behind a long solve
                    conn.send(Frame(FrameType.ACK, frame.request_id,
                                    encode_payload({})))
                    continue
                inbox.put((frame, _time.monotonic()))
        finally:
            # poison AFTER the backlog (blocking put: the worker is
            # draining); already-read frames still run their handlers —
            # their side effects (state pushes) are the peer's committed
            # intent — but responses to a gone peer drop in conn.send
            inbox.put(None)
            # bounded join: the worker may sit in a long handler; the
            # connection teardown must not wait it out (the worker exits
            # on the poison right after, sends going to a dead conn)
            worker.join(timeout=5.0)
            server._on_disconnect(conn)
            conn.close()


def _dispatch_loop(server: "RpcServer", conn: _Conn, inbox) -> None:
    while True:
        item = inbox.get()
        if item is None:
            return
        frame, recv_time = item
        _dispatch_one(server, conn, frame, recv_time)


def _dispatch_one(server: "RpcServer", conn: _Conn, frame: Frame,
                  recv_time: float) -> None:
    import time as _time

    from koordinator_tpu import metrics

    handler = server.handlers.get(frame.type)
    if handler is None:
        conn.send(Frame(FrameType.ERROR, frame.request_id,
                        encode_payload(
                            {"message": f"no handler for {frame.type}"})))
        return
    try:
        doc, arrays = decode_payload(frame.payload)
        # typed request schemas: version/shape skew between
        # peers fails loud here, not deep inside a handler
        validate_doc(frame.type, doc)
        # deadline propagation: the caller's remaining budget rides the
        # doc; the budget clock starts at frame ARRIVAL (the eager read
        # loop's stamp — no cross-host clock sync needed).  Expired
        # already -> shed without dispatching; otherwise the absolute
        # expiry is handed to the handler so long waits INSIDE it (the
        # scheduler round lock) can shed late too (DeadlineExpired).
        deadline_ms = doc.pop("deadline_ms", None)
        if deadline_ms is not None:
            expires = recv_time + float(deadline_ms) / 1000.0
            if _time.monotonic() >= expires:
                metrics.rpc_deadline_shed_total.inc(
                    labels={"type": frame.type.name})
                conn.send(Frame(
                    FrameType.ERROR, frame.request_id,
                    encode_payload(
                        {"message": "deadline expired before "
                         "dispatch", "expired": True})))
                return
            doc["__expires_at__"] = expires
        # trace propagation: a caller's TraceContext rides the doc like
        # deadline_ms; a traced request gets a server-side dispatch span
        # (joined to the caller's trace), untraced requests pay one dict
        # lookup and no span
        tctx = tracing.extract(doc)
        _DISPATCH.conn = conn
        try:
            if tctx is not None:
                with tracing.TRACER.span(
                        f"rpc.{frame.type.name}",
                        service=server.service or None, parent=tctx):
                    out_doc, out_arrays = handler(doc, arrays)
            else:
                out_doc, out_arrays = handler(doc, arrays)
        finally:
            _DISPATCH.conn = None
        rtype = FrameType(out_doc.pop(
            "__type__", int(_RESPONSE_TYPE.get(
                frame.type, FrameType.ACK))))
        conn.send(Frame(rtype, frame.request_id,
                        encode_payload(out_doc, out_arrays)))
    except DeadlineExpired as e:
        conn.send(Frame(FrameType.ERROR, frame.request_id,
                        encode_payload(
                            {"message": str(e), "expired": True})))
    except WireSchemaError as e:
        err_doc = {"message": str(e), "schema": True}
        if getattr(e, "resync", False):
            # the client's whole watch view is stale (e.g. a push for a
            # node this service incarnation never learned) — tell it to
            # re-HELLO, not just fail the one call
            err_doc["resync"] = True
        conn.send(Frame(FrameType.ERROR, frame.request_id,
                        encode_payload(err_doc)))
    except Exception as e:  # handler bug: fail the call, not conn
        conn.send(Frame(FrameType.ERROR, frame.request_id,
                        encode_payload({"message": repr(e)})))


_RESPONSE_TYPE = {
    FrameType.HELLO: FrameType.SNAPSHOT,
    FrameType.SOLVE_REQUEST: FrameType.SOLVE_RESPONSE,
    FrameType.HOOK_REQUEST: FrameType.HOOK_RESPONSE,
}


class _UnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _TcpServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _parse_addr(addr: str):
    """"tcp://host:port" -> ("tcp", (host, port)); anything else is a
    unix-socket path.  IPv4 / hostnames only — an IPv6 literal would need
    AF_INET6 plumbing the transport doesn't have, so reject it loudly
    instead of failing later with an opaque gaierror."""
    if addr.startswith("tcp://"):
        host, _, port = addr[len("tcp://"):].rpartition(":")
        if "[" in host or "]" in host:
            raise ValueError(
                f"IPv6 literals are not supported by the framed "
                f"transport: {addr!r}")
        return "tcp", (host or "127.0.0.1", int(port))
    return "unix", addr


class RpcServer:
    """Framed RPC server; per connection, one eager receive thread, one
    sequential dispatch worker, and one send thread.  ``path`` is a
    unix-socket path (same-host peers) or ``tcp://host:port``
    (cross-host control plane — the reference's gRPC boundary listens
    on TCP the same way)."""

    def __init__(self, path: str, faults=None, service: str = ""):
        self.path = path
        #: optional faults.FaultInjector — chaos harness only; None in
        #: production (one attribute check per frame)
        self.faults = faults
        #: service name stamped on traced-request dispatch spans so a
        #: multi-binary test process still attributes spans to the right
        #: component; empty falls back to the process tracer's service
        self.service = service
        self.kind, target = _parse_addr(path)
        self.handlers: dict[FrameType, Handler] = {}
        self._conns: list[_Conn] = []
        self._conn_lock = threading.Lock()
        self._stopped = False
        if self.kind == "unix":
            if os.path.exists(target):
                os.unlink(target)
            self._server = _UnixServer(target, _ConnHandler)
        else:
            self._server = _TcpServer(target, _ConnHandler)
        self._server.rpc = self  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        """Resolved listen address (useful with tcp://host:0)."""
        if self.kind == "unix":
            return self.path
        host, port = self._server.server_address[:2]
        return f"tcp://{host}:{port}"

    def register(self, ftype: FrameType, handler: Handler) -> None:
        self.handlers[ftype] = handler

    def start(self) -> None:
        # tight poll interval: shutdown() blocks until serve_forever's
        # select loop notices, and the 0.5s stdlib default turns every
        # stop() — a restart, a failover, a test teardown — into a
        # half-second stall
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # flag first: a connection whose handler thread registers AFTER
        # the conns snapshot below would otherwise never be closed and
        # its peer would hang on a half-dead socket (race exposed by the
        # tight poll interval — stop() used to be slow enough to lose it)
        with self._conn_lock:
            self._stopped = True
        self._server.shutdown()
        self._server.server_close()
        with self._conn_lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close()
        if self.kind == "unix" and os.path.exists(self.path):
            os.unlink(self.path)

    # -- server push (watch-stream analog) ----------------------------------

    def _on_connect(self, conn: _Conn) -> None:
        with self._conn_lock:
            if self._stopped:
                # lost the race with stop(): sever immediately so the
                # peer sees EOF instead of a silently dead server
                conn.close()
                return
            self._conns.append(conn)

    def _on_disconnect(self, conn: _Conn) -> None:
        with self._conn_lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def broadcast(self, ftype: FrameType, doc: dict,
                  arrays: dict[str, np.ndarray] | None = None,
                  min_proto: int = 0, legacy=None) -> int:
        """Push a frame (request_id 0 = unsolicited) to all live
        connections — the informer watch-event fan-out. Never blocks:
        frames go through each connection's bounded queue.

        Mixed-version fan-out: when ``min_proto`` > 0, only peers that
        negotiated at least that message protocol get the primary
        payload; older peers (including never-HELLO'd ones at proto 0)
        get the ``legacy`` payload instead — a zero-arg callable
        returning ``(doc, arrays)``, encoded LAZILY so an all-v2 fleet
        never pays the v1 encode.  ``legacy=None`` with ``min_proto``
        set skips old peers entirely (their resync machinery recovers)."""
        frame: Optional[Frame] = None
        legacy_frame: Optional[Frame] = None
        with self._conn_lock:
            conns = list(self._conns)
        sent = 0
        for conn in conns:
            if not conn.alive:
                continue
            if min_proto and conn.proto < min_proto:
                if legacy is None:
                    continue
                if legacy_frame is None:
                    ldoc, larrays = legacy()
                    legacy_frame = Frame(
                        ftype, 0, encode_payload(ldoc, larrays))
                conn.send(legacy_frame)
            else:
                if frame is None:
                    frame = Frame(ftype, 0, encode_payload(doc, arrays))
                conn.send(frame)
            sent += 1
        return sent


class RpcClient:
    """Blocking request/response client. Unsolicited (request_id 0) frames
    are delivered to ``on_push`` — the watch stream."""

    def __init__(self, path: str, on_push=None, timeout: float = 10.0,
                 faults=None, fault_domain: str = ""):
        self.path = path
        self.on_push = on_push
        self.timeout = timeout
        self.faults = faults
        #: correlated-fault domain tag (e.g. "rack:r1") — a storm over
        #: the domain refuses this client's connects, severs or blocks
        #: its calls (faults.FaultInjector storm modes); empty = the
        #: connection sits outside the modeled topology
        self.fault_domain = fault_domain
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._pending: dict[int, "_Waiter"] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 1
        self._reader: Optional[threading.Thread] = None
        self.connected = False
        self.push_errors = 0

    def connect(self) -> None:
        if self.faults is not None:
            if self.fault_domain:
                self.faults.on_connect(self.fault_domain)
            else:
                self.faults.on_connect()
        kind, target = _parse_addr(self.path)
        if kind == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(target)
            sock.settimeout(None)
        else:
            # bound by the client timeout: a black-holed TCP target must
            # fail in self.timeout, not the OS connect default (~2 min)
            sock = socket.create_connection(target, timeout=self.timeout)
            sock.settimeout(None)   # reader thread blocks indefinitely
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self.connected = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        if self.faults is not None and self.fault_domain:
            register = getattr(self.faults, "register_conn", None)
            if register is not None:
                register(self.fault_domain, self._sever_for_fault)

    def _sever_for_fault(self) -> None:
        """Storm sever: shut the socket down so the reader sees EOF and
        in-flight calls fail fast; the fd itself is released by the
        owner's close() (reconnect machinery)."""
        self.connected = False
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self) -> None:
        if self.faults is not None and self.fault_domain:
            unregister = getattr(self.faults, "unregister_conn", None)
            if unregister is not None:
                unregister(self.fault_domain, self._sever_for_fault)
        self.connected = False
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self._sock.close()
            self._sock = None
        # join the reader (bounded) so long soaks with repeated
        # reconnects don't accumulate daemon threads; skip when close()
        # runs ON the reader (a push handler tearing the stream down)
        reader = self._reader
        if reader is not None and reader is not threading.current_thread():
            reader.join(timeout=2.0)
            if not reader.is_alive():
                self._reader = None

    def _read_loop(self) -> None:
        sock = self._sock
        assert sock is not None
        recv = _recv_exact(sock, faults=self.faults)
        try:
            while True:
                frame = read_frame(recv)
                if frame.request_id == 0:
                    if self.on_push is not None:
                        try:
                            self.on_push(frame)
                        except Exception:
                            # a bad push must not kill the stream: later
                            # frames still correlate calls and pushes
                            self.push_errors += 1
                    continue
                with self._pending_lock:
                    waiter = self._pending.pop(frame.request_id, None)
                if waiter is not None:
                    waiter.frame = frame
                    waiter.event.set()
        except (ConnectionError, OSError):
            pass
        finally:
            self.connected = False
            with self._pending_lock:
                waiters = list(self._pending.values())
                self._pending.clear()
            for w in waiters:
                w.event.set()  # fail fast with frame=None

    def call(self, ftype: FrameType, doc: dict,
             arrays: dict[str, np.ndarray] | None = None,
             deadline_ms: float | None = None,
             ) -> tuple[FrameType, dict, dict[str, np.ndarray]]:
        sock = self._sock
        if sock is None:
            raise RpcError("not connected")
        if not self.connected:
            # the reader thread died (peer EOF / transport error): fail
            # fast instead of sending into a half-closed socket and
            # burning the full timeout waiting for a response that can
            # never correlate
            raise RpcError("not connected (stream closed)")
        if self.faults is not None and self.fault_domain:
            action = self.faults.outbound_domain(self.fault_domain)
            if action == "block":
                # asym_send storm: the call fails but the stream stays —
                # inbound pushes keep arriving (asymmetric partition)
                raise RpcError(
                    f"fault injection: domain {self.fault_domain!r} "
                    f"outbound blocked")
            if action == "sever":
                self._sever_for_fault()
                raise RpcError(
                    f"connection lost: domain {self.fault_domain!r} "
                    f"partitioned")
        if deadline_ms is not None:
            # per-call deadline rides the frame doc so the server can
            # shed the request once nobody is waiting for it
            doc = dict(doc, deadline_ms=float(deadline_ms))
        # active trace context rides the doc the same way (copy-on-write
        # no-op when nothing is traced)
        doc = tracing.inject(doc)
        waiter = _Waiter()
        with self._pending_lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = waiter
        frame = Frame(ftype, req_id, encode_payload(doc, arrays))
        try:
            with self._send_lock:
                data = frame.encode()
                cut = (self.faults.outbound_cut(len(data))
                       if self.faults is not None else None)
                if cut is not None:
                    # injected mid-write truncation: the peer's framing
                    # is desynced — sever so both sides fail loud
                    sock.sendall(data[:cut])
                    raise OSError("fault injection: truncated write")
                sock.sendall(data)
        except OSError as e:
            self.connected = False
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcError(f"connection lost: {e}") from e
        wait = self.timeout
        if deadline_ms is not None:
            wait = min(wait, float(deadline_ms) / 1000.0)
        if not waiter.event.wait(wait):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            if deadline_ms is not None and wait < self.timeout:
                # the CALLER'S budget ran out, not the transport: the
                # connection is healthy and the server may still answer
                # (the stale response is dropped by the waiter map).
                # RpcDeadlineError subclasses RpcRemoteError so shared-
                # connection owners (ReconnectingSidecarClient) pass it
                # through instead of tearing the client down and killing
                # other threads' in-flight calls.
                raise RpcDeadlineError(
                    f"deadline ({deadline_ms:g}ms) expired awaiting "
                    f"response")
            raise RpcError("rpc timeout")
        if waiter.frame is None:
            raise RpcError("connection lost")
        rdoc, rarrays = decode_payload(waiter.frame.payload)
        if waiter.frame.type is FrameType.ERROR:
            cls = (RpcDeadlineError if rdoc.get("expired")
                   else RpcRemoteError)
            raise cls(rdoc.get("message", "remote error"), doc=rdoc)
        return waiter.frame.type, rdoc, rarrays


class _Waiter:
    def __init__(self):
        self.event = threading.Event()
        self.frame: Optional[Frame] = None
