"""Cluster state as fixed-capacity padded tensors.

Design notes (SURVEY.md section 7 "hard parts"):

- **Fixed capacity + masks.** Nodes and pods come and go; XLA wants static
  shapes. State tensors are allocated at a capacity (a power-of-two bucket) and
  carry validity masks. Growing past capacity re-allocates at the next bucket —
  a recompile, amortized to O(log N) recompiles over cluster life.
- **Delta scatter updates.** The host keeps an index map (name -> row); informer
  deltas become ``tensor.at[rows].set(values)`` scatters of only changed rows,
  not full-state uploads. This is the double-buffer-friendly update path that
  keeps host->device traffic proportional to churn.
- **Integer exactness.** Resource math is int32 in canonical units
  (see api/resources.py) to match the reference's int64 milli-unit math.

Reference-parity mapping:
  node_allocatable  <- Node.status.allocatable (scheduler NodeInfo snapshot)
  node_requested    <- sum of scheduled pods' requests (NodeInfo.Requested)
  node_usage        <- NodeMetric.status.nodeMetric.nodeUsage (slo/v1alpha1, nodemetric_types.go:131)
  node_agg_usage    <- NodeMetric AggregatedUsage percentile (nodemetric_types.go:50)
  node_prod_usage   <- prod-pool usage (loadaware prod-usage mode)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from koordinator_tpu.api.resources import NUM_RESOURCE_DIMS


#: Per-dimension quantity bound: integer score/percentage math multiplies by
#: 100, so quantities must stay below 2^31/100 to avoid int32 overflow
#: (api/resources.py documents the unit scaling that keeps real nodes within
#: this: 21.4M mcores / 21.4M MiB ~ 20 TiB memory per node).
MAX_QUANTITY = (2**31 - 1) // 100


def _check_bounds(a: np.ndarray | None, what: str) -> None:
    if a is not None and np.asarray(a).size and np.asarray(a).max() > MAX_QUANTITY:
        raise ValueError(
            f"{what} exceeds MAX_QUANTITY={MAX_QUANTITY}; rescale units "
            "(see api/resources.py)"
        )


def _bucket(n: int, minimum: int = 64) -> int:
    """Smallest power-of-two capacity >= n (recompile bucketing)."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


#: donating row scatter: XLA aliases the output to the input buffer, so a
#: flush-sized (N, R) tensor is updated in place instead of reallocated
_row_set_donating = jax.jit(
    lambda cur, rows, value: cur.at[rows].set(value), donate_argnums=(0,))


@struct.dataclass
class ClusterState:
    """Per-node tensors, shape (N, R) / (N,). N is the padded node capacity."""

    node_allocatable: jax.Array  # (N, R) int32
    node_requested: jax.Array    # (N, R) int32 — requests of pods bound to the node
    node_usage: jax.Array        # (N, R) int32 — latest real usage (NodeMetric)
    node_agg_usage: jax.Array    # (N, R) int32 — aggregated percentile usage (e.g. p95)
    node_prod_usage: jax.Array   # (N, R) int32 — usage by prod-band pods only
    node_valid: jax.Array        # (N,)  bool
    #: (N,) int32 label/taint equivalence-class id per node: nodes with the
    #: same scheduling-relevant labels+taints share a class, so pod
    #: feasibility factors into a (P, C) selector mask + this map instead of
    #: a dense (P, N) tensor (C ≪ N; the reference walks nodeSelector/taints
    #: per (pod, node) — the class map is the vectorized equivalent).
    node_class: jax.Array

    @property
    def capacity(self) -> int:
        return self.node_allocatable.shape[0]

    @property
    def free(self) -> jax.Array:
        """(N, R) request-free capacity; 0 for invalid nodes."""
        free = self.node_allocatable - self.node_requested
        return jnp.where(self.node_valid[:, None], free, 0)

    @classmethod
    def zeros(cls, capacity: int, dims: int = NUM_RESOURCE_DIMS) -> "ClusterState":
        # one DISTINCT buffer per field: the donating flush consumes
        # fields independently, so aliased zeros would die together
        def z():
            return jnp.zeros((capacity, dims), dtype=jnp.int32)

        return cls(
            node_allocatable=z(),
            node_requested=z(),
            node_usage=z(),
            node_agg_usage=z(),
            node_prod_usage=z(),
            node_valid=jnp.zeros((capacity,), dtype=bool),
            node_class=jnp.zeros((capacity,), dtype=jnp.int32),
        )

    @classmethod
    def from_arrays(
        cls,
        allocatable: np.ndarray,
        requested: np.ndarray | None = None,
        usage: np.ndarray | None = None,
        agg_usage: np.ndarray | None = None,
        prod_usage: np.ndarray | None = None,
        capacity: int | None = None,
        node_class: np.ndarray | None = None,
    ) -> "ClusterState":
        """Build padded device state from (n, R) host arrays of n real nodes."""
        n, dims = allocatable.shape
        cap = capacity if capacity is not None else _bucket(n)
        _check_bounds(allocatable, "node allocatable")

        def pad(a):
            out = np.zeros((cap, dims), dtype=np.int32)
            if a is not None:
                out[:n] = a
            return jnp.asarray(out)

        valid = np.zeros(cap, dtype=bool)
        valid[:n] = True
        nclass = np.zeros(cap, dtype=np.int32)
        if node_class is not None:
            nclass[:n] = node_class
        return cls(
            node_allocatable=pad(allocatable),
            node_requested=pad(requested),
            node_usage=pad(usage),
            node_agg_usage=pad(agg_usage if agg_usage is not None else usage),
            node_prod_usage=pad(prod_usage if prod_usage is not None else usage),
            node_valid=jnp.asarray(valid),
            node_class=jnp.asarray(nclass),
        )

    def scatter_update(self, rows: jax.Array, donate: bool = False,
                       **updates: jax.Array) -> "ClusterState":
        """Apply a delta: replace the given rows of the named tensors.

        ``rows`` is (K,) int32; each update value is (K, R) (or (K,) for masks).
        Only the changed rows travel host->device.

        ``donate=True`` routes each row-set through a donating jit so the
        (N, R) tensor is updated in place instead of reallocated — for
        callers that OWN the state exclusively (the snapshot's flush):
        the pre-update buffers are dead after the call and any stale
        reference to them errors loudly.
        """
        new = {}
        setter = _row_set_donating if donate else (
            lambda cur, r, v: cur.at[r].set(v))
        for name, value in updates.items():
            cur = getattr(self, name)
            new[name] = setter(cur, rows, value)
        return self.replace(**new)

    def gather_rows(self, rows: jax.Array,
                    row_valid: jax.Array | None = None) -> "ClusterState":
        """Sub-state of the given node rows (shape (K, R) / (K,)): the
        dirty-column view the incremental candidate refresh scores
        against.  ``row_valid`` additionally masks padded entries of a
        bucketed ``rows`` vector so they score as invalid nodes."""
        valid = self.node_valid[rows]
        if row_valid is not None:
            valid = valid & row_valid
        return ClusterState(
            node_allocatable=self.node_allocatable[rows],
            node_requested=self.node_requested[rows],
            node_usage=self.node_usage[rows],
            node_agg_usage=self.node_agg_usage[rows],
            node_prod_usage=self.node_prod_usage[rows],
            node_valid=valid,
            node_class=self.node_class[rows],
        )

    def add_pod(self, node_idx: jax.Array, request: jax.Array) -> "ClusterState":
        """Account a pod's request onto a node (Reserve semantics)."""
        return self.replace(
            node_requested=self.node_requested.at[node_idx].add(request)
        )

    def remove_pod(self, node_idx: jax.Array, request: jax.Array) -> "ClusterState":
        """Unreserve (scheduling failure / pod deletion)."""
        return self.replace(
            node_requested=self.node_requested.at[node_idx].add(-request)
        )


@struct.dataclass
class PodBatch:
    """A batch of pending pods, shape (P, R) / (P,). P is padded pod capacity.

    Placement constraints (nodeSelector / affinity / taints+tolerations) come
    in one of two representations:

    - **factored** (the default, the scale path): ``selector_mask`` is a
      (P, C) bool over node equivalence classes and the node→class map lives
      in ``ClusterState.node_class``; feasibility expands lazily on device as
      ``selector_mask[:, node_class]``, so host work and transfer are
      O(P·C + N), never O(P·N).
    - **dense**: an explicit host-computed (P, N) ``feasible`` mask for
      callers that need per-(pod, node) edits (scheduling hints, topology
      pinning, tests).

    Exactly one of the two is set; use :meth:`feasible_rows` /
    :meth:`feasible_row` instead of touching either field.
    """

    requests: jax.Array    # (P, R) int32
    priority: jax.Array    # (P,) int32 — koordinator priority value
    qos: jax.Array         # (P,) int8  — QoSClass codes
    gang_id: jax.Array     # (P,) int32 — gang index, -1 = not in a gang
    quota_id: jax.Array    # (P,) int32 — elastic-quota index, -1 = none
    non_preemptible: jax.Array  # (P,) bool — checks/consumes quota min
    valid: jax.Array       # (P,) bool
    #: (P,) int32 tie-break rotation identity: the candidate ranking's
    #: per-pod rotation (ops/batch_assign._ranked_scores) derives from
    #: this, NOT from the pod's batch row, so a pod keeps its candidate
    #: set when the queue around it churns (the incremental candidate
    #: cache depends on that stability).  Defaults to the batch row
    #: index; the scheduler assigns a stable id per pod name.
    rot_id: jax.Array
    feasible: jax.Array | None       # (P, N) bool dense mask, or None
    selector_mask: jax.Array | None  # (P, C) bool class mask, or None

    @property
    def capacity(self) -> int:
        return self.requests.shape[0]

    def feasible_rows(self, state: "ClusterState") -> jax.Array:
        """(P, N) feasibility, expanding the factored form on device.

        A node whose class id is outside this batch's selector-mask width
        (a class registered after the batch was built) is INFEASIBLE for
        every pod — failing safe (the pod retries next round against a
        rebuilt batch) rather than silently inheriting another class's mask.
        """
        if self.feasible is not None:
            return self.feasible
        c = self.selector_mask.shape[1]
        in_range = state.node_class < c
        nc = jnp.minimum(state.node_class, c - 1)
        return self.selector_mask[:, nc] & in_range[None, :]

    def feasible_row(self, state: "ClusterState", idx) -> jax.Array:
        """(N,) feasibility for one pod (cheap in the factored form)."""
        if self.feasible is not None:
            return self.feasible[idx]
        c = self.selector_mask.shape[1]
        in_range = state.node_class < c
        nc = jnp.minimum(state.node_class, c - 1)
        return self.selector_mask[idx][nc] & in_range

    def compact(
        self, keep: np.ndarray, min_capacity: int = 32
    ) -> tuple["PodBatch", np.ndarray]:
        """(small_batch, kept_indices): gather the ``keep`` rows into a new
        batch padded to a power-of-two capacity (power-of-two bucketing keeps
        the jit cache bounded).  Padded rows are invalid.

        The scale rationale: a follow-up solve over a handful of leftover
        pods (the scheduler's exact rescue pass) must not pay the full
        O(capacity) scan of the original 50k-row batch.
        """
        idx = np.flatnonzero(np.asarray(keep))
        cap = max(min_capacity, 1 << (max(len(idx), 1) - 1).bit_length())
        pad = np.zeros(cap, np.int32)
        pad[: len(idx)] = idx
        gidx = jnp.asarray(pad)
        valid_pad = np.zeros(cap, bool)
        valid_pad[: len(idx)] = True

        # every PodBatch field is per-pod along axis 0, so gather the whole
        # pytree (None constraint fields drop out of the map)
        small = jax.tree.map(lambda a: jnp.take(a, gidx, axis=0), self)
        return small.replace(valid=small.valid & jnp.asarray(valid_pad)), idx

    @classmethod
    def build(
        cls,
        requests: np.ndarray,
        priority: np.ndarray | None = None,
        qos: np.ndarray | None = None,
        gang_id: np.ndarray | None = None,
        quota_id: np.ndarray | None = None,
        non_preemptible: np.ndarray | None = None,
        feasible: np.ndarray | None = None,
        selector_mask: np.ndarray | None = None,
        node_capacity: int = 64,
        class_capacity: int = 1,
        capacity: int | None = None,
        rot_id: np.ndarray | None = None,
    ) -> "PodBatch":
        p, dims = requests.shape
        cap = capacity if capacity is not None else _bucket(p)
        _check_bounds(requests, "pod requests")

        req = np.zeros((cap, dims), dtype=np.int32)
        req[:p] = requests

        def pad1(a, fill, dtype):
            out = np.full(cap, fill, dtype=dtype)
            if a is not None:
                out[:p] = a
            return jnp.asarray(out)

        if feasible is not None:
            feas = np.zeros((cap, node_capacity), dtype=bool)
            feas[:p, : feasible.shape[1]] = feasible
            feas_arr, sel_arr = jnp.asarray(feas), None
        else:
            c_cap = class_capacity
            sel = np.zeros((cap, c_cap), dtype=bool)
            if selector_mask is not None:
                sel[:p, : selector_mask.shape[1]] = selector_mask
            else:
                sel[:p] = True  # unconstrained pods allow every class
            feas_arr, sel_arr = None, jnp.asarray(sel)

        valid = np.zeros(cap, dtype=bool)
        valid[:p] = True

        # rotation identity defaults to the batch row (the pre-cache
        # behavior); padded rows keep their row index (inert: invalid)
        rot = np.arange(cap, dtype=np.int32)
        if rot_id is not None:
            rot[:p] = rot_id

        return cls(
            requests=jnp.asarray(req),
            priority=pad1(priority, 0, np.int32),
            qos=pad1(qos, 0, np.int8),
            gang_id=pad1(gang_id, -1, np.int32),
            quota_id=pad1(quota_id, -1, np.int32),
            non_preemptible=pad1(non_preemptible, False, bool),
            valid=jnp.asarray(valid),
            rot_id=jnp.asarray(rot),
            feasible=feas_arr,
            selector_mask=sel_arr,
        )
