"""Device-resident cluster state: the tensors every solve reads.

Replaces the reference's per-node Go object caches (scheduler NodeInfo snapshot,
loadaware pod-assign cache, deviceshare nodeDevice cache) with fixed-capacity
padded tensors that live on the TPU and are updated by delta scatter.
"""

from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

__all__ = ["ClusterState", "PodBatch"]
