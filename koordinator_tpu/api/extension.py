"""The annotation/label protocol (reference: ``apis/extension/`` 3.4k LoC —
this IS the wire format between all components).

Accessors parse/render the ``koordinator.sh/*`` labels and annotations carried
on pods and nodes. JSON payload schemas follow the reference field names so a
reference-cluster pod annotation round-trips.
"""

from __future__ import annotations

import json
from typing import Mapping, Optional

from koordinator_tpu.api.priority import PriorityClass
from koordinator_tpu.api.qos import QoSClass

DOMAIN = "koordinator.sh"
SCHEDULING_DOMAIN = "scheduling.koordinator.sh"
NODE_DOMAIN = "node.koordinator.sh"

# Labels (apis/extension/constants.go)
LABEL_POD_QOS = f"{DOMAIN}/qosClass"
LABEL_POD_PRIORITY = f"{DOMAIN}/priority"
LABEL_POD_PRIORITY_CLASS = f"{DOMAIN}/priority-class"
LABEL_POD_MUTATING_UPDATE = f"{DOMAIN}/mutating-update"

# Gang / coscheduling (apis/extension/coscheduling.go)
#: quota.scheduling.koordinator.sh/name — the pod's elastic quota
#: (apis/extension/elastic_quota.go:38)
LABEL_QUOTA_NAME = "quota.scheduling.koordinator.sh/name"

LABEL_GANG_NAME = "pod-group.scheduling.sigs.k8s.io/name"
LABEL_GANG_MIN_NUM = "pod-group.scheduling.sigs.k8s.io/min-available"
ANNOTATION_GANG_GROUPS = f"{SCHEDULING_DOMAIN}/gang-groups"

# Fine-grained CPU (apis/extension/numa_aware.go:34-37)
ANNOTATION_RESOURCE_SPEC = f"{SCHEDULING_DOMAIN}/resource-spec"
ANNOTATION_RESOURCE_STATUS = f"{SCHEDULING_DOMAIN}/resource-status"

# Device allocation (apis/extension/device_share.go:32)
ANNOTATION_DEVICE_ALLOCATED = f"{SCHEDULING_DOMAIN}/device-allocated"

# Reservation (apis/extension/reservation.go)
ANNOTATION_RESERVATION_ALLOCATED = f"{SCHEDULING_DOMAIN}/reservation-allocated"
ANNOTATION_RESERVATION_AFFINITY = f"{SCHEDULING_DOMAIN}/reservation-affinity"
LABEL_RESERVATION_IGNORED = f"{SCHEDULING_DOMAIN}/reservation-ignored"

# Node-level (apis/extension/node_resource_amplification.go, cpu_normalization.go)
ANNOTATION_NODE_AMPLIFICATION = f"{NODE_DOMAIN}/resource-amplification-ratio"
#: kubelet-reported allocatable saved by the node mutating webhook before
#: amplification overwrites it (AnnotationNodeRawAllocatable)
ANNOTATION_NODE_RAW_ALLOCATABLE = f"{NODE_DOMAIN}/raw-allocatable"
ANNOTATION_CPU_NORMALIZATION = f"{NODE_DOMAIN}/cpu-normalization-ratio"
ANNOTATION_NODE_RESERVATION = f"{NODE_DOMAIN}/reservation"
LABEL_CPU_BIND_POLICY = f"{NODE_DOMAIN}/cpu-bind-policy"

# Schedule explanation (apis/extension/schedule_explanation.go)
ANNOTATION_SCHEDULE_EXPLANATION = f"{SCHEDULING_DOMAIN}/schedule-explanation"

# Eviction / descheduling
LABEL_SOFT_EVICTION = f"{SCHEDULING_DOMAIN}/soft-eviction"
ANNOTATION_EVICTION_COST = f"{DOMAIN}/eviction-cost"
#: per-pod resctrl request: JSON {"l3": percent, "mb": percent}
#: (apis/extension AnnotationResctrl)
ANNOTATION_RESCTRL = f"{NODE_DOMAIN}/resctrl"
#: per-pod network QoS: JSON {"ingressBps": n, "egressBps": n}
#: (apis/extension/constants.go:48 AnnotationNetworkQOS)
ANNOTATION_NETWORK_QOS = f"{DOMAIN}/networkQOS"

# Extended resource names (apis/extension/resource.go:27-30)
RESOURCE_BATCH_CPU = "kubernetes.io/batch-cpu"
RESOURCE_BATCH_MEMORY = "kubernetes.io/batch-memory"
RESOURCE_MID_CPU = "kubernetes.io/mid-cpu"
RESOURCE_MID_MEMORY = "kubernetes.io/mid-memory"
RESOURCE_GPU = "kubernetes.io/gpu"
RESOURCE_GPU_CORE = "kubernetes.io/gpu-core"
RESOURCE_GPU_MEMORY = "kubernetes.io/gpu-memory"
RESOURCE_GPU_MEMORY_RATIO = "kubernetes.io/gpu-memory-ratio"
RESOURCE_RDMA = "koordinator.sh/rdma"


def get_pod_qos(labels: Mapping[str, str]) -> QoSClass:
    return QoSClass.parse(labels.get(LABEL_POD_QOS, ""))


def set_pod_qos(labels: dict, qos: QoSClass) -> dict:
    labels[LABEL_POD_QOS] = qos.name
    return labels


def get_pod_priority_class(priority: Optional[int]) -> PriorityClass:
    from koordinator_tpu.api.priority import priority_class_of

    return priority_class_of(priority or 0)


# ---- JSON annotation payloads ----------------------------------------------


def get_resource_spec(annotations: Mapping[str, str]) -> dict:
    """CPU orchestration request: {preferredCPUBindPolicy, preferredCPUExclusivePolicy,
    requiredCPUBindPolicy, numaAllocateStrategy}."""
    raw = annotations.get(ANNOTATION_RESOURCE_SPEC, "")
    if not raw:
        return {}
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return {}


def set_resource_status(annotations: dict, cpuset: str,
                        numa_node_resources: list[dict] | None = None) -> dict:
    """Scheduler -> agent cpuset result (the resource-status annotation the
    cpuset runtime hook consumes)."""
    annotations[ANNOTATION_RESOURCE_STATUS] = json.dumps(
        {"cpuset": cpuset, "numaNodeResources": numa_node_resources or []},
        sort_keys=True,
    )
    return annotations


def get_resource_status(annotations: Mapping[str, str]) -> dict:
    raw = annotations.get(ANNOTATION_RESOURCE_STATUS, "")
    if not raw:
        return {}
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return {}


def set_device_allocations(annotations: dict, allocations: dict) -> dict:
    """{"gpu": [{"minor": 0, "resources": {...}}], "rdma": [...]}"""
    annotations[ANNOTATION_DEVICE_ALLOCATED] = json.dumps(allocations, sort_keys=True)
    return annotations


def get_device_allocations(annotations: Mapping[str, str]) -> dict:
    raw = annotations.get(ANNOTATION_DEVICE_ALLOCATED, "")
    if not raw:
        return {}
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return {}


def set_reservation_allocated(annotations: dict, name: str, uid: str) -> dict:
    annotations[ANNOTATION_RESERVATION_ALLOCATED] = json.dumps(
        {"name": name, "uid": uid}, sort_keys=True
    )
    return annotations


def get_reservation_allocated(annotations: Mapping[str, str]) -> Optional[dict]:
    raw = annotations.get(ANNOTATION_RESERVATION_ALLOCATED, "")
    if not raw:
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return None


def get_node_amplification_ratios(annotations: Mapping[str, str]) -> dict[str, int]:
    """resource -> ratio percent (>=100). Encoded as {"cpu": 1.5} floats in the
    reference; normalized here to integer percents."""
    raw = annotations.get(ANNOTATION_NODE_AMPLIFICATION, "")
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
        return {k: int(round(float(v) * 100)) for k, v in parsed.items()}
    except (json.JSONDecodeError, TypeError, ValueError):
        return {}


def get_cpu_normalization_ratio_pct(annotations: Mapping[str, str]) -> int:
    raw = annotations.get(ANNOTATION_CPU_NORMALIZATION, "")
    try:
        return int(round(float(raw) * 100)) if raw else 100
    except ValueError:
        return 100


def get_node_reservation(annotations: Mapping[str, str]) -> dict[str, int]:
    """Node-level reserved resources ({"resources": {"cpu": "2"}} form);
    values normalized to milli-cpu / bytes by the caller's convention."""
    raw = annotations.get(ANNOTATION_NODE_RESERVATION, "")
    if not raw:
        return {}
    try:
        parsed = json.loads(raw)
    except json.JSONDecodeError:
        return {}
    if not isinstance(parsed, dict):
        return {}
    resources = parsed.get("resources", {})
    return resources if isinstance(resources, dict) else {}
