"""QoS classes.

Reference: ``apis/extension/qos.go:19-28`` defines five QoS classes
(LSE/LSR/LS/BE/SYSTEM) carried on pods via the ``koordinator.sh/qosClass`` label
(``apis/extension/constants.go:33``).

Here each class is an integer code so a batch of pods carries a ``(P,)`` int8
tensor of QoS classes, and QoS-conditional math (e.g. the load-aware estimator's
QoS-dependent scaling factors) is a gather over a small per-class constant table
instead of branching.
"""

from __future__ import annotations

import enum


class QoSClass(enum.IntEnum):
    """Pod QoS class, ordered roughly by "sensitivity" (higher = more sensitive).

    Integer codes are stable protocol values used inside tensors; do not reorder.
    """

    NONE = 0
    BE = 1      # best-effort batch: may be suppressed/evicted
    LS = 2      # latency-sensitive, shares cores
    LSR = 3     # latency-sensitive reserved: exclusive cpuset
    LSE = 4     # latency-sensitive exclusive: exclusive cpuset, no BE sharing
    SYSTEM = 5  # node system agents

    @classmethod
    def parse(cls, s: str) -> "QoSClass":
        """Parse the label value form ("LS", "BE", ...); empty/unknown -> NONE."""
        try:
            return cls[s.upper()] if s else cls.NONE
        except KeyError:
            return cls.NONE

    @property
    def is_latency_sensitive(self) -> bool:
        return self in (QoSClass.LS, QoSClass.LSR, QoSClass.LSE)

    @property
    def is_best_effort(self) -> bool:
        return self is QoSClass.BE


NUM_QOS_CLASSES = len(QoSClass)
