"""The CRD object model (reference: ``apis/`` — 13 CRDs, SURVEY.md §2.2).

These dataclasses are the host-side protocol objects the components exchange
(the reference exchanges them through the kube-apiserver; here they cross the
Go/Python bridge or in-process queues). Tensor-side equivalents live in
``state/`` and the per-subsystem kernels — these types are the boundary
encoding, so they stay plain frozen dataclasses with explicit defaults.

Parity map:
- NodeMetric         <- apis/slo/v1alpha1/nodemetric_types.go:131
- NodeSLO strategies <- apis/slo/v1alpha1/nodeslo_types.go:29-451
- Device             <- apis/scheduling/v1alpha1/device_types.go:112
- Reservation        <- apis/scheduling/v1alpha1/reservation_types.go:250
- PodMigrationJob    <- apis/scheduling/v1alpha1/pod_migration_job_types.go:214
- ClusterNetworkTopology <- cluster_network_topology_types.go:75
- PodGroup / ElasticQuota <- apis/thirdparty/.../types.go:32,123
- ClusterColocationProfile <- apis/configuration/
- Recommendation     <- apis/analysis/v1alpha1/recommendation_types.go:96
- ScheduleExplanation <- scheduling.koordinator.sh_scheduleexplanations.yaml
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Tuple

# ---------------------------------------------------------------------------
# slo.koordinator.sh: NodeMetric
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResourceUsage:
    cpu_milli: int = 0
    memory_bytes: int = 0
    extras: Mapping[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class AggregatedUsage:
    """Percentile-aggregated node usage (AggregatedUsage, nodemetric_types.go:50)."""

    cpu_milli_p: Mapping[float, int] = dataclasses.field(default_factory=dict)
    memory_bytes_p: Mapping[float, int] = dataclasses.field(default_factory=dict)
    duration_seconds: float = 0.0


@dataclasses.dataclass(frozen=True)
class PodMetricInfo:
    namespace: str
    name: str
    uid: str
    usage: ResourceUsage = ResourceUsage()
    priority: int = 0
    qos_class: str = "NONE"


@dataclasses.dataclass(frozen=True)
class NodeMetricStatus:
    update_time: float = 0.0
    node_usage: ResourceUsage = ResourceUsage()
    system_usage: ResourceUsage = ResourceUsage()
    aggregated_node_usage: Optional[AggregatedUsage] = None
    pods_metrics: Tuple[PodMetricInfo, ...] = ()
    #: collectors went silent past the expiration budget — consumers must
    #: treat usage as unknown (nodemetric "expired" condition)
    degraded: bool = False


@dataclasses.dataclass(frozen=True)
class NodeMetricSpec:
    """Collect policy pushed by the manager (NodeMetricCollectPolicy)."""

    aggregate_duration_seconds: int = 300
    report_interval_seconds: int = 60
    node_memory_collect_policy: str = "usageWithoutPageCache"


@dataclasses.dataclass(frozen=True)
class NodeMetric:
    name: str
    spec: NodeMetricSpec = NodeMetricSpec()
    status: NodeMetricStatus = NodeMetricStatus()


# ---------------------------------------------------------------------------
# slo.koordinator.sh: NodeSLO (the per-node QoS strategy bundle)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResourceThresholdStrategy:
    """Suppression/eviction thresholds (ResourceThresholdStrategy)."""

    enable: bool = False
    cpu_suppress_threshold_percent: int = 65
    cpu_suppress_policy: str = "cpuset"       # cpuset | cfsQuota
    cpu_evict_be_usage_threshold_percent: int = 90
    cpu_evict_be_satisfaction_lower_percent: int = 0
    cpu_evict_be_satisfaction_upper_percent: int = 0
    cpu_evict_time_window_seconds: int = 60
    memory_evict_threshold_percent: int = 70
    memory_evict_lower_percent: int = 0       # 0 => threshold - 2
    # allocatable-eviction thresholds (cpu_evict.go:356): requested batch
    # resource over batch ALLOCATABLE (the colocation model's overcommit),
    # not physical usage; <0 disables
    cpu_evict_by_allocatable_threshold_percent: int = -1
    cpu_evict_by_allocatable_lower_percent: int = -1
    memory_evict_by_allocatable_threshold_percent: int = -1
    memory_evict_by_allocatable_lower_percent: int = -1


@dataclasses.dataclass(frozen=True)
class CPUQoS:
    group_identity: int = 0                   # bvt_warp_ns: -1 BE, 0 none, 2 LS
    core_sched: bool = False
    sched_idle: int = 0                       # cpu.idle for BE on v2


@dataclasses.dataclass(frozen=True)
class MemoryQoS:
    enable: bool = False
    min_limit_percent: int = 0                # memory.min = request * pct
    low_limit_percent: int = 0                # memory.low
    throttling_percent: int = 0               # memory.high = limit * pct
    wmark_ratio: int = 95
    wmark_scale_permill: int = 20
    wmark_min_adj: int = 0
    priority: int = 0
    priority_enable: int = 0
    oom_kill_group: int = 0


@dataclasses.dataclass(frozen=True)
class ResctrlQoS:
    cat_range_start_percent: int = 0
    cat_range_end_percent: int = 100
    mba_percent: int = 100


@dataclasses.dataclass(frozen=True)
class BlkIOQoS:
    enable: bool = False
    weight: int = 100
    read_bps: int = 0                         # 0 = unlimited
    write_bps: int = 0
    read_iops: int = 0
    write_iops: int = 0


@dataclasses.dataclass(frozen=True)
class NetworkQoS:
    enable: bool = False
    ingress_request_mbps: int = 0
    ingress_limit_mbps: int = 0
    egress_request_mbps: int = 0
    egress_limit_mbps: int = 0


@dataclasses.dataclass(frozen=True)
class QoSStrategy:
    """Per-QoS-class knobs (ResourceQOSStrategy has lse/lsr/ls/be branches)."""

    cpu: CPUQoS = CPUQoS()
    memory: MemoryQoS = MemoryQoS()
    resctrl: ResctrlQoS = ResctrlQoS()
    blkio: BlkIOQoS = BlkIOQoS()
    network: NetworkQoS = NetworkQoS()


@dataclasses.dataclass(frozen=True)
class CPUBurstStrategy:
    policy: str = "none"                      # none | cpuBurstOnly | cfsQuotaBurstOnly | auto
    cpu_burst_percent: int = 1000             # burst buffer = limit * pct
    cfs_quota_burst_percent: int = 300
    cfs_quota_burst_period_seconds: int = -1  # -1 = forever
    share_pool_threshold_percent: int = 50    # node-level guard


@dataclasses.dataclass(frozen=True)
class SystemStrategy:
    min_free_kbytes_factor: int = 100
    watermark_scale_factor: int = 150
    memcg_reap_enabled: bool = False


@dataclasses.dataclass(frozen=True)
class NodeSLO:
    """The rendered per-node strategy (NodeSLOSpec)."""

    name: str = ""
    resource_used_threshold_with_be: ResourceThresholdStrategy = (
        ResourceThresholdStrategy()
    )
    resource_qos_ls: QoSStrategy = QoSStrategy(cpu=CPUQoS(group_identity=2))
    resource_qos_lsr: QoSStrategy = QoSStrategy(cpu=CPUQoS(group_identity=2))
    resource_qos_be: QoSStrategy = QoSStrategy(cpu=CPUQoS(group_identity=-1))
    cpu_burst_strategy: CPUBurstStrategy = CPUBurstStrategy()
    system_strategy: SystemStrategy = SystemStrategy()
    extensions: Mapping[str, object] = dataclasses.field(default_factory=dict)


# ---------------------------------------------------------------------------
# scheduling.koordinator.sh: Device
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    """One device unit (DeviceInfo, device_types.go)."""

    type: str                                  # gpu | rdma | xpu
    uuid: str = ""
    minor: int = 0
    health: bool = True
    resources: Mapping[str, int] = dataclasses.field(default_factory=dict)
    numa_node: int = -1
    pcie_id: str = ""
    busid: str = ""
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    vf_groups: Tuple[str, ...] = ()            # rdma virtual functions


@dataclasses.dataclass(frozen=True)
class Device:
    """Per-node device CR: topology + health of all accelerators."""

    node_name: str
    devices: Tuple[DeviceInfo, ...] = ()
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)


# ---------------------------------------------------------------------------
# scheduling.koordinator.sh: Reservation (protocol form)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReservationSpec:
    owners_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    requests: Mapping[str, int] = dataclasses.field(default_factory=dict)
    ttl_seconds: int = 0                       # 0 = never expire
    pre_allocation: bool = False
    allocate_once: bool = True
    allocate_policy: str = "Aligned"           # Default | Aligned | Restricted
    unschedulable: bool = False


@dataclasses.dataclass(frozen=True)
class ReservationStatus:
    phase: str = "Pending"                     # Pending|Available|Succeeded|Failed
    node_name: str = ""
    allocatable: Mapping[str, int] = dataclasses.field(default_factory=dict)
    allocated: Mapping[str, int] = dataclasses.field(default_factory=dict)
    current_owners: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Reservation:
    name: str
    uid: str = ""
    spec: ReservationSpec = ReservationSpec()
    status: ReservationStatus = ReservationStatus()


# ---------------------------------------------------------------------------
# scheduling.koordinator.sh: PodMigrationJob
# ---------------------------------------------------------------------------

MIGRATION_PHASE_PENDING = "Pending"
MIGRATION_PHASE_RUNNING = "Running"
MIGRATION_PHASE_SUCCEEDED = "Succeed"
MIGRATION_PHASE_FAILED = "Failed"


@dataclasses.dataclass(frozen=True)
class PodMigrationJobSpec:
    pod_uid: str = ""
    pod_namespace: str = ""
    pod_name: str = ""
    mode: str = "ReservationFirst"             # ReservationFirst | EvictDirectly
    ttl_seconds: int = 300
    delete_options: Mapping[str, object] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class PodMigrationJobStatus:
    phase: str = MIGRATION_PHASE_PENDING
    reason: str = ""
    message: str = ""
    reservation_name: str = ""
    node_name: str = ""


@dataclasses.dataclass(frozen=True)
class PodMigrationJob:
    name: str
    spec: PodMigrationJobSpec = PodMigrationJobSpec()
    status: PodMigrationJobStatus = PodMigrationJobStatus()
    creation_time: float = 0.0


# ---------------------------------------------------------------------------
# scheduling.koordinator.sh: ClusterNetworkTopology (protocol form)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class NetworkTopologyLayer:
    name: str                                  # e.g. "spine", "block"
    parent: str = ""


@dataclasses.dataclass(frozen=True)
class NetworkTopologyNodeInfo:
    node_name: str
    path: Tuple[str, ...] = ()                 # labels from root to leaf


@dataclasses.dataclass(frozen=True)
class ClusterNetworkTopology:
    layers: Tuple[NetworkTopologyLayer, ...] = ()
    nodes: Tuple[NetworkTopologyNodeInfo, ...] = ()


# ---------------------------------------------------------------------------
# scheduling.sigs.k8s.io (thirdparty): PodGroup + ElasticQuota
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PodGroup:
    name: str
    namespace: str = "default"
    min_member: int = 1
    schedule_timeout_seconds: int = 600
    mode: str = "Strict"                       # Strict | NonStrict
    gang_group: Tuple[str, ...] = ()           # cross-gang group ids


@dataclasses.dataclass(frozen=True)
class ElasticQuota:
    name: str
    namespace: str = "default"
    parent: str = "root"
    min: Mapping[str, int] = dataclasses.field(default_factory=dict)
    max: Mapping[str, int] = dataclasses.field(default_factory=dict)
    shared_weight: Mapping[str, int] = dataclasses.field(default_factory=dict)
    is_parent: bool = False
    allow_lent_resource: bool = True
    guarantee_usage: bool = False
    tree_id: str = ""
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class ElasticQuotaProfile:
    """quota.koordinator.sh ElasticQuotaProfile: generates a quota tree from a
    node selector (elastic_quota_profile_types.go:50)."""

    name: str
    quota_name: str = ""
    node_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    quota_labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    resource_ratio_percent: int = 100


# ---------------------------------------------------------------------------
# config.koordinator.sh: ClusterColocationProfile
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClusterColocationProfile:
    """Webhook templating: inject QoS/priority/scheduler into matching pods."""

    name: str
    namespace_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    pod_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    qos_class: str = ""                        # inject koordinator.sh/qosClass
    priority_class_name: str = ""
    koordinator_priority: Optional[int] = None
    scheduler_name: str = ""
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    patch_probability: float = 1.0             # canary percent


# ---------------------------------------------------------------------------
# analysis.koordinator.sh: Recommendation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Recommendation:
    """VPA-ish resource recommendation per workload (recommendation_types.go:96)."""

    name: str
    namespace: str = "default"
    workload_ref: str = ""                     # kind/name
    target_cpu_milli: int = 0
    target_memory_bytes: int = 0
    update_time: float = 0.0


# ---------------------------------------------------------------------------
# ScheduleExplanation (persisted diagnosis)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ScheduleExplanation:
    pod_uid: str
    pod_namespace: str = ""
    pod_name: str = ""
    reasons: Tuple[str, ...] = ()              # per-node or per-plugin failures
    node_offers: Mapping[str, str] = dataclasses.field(default_factory=dict)
    update_time: float = 0.0
