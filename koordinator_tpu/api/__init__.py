"""L1 protocol types: the QoS/priority/resource model shared by every component.

Mirrors the reference's ``apis/extension`` annotation protocol (SURVEY.md section 2.2)
as first-class enums and tensor-friendly integer codes instead of string labels.
"""

from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.api.priority import PriorityClass, priority_class_of
from koordinator_tpu.api.resources import (
    ResourceDim,
    NUM_RESOURCE_DIMS,
    ResourceVector,
    resource_vector,
)

__all__ = [
    "QoSClass",
    "PriorityClass",
    "priority_class_of",
    "ResourceDim",
    "NUM_RESOURCE_DIMS",
    "ResourceVector",
    "resource_vector",
]
