"""The canonical resource-dimension model.

The reference passes ``map[v1.ResourceName]int64`` resource lists everywhere and
vectorizes ad hoc (``pkg/scheduler/plugins/loadaware/helper.go`` —
``NewResourceVectorizer``). Here the vectorization IS the model: every resource
list is a fixed-width ``(R,)`` int32 vector with a global dimension order, so a
cluster is a ``(nodes, R)`` matrix and a pending-pod batch is a ``(pods, R)``
matrix that go straight onto the TPU.

Units are chosen so per-node quantities stay below 2^31/100 (the score and
percentage kernels multiply by MaxNodeScore=100 in int32; see
state/cluster_state.py MAX_QUANTITY — the reference does this math in int64,
we keep integer exactness by bounding units instead):

    cpu:            milli-cores   (bound 21.4M mcores = 21k cores per node)
    memory:         MiB           (bound 21.4M MiB ~ 20 TiB per node)
    ephemeral:      MiB
    gpu:            milli-GPU     (koordinator's kubernetes.io/gpu convention)
    gpu_memory:     MiB
    rdma:           milli-VF
    batch/mid cpu:  milli-cores   (kubernetes.io/batch-cpu etc., apis/extension/resource.go:27-30)
    batch/mid mem:  MiB
"""

from __future__ import annotations

import enum
from typing import Mapping

import numpy as np


class ResourceDim(enum.IntEnum):
    """Global resource dimension order for all (…, R) tensors. Do not reorder."""

    CPU = 0
    MEMORY = 1
    EPHEMERAL = 2
    GPU = 3
    GPU_MEMORY = 4
    RDMA = 5
    BATCH_CPU = 6
    BATCH_MEMORY = 7
    MID_CPU = 8
    MID_MEMORY = 9


NUM_RESOURCE_DIMS = len(ResourceDim)

#: Dimensions accounted in the "prod" pool vs the overcommitted pools.
PROD_DIMS = (ResourceDim.CPU, ResourceDim.MEMORY)
BATCH_DIMS = (ResourceDim.BATCH_CPU, ResourceDim.BATCH_MEMORY)
MID_DIMS = (ResourceDim.MID_CPU, ResourceDim.MID_MEMORY)

#: name <-> dim mapping using koordinator's resource-name protocol
#: (apis/extension/resource.go:27-30).
RESOURCE_NAMES: dict[str, ResourceDim] = {
    "cpu": ResourceDim.CPU,
    "memory": ResourceDim.MEMORY,
    "ephemeral-storage": ResourceDim.EPHEMERAL,
    "kubernetes.io/gpu": ResourceDim.GPU,
    "kubernetes.io/gpu-memory": ResourceDim.GPU_MEMORY,
    "koordinator.sh/rdma": ResourceDim.RDMA,
    "kubernetes.io/batch-cpu": ResourceDim.BATCH_CPU,
    "kubernetes.io/batch-memory": ResourceDim.BATCH_MEMORY,
    "kubernetes.io/mid-cpu": ResourceDim.MID_CPU,
    "kubernetes.io/mid-memory": ResourceDim.MID_MEMORY,
}

DIM_TO_NAME = {dim: name for name, dim in RESOURCE_NAMES.items()}

ResourceVector = np.ndarray  # (R,) int32, host-side alias


def resource_vector(quantities: Mapping[str, int] | None = None, **kw: int) -> np.ndarray:
    """Build an (R,) int32 vector from {resource-name: quantity-in-canonical-units}.

    Keyword form accepts dim names: ``resource_vector(cpu=4000, memory=8192)``.
    """
    vec = np.zeros(NUM_RESOURCE_DIMS, dtype=np.int32)
    if quantities:
        for name, q in quantities.items():
            vec[RESOURCE_NAMES[name]] = q
    for name, q in kw.items():
        vec[ResourceDim[name.upper()]] = q
    return vec


def stack_vectors(vectors, capacity: int | None = None) -> np.ndarray:
    """Stack host resource vectors into an (N, R) matrix, zero-padded to capacity."""
    n = len(vectors)
    cap = capacity if capacity is not None else n
    if cap < n:
        raise ValueError(f"capacity {cap} < {n} vectors")
    out = np.zeros((cap, NUM_RESOURCE_DIMS), dtype=np.int32)
    if n:
        out[:n] = np.stack(vectors)
    return out
