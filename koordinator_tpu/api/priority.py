"""Koordinator priority bands.

Reference: ``apis/extension/priority.go:29-48`` — pod priority values are
partitioned into four bands; the band determines which extended-resource pool
(prod / mid / batch / free) the pod's requests are accounted against:

    koord-prod  [9000, 9999]
    koord-mid   [7000, 7999]
    koord-batch [5000, 5999]
    koord-free  [3000, 3999]

Band classification over a ``(P,)`` priority tensor is plain integer
arithmetic (see :func:`priority_band_tensor`), so the scheduler can split a pod
batch into per-band resource accounting without host round-trips.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class PriorityClass(enum.IntEnum):
    """Priority band codes (tensor-stable)."""

    NONE = 0
    FREE = 1
    BATCH = 2
    MID = 3
    PROD = 4


# Band boundaries, inclusive. Mirrors apis/extension/priority.go:29-48.
PRIORITY_PROD_MIN, PRIORITY_PROD_MAX = 9000, 9999
PRIORITY_MID_MIN, PRIORITY_MID_MAX = 7000, 7999
PRIORITY_BATCH_MIN, PRIORITY_BATCH_MAX = 5000, 5999
PRIORITY_FREE_MIN, PRIORITY_FREE_MAX = 3000, 3999

_BANDS = (
    (PriorityClass.PROD, PRIORITY_PROD_MIN, PRIORITY_PROD_MAX),
    (PriorityClass.MID, PRIORITY_MID_MIN, PRIORITY_MID_MAX),
    (PriorityClass.BATCH, PRIORITY_BATCH_MIN, PRIORITY_BATCH_MAX),
    (PriorityClass.FREE, PRIORITY_FREE_MIN, PRIORITY_FREE_MAX),
)


def priority_class_of(priority: int) -> PriorityClass:
    """Band of a single scalar priority value."""
    for band, lo, hi in _BANDS:
        if lo <= priority <= hi:
            return band
    return PriorityClass.NONE


#: HP ("high priority" = Prod+Mid) band floor for the colocation formula
#: (slo-controller/noderesource plugins/util/util.go:55 — HP.Used counts
#: the pods batch capacity must stay out of the way of).  One definition
#: shared by the manager's NodeMetric sum and the koordlet's wire-report
#: aggregation: if these diverged, batch allocatable would differ by
#: which path a record arrived on.
HP_PRIORITY_MIN = 6000


def is_hp_band(qos_class: str, priority: int) -> bool:
    """Does a pod count as HP (Prod+Mid) for the colocation formula?"""
    return qos_class not in ("BE",) and priority >= HP_PRIORITY_MIN


def priority_band_tensor(priority):
    """Vectorized band classification: (P,) int32 priorities -> (P,) int8 bands."""
    band = jnp.zeros(priority.shape, dtype=jnp.int8)
    for cls, lo, hi in _BANDS:
        in_band = (priority >= lo) & (priority <= hi)
        band = jnp.where(in_band, jnp.int8(int(cls)), band)
    return band
