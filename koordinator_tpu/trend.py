"""Long-horizon trend engine: steady / drifting / leaking verdicts.

The SLO burn-rate engine (slo_monitor.py) answers "are we inside budget
right now"; nothing answered "is this process leaking or drifting under
hours of churn" — the acceptance bar every scaling item in ROADMAP.md
(sharded solver, predictive loop) is judged against.  This module is
that instrument:

1. **Slope fitting.**  :func:`fit_slope` runs an ordinary-least-squares
   fit over one windowed series (the same
   :class:`~koordinator_tpu.koordlet.metriccache.AggregateResult`
   views the SLO engine queries).  Degenerate windows — empty, a single
   sample, zero time span — return ``None``, the no-verdict sentinel;
   a NaN must never reach a verdict table or a dashboard.

2. **Classification.**  Each :class:`TrendSpec` names one series (RSS,
   fds, threads, queue depth, deltasync backlog, ...) and two
   thresholds that BOTH must be exceeded before a series is non-steady:

   - ``abs_floor`` — absolute growth over the evaluated window below
     which the series is always ``steady`` (noise immunity: a 20-second
     smoke window must not flag 2 threads of jitter);
   - ``max_rate_per_hour`` — the fitted slope, scaled to units/hour,
     above which growth is pathological at ANY window length (a
     10-thread/hour leak is a leak whether the window is 30 minutes or
     6 hours).

   Growth past both thresholds in the spec's leak ``direction`` that is
   also *persistent* — both half-windows grow, the window ends above
   where it started, and the fit explains the data (``min_r2``) — is
   ``leaking``.  Threshold-exceeding growth that is not persistent
   (a step after a resync, a sawtooth's edge, a downward trend) is
   ``drifting``.  Everything else is ``steady``; unevaluable windows
   are ``no_data``.

3. **Engine.**  :class:`TrendEngine` layers on the SLO monitor's
   :class:`MetricCache`: every registered spec is evaluated over every
   label set present (so per-``binary`` self-telemetry series get
   per-binary verdicts), the verdicts land in the
   ``trend_verdict{series}`` / ``trend_slope_per_hour{series}`` gauges
   (dashboards), and the full report is served at ``/debug/steady`` on
   both debug surfaces and tabulated by ``tools/soak_report.py`` —
   which fails the soak on any ``leaking`` verdict.

Reference anchors: the koordlet's decaying-histogram pipeline
(prediction/histogram.py) is the in-process cheap-time-series-analysis
pattern this extends; "A Predictive Autoscaler for Elastic Batch Jobs"
(PAPERS.md) grounds the windowed-trend-as-control-signal idea.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Iterable, Mapping, Optional

import numpy as np

from koordinator_tpu import metrics
from koordinator_tpu.koordlet.metriccache import MetricCache

logger = logging.getLogger("koordinator_tpu.trend")

VERDICT_STEADY = "steady"
VERDICT_DRIFTING = "drifting"
VERDICT_LEAKING = "leaking"
VERDICT_NO_DATA = "no_data"

#: gauge encoding of the verdicts (dashboards can threshold-color on
#: the value; the label carries the series name)
VERDICT_CODES = {
    VERDICT_NO_DATA: -1.0,
    VERDICT_STEADY: 0.0,
    VERDICT_DRIFTING: 1.0,
    VERDICT_LEAKING: 2.0,
}


@dataclasses.dataclass(frozen=True)
class SlopeFit:
    """One OLS fit over a windowed series (host scalars, JSON-able)."""

    n: int                 # samples fitted
    slope: float           # units per second
    intercept: float       # value at the window's first timestamp
    r2: float              # fraction of variance the line explains
    t_span: float          # seconds between first and last sample
    first: float           # value at the earliest timestamp
    last: float            # value at the latest timestamp
    mean: float

    @property
    def growth(self) -> float:
        """Fitted growth across the window (slope * span) — the
        quantity ``abs_floor`` bounds."""
        return self.slope * self.t_span


def fit_slope(ts, values) -> Optional[SlopeFit]:
    """OLS slope over one series; ``None`` (the no-verdict sentinel,
    never NaN) for windows that cannot support a fit: empty, a single
    sample, or all samples at one timestamp."""
    ts = np.asarray(ts, np.float64)
    values = np.asarray(values, np.float64)
    n = len(values)
    if n < 2:
        return None
    order = np.argsort(ts)
    ts, values = ts[order], values[order]
    t_span = float(ts[-1] - ts[0])
    if t_span <= 0:
        return None
    tc = ts - ts.mean()
    denom = float((tc * tc).sum())
    slope = float((tc * (values - values.mean())).sum() / denom)
    intercept = float(values.mean() - slope * (ts.mean() - ts[0]))
    fitted = intercept + slope * (ts - ts[0])
    ss_res = float(((values - fitted) ** 2).sum())
    ss_tot = float(((values - values.mean()) ** 2).sum())
    # a constant series is a PERFECT fit of its flat line, not an
    # undefined ratio — r2 must stay NaN-free for the verdict math
    r2 = 1.0 if ss_tot <= 0.0 else max(0.0, 1.0 - ss_res / ss_tot)
    return SlopeFit(n=n, slope=slope, intercept=intercept, r2=r2,
                    t_span=t_span, first=float(values[0]),
                    last=float(values[-1]), mean=float(values.mean()))


@dataclasses.dataclass(frozen=True)
class TrendSpec:
    """One series under long-horizon watch."""

    series: str                      # full exposition name in the cache
    #: None = evaluate every label set present independently (the
    #: per-binary self-telemetry series); a dict pins one label set
    labels: Optional[Mapping[str, str]] = None
    #: absolute growth across the window below which the series is
    #: always steady (in the series' own units)
    abs_floor: float = 0.0
    #: fitted slope (units/hour) above which growth is pathological
    max_rate_per_hour: float = 0.0
    #: which direction a LEAK grows ("up" for resources; "any" means
    #: the series can drift but never leak)
    direction: str = "up"
    #: below this many samples the window is no_data, not a verdict
    min_samples: int = 8
    #: a leak's fit must explain at least this much variance — a
    #: threshold-crossing slope through uncorrelated noise downgrades
    #: to drifting instead of paging as a leak
    min_r2: float = 0.25
    #: human context for the verdict table
    description: str = ""


def default_trend_specs(scale: float = 1.0) -> list[TrendSpec]:
    """The shipped leak watch: the process self-telemetry gauges every
    binary registers (selftelemetry.py) plus the queue-depth and
    deltasync-backlog series.  ``scale`` multiplies the absolute floors
    for bigger deployments (a 10k-node soak legitimately holds more
    pending pods than a 16-node smoke)."""
    mib = 1024.0 * 1024.0
    return [
        TrendSpec("koord_process_rss_bytes",
                  abs_floor=96 * mib * scale, max_rate_per_hour=256 * mib,
                  min_samples=12,
                  description="resident set size (proc statm)"),
        TrendSpec("koord_process_open_fds",
                  abs_floor=24 * scale, max_rate_per_hour=96,
                  description="open file descriptors"),
        TrendSpec("koord_process_threads",
                  abs_floor=8 * scale, max_rate_per_hour=32,
                  description="live Python threads"),
        TrendSpec("koord_process_alloc_blocks",
                  abs_floor=400_000 * scale, max_rate_per_hour=2_000_000,
                  min_samples=12,
                  description="interpreter-allocated memory blocks "
                              "(sys.getallocatedblocks)"),
        TrendSpec("koord_process_gc_objects",
                  abs_floor=200_000 * scale, max_rate_per_hour=1_000_000,
                  min_samples=12,
                  description="gc-tracked container objects"),
        TrendSpec("koord_scheduler_pending_pods",
                  abs_floor=max(64.0, 64 * scale),
                  max_rate_per_hour=600,
                  description="scheduler admission queue depth"),
        TrendSpec("koord_transport_sync_binding_backlog_peak",
                  abs_floor=max(64.0, 64 * scale), max_rate_per_hour=512,
                  description="deltasync local-binding backlog "
                              "high-water mark"),
        TrendSpec("koord_scheduler_solver_device_bytes",
                  abs_floor=128 * mib * scale, max_rate_per_hour=512 * mib,
                  description="device-resident solver tensors"),
    ]


def classify(spec: TrendSpec, fit: Optional[SlopeFit],
             half_fits: tuple[Optional[SlopeFit], Optional[SlopeFit]]
             = (None, None)) -> dict:
    """One spec's verdict over one fitted window (pure; unit-tested
    against constant/linear/noisy/step/sawtooth shapes)."""
    if fit is None or fit.n < spec.min_samples:
        return {"verdict": VERDICT_NO_DATA,
                "reason": ("no fit" if fit is None else
                           f"{fit.n} samples < min_samples "
                           f"{spec.min_samples}")}
    rate_per_hour = fit.slope * 3600.0
    doc = {
        "slope_per_sec": fit.slope,
        "rate_per_hour": rate_per_hour,
        "growth": fit.growth,
        "r2": fit.r2,
        "samples": fit.n,
        "window_span_s": fit.t_span,
        "first": fit.first,
        "last": fit.last,
    }
    exceeds = (abs(fit.growth) > spec.abs_floor
               and abs(rate_per_hour) > spec.max_rate_per_hour)
    if not exceeds:
        doc["verdict"] = VERDICT_STEADY
        return doc
    leakward = (fit.slope > 0 if spec.direction == "up"
                else fit.slope < 0 if spec.direction == "down"
                else False)
    sign = 1.0 if spec.direction != "down" else -1.0
    first_half, second_half = half_fits
    persistent = (
        leakward
        and fit.r2 >= spec.min_r2
        # the window must END displaced from where it started (a
        # sawtooth that returned home is churn, not a leak) ...
        and sign * (fit.last - fit.first) > spec.abs_floor
        # ... and BOTH halves must grow leakward: a step (resync,
        # capacity doubling) puts all its growth in one half
        and first_half is not None and second_half is not None
        and sign * first_half.slope > 0 and sign * second_half.slope > 0
    )
    doc["verdict"] = VERDICT_LEAKING if persistent else VERDICT_DRIFTING
    return doc


class TrendEngine:
    """Evaluates the registered specs' windowed slopes over a
    :class:`MetricCache` — normally the SLO monitor's, so one sampling
    pass feeds both burn rates and trends.

    Thread-safe the same way :class:`SloMonitor` is: evaluations
    serialize on one lock (on-demand ``/debug/steady`` requests arrive
    on gateway threads), and the latest report is retained for cheap
    re-reads.
    """

    def __init__(self, cache: MetricCache,
                 specs: Iterable[TrendSpec] | None = None,
                 window_s: float = 1800.0,
                 clock=time.time):
        self.cache = cache
        self.specs: list[TrendSpec] = (list(specs) if specs is not None
                                       else default_trend_specs())
        self.window_s = window_s
        self.clock = clock
        self._lock = threading.Lock()
        self._last_report: dict | None = None

    def register(self, spec: TrendSpec) -> None:
        with self._lock:
            self.specs.append(spec)

    # -- evaluation ----------------------------------------------------------

    def _evaluate_series(self, spec: TrendSpec,
                         labels: Mapping[str, str] | None,
                         start: float, end: float) -> dict:
        res = self.cache.query(spec.series, labels, start=start, end=end)
        fit = fit_slope(res.ts, res.values)
        halves: tuple[Optional[SlopeFit], Optional[SlopeFit]] = (None, None)
        if fit is not None and fit.t_span > 0:
            mid = float(np.min(res.ts)) + fit.t_span / 2.0
            lo = res.ts <= mid
            halves = (fit_slope(res.ts[lo], res.values[lo]),
                      fit_slope(res.ts[~lo], res.values[~lo]))
        doc = classify(spec, fit, halves)
        doc.update({
            "series": spec.series,
            "labels": dict(labels or {}),
            "abs_floor": spec.abs_floor,
            "max_rate_per_hour": spec.max_rate_per_hour,
            "description": spec.description,
        })
        return doc

    def evaluate(self, now: float | None = None,
                 window_s: float | None = None) -> dict:
        """Evaluate every spec over every present label set, publish the
        verdict gauges, and return (and retain) the ``/debug/steady``
        body."""
        now = self.clock() if now is None else now
        window = self.window_s if window_s is None else window_s
        start = now - window
        with self._lock:
            specs = list(self.specs)
        series_docs: list[dict] = []
        for spec in specs:
            label_sets: list = ([spec.labels] if spec.labels is not None
                                else self.cache.series_labels(spec.series)
                                or [None])
            for labels in label_sets:
                series_docs.append(
                    self._evaluate_series(spec, labels, start, now))
        counts = {v: 0 for v in VERDICT_CODES}
        for doc in series_docs:
            counts[doc["verdict"]] += 1
            # one gauge line per (series, labels): the label set rides
            # flattened so per-binary verdicts stay distinguishable
            glabels = {"series": doc["series"], **doc["labels"]}
            metrics.trend_verdict.set(VERDICT_CODES[doc["verdict"]],
                                      labels=glabels)
            metrics.trend_slope_per_hour.set(
                float(doc.get("rate_per_hour", 0.0)), labels=glabels)
            if doc["verdict"] == VERDICT_LEAKING:
                logger.warning(
                    "trend LEAK: %s%s growing %.3g/h over %.0fs "
                    "(r2=%.2f)", doc["series"], doc["labels"],
                    doc["rate_per_hour"], doc["window_span_s"], doc["r2"])
        report = {
            "evaluated_at": now,
            "window_s": window,
            "verdicts": counts,
            "leaking": [f"{d['series']}{d['labels'] or ''}"
                        for d in series_docs
                        if d["verdict"] == VERDICT_LEAKING],
            "drifting": [f"{d['series']}{d['labels'] or ''}"
                         for d in series_docs
                         if d["verdict"] == VERDICT_DRIFTING],
            "series": series_docs,
        }
        with self._lock:
            self._last_report = report
        return report

    def report(self) -> dict:
        """The latest evaluation; evaluates on demand when none is
        retained (the first ``/debug/steady`` request)."""
        with self._lock:
            last = self._last_report
        return last if last is not None else self.evaluate()
