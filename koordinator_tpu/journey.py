"""Pod-journey ledger: always-on e2e scheduling-latency sketches (ISSUE 20).

Every latency surface before this one was round-scoped and process-local:
``scheduling_latency`` is a fixed-bucket per-round histogram, the timeline
observatory attributes wall time per *cycle*, and spans are opt-in.  None
of them can state "the p99 pod waited X ms from arrival to bind, and Y of
that was queue wait".  The journey ledger closes that gap:

* **Arrival** is stamped at the manager/ingest leg and rides deltasync as
  an optional ``arrival_ts`` doc key (a sparse-extras column on v4
  ``events_v2`` frames; a plain JSON key on v1/v3 — no proto bump).
* **Enqueue** is stamped when the pod lands in the scheduler's pending
  queue; **bind** is stamped by the (batched) bind-commit path, which
  computes the whole round's e2e latencies in one vectorized op.
* Latencies feed per-(tenant, qos, stage) **DDSketch-style log-bucketed
  quantile sketches**: fixed <=1% relative error, O(1) insert, and merge
  is bucket-wise addition — associative, commutative, and loss-free, so
  per-process JSONL snapshots merge into one fleet-wide journey table
  (``tools/latency_report.py``) without shipping raw samples.

Stages (per pod, seconds):

* ``ingest``     — manager ingest -> scheduler enqueue (deltasync hop)
* ``queue_wait`` — enqueue -> the solve round that binds the pod starts
* ``solve``      — round start -> commit (dispatch + device block)
* ``commit``     — commit bookkeeping -> bind ack
* ``e2e``        — arrival (or enqueue when no arrival stamp) -> ack

Kill switch: ``KOORD_JOURNEY=0`` or ``--no-journey`` disables recording
entirely.  The ledger never touches solve inputs, the pending-queue sort
key, or quota charges — scheduling decisions are bit-identical either way
(asserted by tests/test_journey.py).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Iterable

import numpy as np

__all__ = [
    "DDSketch",
    "JourneyLedger",
    "LEDGER",
    "STAGES",
    "RELATIVE_ACCURACY",
]

# Relative accuracy target: quantile(q) is within +/-1% of the true value
# (for values inside the representable range).  gamma is the log-bucket
# base: bucket i covers (gamma^(i-1), gamma^i].
RELATIVE_ACCURACY = 0.01
_GAMMA = (1.0 + RELATIVE_ACCURACY) / (1.0 - RELATIVE_ACCURACY)
_LOG_GAMMA = math.log(_GAMMA)

# Values below this floor land in the zero bucket: 1ns is far below any
# observable scheduling latency and keeps bucket indices bounded.
_MIN_VALUE = 1e-9

# Sentinel bucket index for zero-bucket samples inside the batched
# composite-key pass (real bucket indices stay within 32 bits).
_ZERO_IDX = -(1 << 31)

STAGES = ("e2e", "ingest", "queue_wait", "solve", "commit")


class DDSketch:
    """Mergeable log-bucketed quantile sketch (DDSketch, arXiv:1908.10693).

    Bucket ``i`` covers ``(gamma^(i-1), gamma^i]`` with
    ``gamma = (1+a)/(1-a)``, so reporting the bucket midpoint
    ``2*gamma^i/(gamma+1)`` is within relative error ``a`` of any value in
    the bucket.  Inserts are O(1); merge is bucket-wise addition, which is
    associative and commutative with the empty sketch as identity —
    exactly the algebra fleet aggregation needs.
    """

    __slots__ = ("buckets", "zero_count", "count", "_min", "_max", "_sum")

    def __init__(self) -> None:
        self.buckets: dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self._min = math.inf
        self._max = -math.inf
        self._sum = 0.0

    # -- insert ---------------------------------------------------------
    @staticmethod
    def _index(value: float) -> int:
        return int(math.ceil(math.log(value) / _LOG_GAMMA))

    def insert(self, value: float) -> None:
        value = float(value)
        if not math.isfinite(value):
            return
        self.count += 1
        self._sum += max(value, 0.0)
        v = max(value, 0.0)
        self._min = min(self._min, v)
        self._max = max(self._max, v)
        if value <= _MIN_VALUE:
            self.zero_count += 1
            return
        idx = self._index(value)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def insert_many(self, values: Iterable[float]) -> None:
        for v in values:
            self.insert(v)

    def insert_repeated(self, value: float, n: int) -> None:
        """``n`` copies of the same value in O(1) — one bucket add.

        The solve/commit stages record one round-scalar for every pod
        the round carried; repeating the scalar insert n times (or
        materializing ``np.full(n, v)``) is pure waste.
        """
        if n <= 0:
            return
        value = float(value)
        if not math.isfinite(value):
            return
        v = max(value, 0.0)
        self.count += n
        self._sum += v * n
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        if value <= _MIN_VALUE:
            self.zero_count += n
        else:
            idx = self._index(value)
            self.buckets[idx] = self.buckets.get(idx, 0) + n

    def insert_batch(self, values: np.ndarray) -> None:
        """Vectorized insert: one log + one unique over the whole batch
        (the bind-commit path records a full round in one call)."""
        v = np.asarray(values, np.float64).reshape(-1)
        v = v[np.isfinite(v)]
        if v.size == 0:
            return
        clipped = np.maximum(v, 0.0)
        self.count += int(v.size)
        self._sum += float(clipped.sum())
        self._min = min(self._min, float(clipped.min()))
        self._max = max(self._max, float(clipped.max()))
        small = v <= _MIN_VALUE
        self.zero_count += int(small.sum())
        pos = v[~small]
        if pos.size:
            idx = np.ceil(np.log(pos) / _LOG_GAMMA).astype(np.int64)
            uniq, counts = np.unique(idx, return_counts=True)
            for i, n in zip(uniq.tolist(), counts.tolist()):
                self.buckets[i] = self.buckets.get(i, 0) + n

    # -- merge algebra --------------------------------------------------
    def merge(self, other: "DDSketch") -> "DDSketch":
        """Fold ``other`` into this sketch (bucket-wise add); returns self."""
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.zero_count += other.zero_count
        self.count += other.count
        self._sum += other._sum
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        return self

    def copy(self) -> "DDSketch":
        out = DDSketch()
        out.buckets = dict(self.buckets)
        out.zero_count = self.zero_count
        out.count = self.count
        out._min = self._min
        out._max = self._max
        out._sum = self._sum
        return out

    # -- quantiles ------------------------------------------------------
    def quantile(self, q: float) -> float | None:
        """The q-quantile (0<=q<=1), or None for an empty sketch."""
        if self.count <= 0:
            return None
        rank = q * (self.count - 1)
        if rank < self.zero_count:
            return 0.0
        seen = float(self.zero_count)
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen > rank:
                # bucket midpoint: within RELATIVE_ACCURACY of any value
                # the bucket can hold
                return 2.0 * _GAMMA ** idx / (_GAMMA + 1.0)
        return self._max if self._max > -math.inf else 0.0

    def mean(self) -> float | None:
        return self._sum / self.count if self.count else None

    @property
    def max_value(self) -> float | None:
        return self._max if self.count else None

    @property
    def min_value(self) -> float | None:
        return self._min if self.count else None

    # -- serialization --------------------------------------------------
    def to_doc(self) -> dict:
        """Compact, byte-deterministic doc: bucket keys sorted ascending."""
        doc: dict = {
            "alpha": RELATIVE_ACCURACY,
            "count": self.count,
            "zero": self.zero_count,
            "buckets": {str(i): self.buckets[i]
                        for i in sorted(self.buckets)},
        }
        if self.count:
            doc["min"] = self._min
            doc["max"] = self._max
            doc["sum"] = self._sum
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "DDSketch":
        out = cls()
        out.zero_count = int(doc.get("zero", 0))
        out.count = int(doc.get("count", 0))
        out.buckets = {int(k): int(v)
                       for k, v in doc.get("buckets", {}).items()}
        if out.count:
            out._min = float(doc.get("min", math.inf))
            out._max = float(doc.get("max", -math.inf))
            out._sum = float(doc.get("sum", 0.0))
        return out


class JourneyLedger:
    """Per-(tenant, qos, stage) sketch registry for pod journeys.

    All recording is O(1) per pod and guarded behind :attr:`enabled`; the
    disabled ledger is a handful of attribute loads per round — cheap
    enough to leave the call sites unconditional.

    The scheduling path only STAGES work: ``record_bind_batch`` pops the
    pods' stamps and appends one tuple.  The numpy/sketch digestion —
    bucket indexing, per-series aggregation — runs on the first read
    (report / snapshot / gauges) or after :data:`_STAGED_MAX` staged
    rounds, consolidated into one composite-key pass over every staged
    batch at once.  That keeps the bind critical path to dict ops and
    amortizes the vector math onto the telemetry sampler.
    """

    #: staged rounds that force an inline digest (bounds memory when no
    #: reader ever samples the ledger)
    _STAGED_MAX = 512

    def __init__(self, enabled: bool = True) -> None:
        self._enabled = bool(enabled)
        self._lock = threading.Lock()
        # (tenant, qos, stage) -> DDSketch
        self._sketches: dict[tuple[str, int, str], DDSketch] = {}
        # pod name -> (arrival_ts wall, enqueue wall, enqueue perf)
        self._pending: dict[str, tuple[float, float, float]] = {}
        # staged bind rounds awaiting digestion:
        # (tenant, qos_list, stamps, round_start_perf, solve_s, commit_s)
        self._staged: list[tuple] = []

    # -- lifecycle ------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """Flip recording; disabling clears all accumulated state."""
        with self._lock:
            self._enabled = bool(enabled)
            if not enabled:
                self._sketches.clear()
                self._pending.clear()
                self._staged.clear()

    def reset_for_tests(self) -> None:
        with self._lock:
            self._sketches.clear()
            self._pending.clear()
            self._staged.clear()

    # -- recording ------------------------------------------------------
    def note_enqueue(self, pod_name: str, arrival_ts: float = 0.0) -> None:
        """Stamp a pod's scheduler-enqueue time (and its upstream arrival
        stamp, if one rode deltasync in).

        Lock-free on purpose: this runs once per pod on the enqueue hot
        path, a single ``dict.setdefault`` is GIL-atomic, and
        first-enqueue-wins is exactly the semantics a resync replay
        needs (a replayed POD_ADD must not reset the pod's clock).
        """
        if not self._enabled:
            return
        self._pending.setdefault(
            pod_name,
            (float(arrival_ts or 0.0), time.time(), time.perf_counter()))

    def forget(self, pod_name: str) -> None:
        """Drop a pod's stamps (deleted before ever binding).

        Lock-free like ``note_enqueue``: ``dict.pop`` is GIL-atomic and
        this runs once per dequeued pod.
        """
        if not self._enabled:
            return
        self._pending.pop(pod_name, None)

    def record_bind_batch(self, tenant: str, pods, *,
                          round_start_perf: float,
                          commit_perf: float,
                          ack_perf: float | None = None) -> None:
        """Record one committed round's journeys in a single pass.

        ``pods`` is the round's bound PodSpec list; ``round_start_perf``
        is the perf_counter stamp when the binding solve round started,
        ``commit_perf`` when the commit bookkeeping began, ``ack_perf``
        when the bind callbacks acked (defaults to now).

        Scheduling-path cost is dict pops plus one list append — the
        vector math runs later in :meth:`_digest_locked`.
        """
        if not self._enabled or not pods:
            return
        if ack_perf is None:
            ack_perf = time.perf_counter()
        solve_s = max(commit_perf - round_start_perf, 0.0)
        commit_s = max(ack_perf - commit_perf, 0.0)
        with self._lock:
            if not self._enabled:
                return
            pop = self._pending.pop
            pairs = [(pod.qos, st) for pod in pods
                     if (st := pop(pod.name, None)) is not None]
            if not pairs:
                return
            self._staged.append((tenant, [q for q, _ in pairs],
                                 [st for _, st in pairs],
                                 round_start_perf, solve_s, commit_s))
            if len(self._staged) >= self._STAGED_MAX:
                self._digest_locked()

    def _digest_locked(self) -> None:
        """Fold every staged bind round into the sketches in one pass.

        Per staged round only a handful of (P,)-shaped ops run to turn
        stamps into stage latencies; bucket counting and per-series
        count/sum/min/max for ALL (tenant, qos, stage) series across
        the whole drain then happen through one composite-key
        ``np.unique`` plus one sort — the numpy fixed cost is paid per
        digest, not per round.  The per-round scalar stages (solve,
        commit) never touch numpy: n identical samples are one O(1)
        bucket add.  Caller holds ``self._lock``.
        """
        staged = self._staged
        if not staged:
            return
        self._staged = []
        seg_groups: list[int] = []
        seg_vals: list[np.ndarray] = []
        sketches: list[DDSketch] = []
        gid: dict[tuple[str, int, str], int] = {}

        def group(tenant: str, qos: int, stage: str) -> int:
            key = (tenant, qos, stage)
            g = gid.get(key)
            if g is None:
                g = gid[key] = len(sketches)
                sketches.append(self._sketch(tenant, qos, stage))
            return g

        for (tenant, qos_list, stamps, round_start_perf,
             solve_s, commit_s) in staged:
            stamp_arr = np.asarray(stamps, np.float64)    # (P, 3)
            arrival = stamp_arr[:, 0]
            queue_s = np.maximum(round_start_perf - stamp_arr[:, 2], 0.0)
            has_arrival = arrival > 0.0
            any_arrival = bool(has_arrival.any())
            # e2e closes on the same monotonic clock the stages use;
            # the ingest hop (wall-clock, cross-process) is added on
            # top when an arrival stamp rode deltasync in.  That hop
            # inherits producer↔scheduler clock offset one-for-one:
            # negative skew clamps to 0 below, positive skew inflates
            # ingest/e2e (see the clock-skew caveat in
            # docs/observability.md)
            if any_arrival:
                ingest_s = np.where(
                    has_arrival,
                    np.maximum(stamp_arr[:, 1] - arrival, 0.0), 0.0)
                e2e_s = ingest_s + queue_s + (solve_s + commit_s)
            else:
                ingest_s = None
                e2e_s = queue_s + (solve_s + commit_s)
            distinct = sorted(set(qos_list))
            for q in distinct:
                if len(distinct) == 1:
                    sel = None                      # whole round
                    n = len(qos_list)
                    ing = (ingest_s[has_arrival]
                           if any_arrival else None)
                    seg_vals.append(e2e_s)
                    seg_groups.append(group(tenant, q, "e2e"))
                    seg_vals.append(queue_s)
                else:
                    sel = np.asarray(qos_list) == q
                    n = int(sel.sum())
                    ing = (ingest_s[sel & has_arrival]
                           if any_arrival else None)
                    seg_vals.append(e2e_s[sel])
                    seg_groups.append(group(tenant, q, "e2e"))
                    seg_vals.append(queue_s[sel])
                seg_groups.append(group(tenant, q, "queue_wait"))
                if ing is not None and ing.size:
                    seg_vals.append(ing)
                    seg_groups.append(group(tenant, q, "ingest"))
                self._sketch(tenant, q, "solve").insert_repeated(
                    solve_s, n)
                self._sketch(tenant, q, "commit").insert_repeated(
                    commit_s, n)

        flat = np.concatenate(seg_vals)
        lens = np.fromiter((v.size for v in seg_vals), np.int64,
                           count=len(seg_vals))
        groups = np.repeat(np.asarray(seg_groups, np.int64), lens)
        small = flat <= _MIN_VALUE
        idx = np.ceil(np.log(np.where(small, 1.0, flat))
                      / _LOG_GAMMA).astype(np.int64)
        idx[small] = _ZERO_IDX
        # composite (group, bucket) key: bucket indices for any
        # representable latency fit comfortably in 32 bits
        composite = groups * (1 << 33) + (idx + (1 << 32))
        uniq, counts = np.unique(composite, return_counts=True)
        # per-group count/sum/min/max via one sort + reduceat
        order = np.argsort(groups, kind="stable")
        sv, sg = flat[order], groups[order]
        starts = np.concatenate(([0], np.flatnonzero(np.diff(sg)) + 1))
        g_ids = sg[starts].tolist()
        g_counts = np.diff(np.concatenate((starts, [sg.size]))).tolist()
        g_sums = np.add.reduceat(sv, starts).tolist()
        g_mins = np.minimum.reduceat(sv, starts).tolist()
        g_maxs = np.maximum.reduceat(sv, starts).tolist()
        for g, cnt, gsum, gmin, gmax in zip(
                g_ids, g_counts, g_sums, g_mins, g_maxs):
            sk = sketches[g]
            sk.count += cnt
            sk._sum += gsum
            if gmin < sk._min:
                sk._min = gmin
            if gmax > sk._max:
                sk._max = gmax
        for comp, cnt in zip(uniq.tolist(), counts.tolist()):
            g, b = divmod(comp, 1 << 33)
            b -= 1 << 32
            sk = sketches[g]
            if b == _ZERO_IDX:
                sk.zero_count += cnt
            else:
                sk.buckets[b] = sk.buckets.get(b, 0) + cnt

    def _sketch(self, tenant: str, qos: int, stage: str) -> DDSketch:
        key = (tenant, qos, stage)
        sk = self._sketches.get(key)
        if sk is None:
            sk = self._sketches[key] = DDSketch()
        return sk

    # -- reporting ------------------------------------------------------
    def tenants(self) -> list[str]:
        with self._lock:
            self._digest_locked()
            return sorted({t for (t, _q, _s) in self._sketches})

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def snapshot_doc(self, tenant: str | None = None) -> dict:
        """Serializable snapshot: ``{"series": [{tenant,qos,stage,sketch}]}``.

        Deterministic ordering (sorted keys) so identical ledgers produce
        byte-identical JSON.
        """
        with self._lock:
            self._digest_locked()
            keys = sorted(k for k in self._sketches
                          if tenant is None or k[0] == tenant)
            series = [{"tenant": t, "qos": q, "stage": s,
                       "sketch": self._sketches[(t, q, s)].to_doc()}
                      for (t, q, s) in keys]
        return {"alpha": RELATIVE_ACCURACY, "series": series}

    def report(self, tenant: str | None = None,
               quantiles: tuple[float, ...] = (0.5, 0.9, 0.99)) -> dict:
        """Human-facing journey table: per-series quantiles + counts."""
        with self._lock:
            self._digest_locked()
            keys = sorted(k for k in self._sketches
                          if tenant is None or k[0] == tenant)
            rows = []
            for (t, q, s) in keys:
                sk = self._sketches[(t, q, s)]
                row = {"tenant": t, "qos": q, "stage": s,
                       "count": sk.count,
                       "mean_s": sk.mean(), "max_s": sk.max_value}
                for quant in quantiles:
                    row[f"p{int(quant * 100)}_s"] = sk.quantile(quant)
                rows.append(row)
        return {"enabled": self._enabled, "alpha": RELATIVE_ACCURACY,
                "series": rows}

    def write_jsonl(self, path: str) -> int:
        """Append one snapshot line per (tenant, qos, stage) series."""
        doc = self.snapshot_doc()
        with open(path, "a", encoding="utf-8") as fh:
            for row in doc["series"]:
                fh.write(json.dumps(row, sort_keys=True,
                                    separators=(",", ":")) + "\n")
        return len(doc["series"])

    # -- metrics / SloMonitor bridge ------------------------------------
    def publish_gauges(self) -> None:
        """Publish per-series quantile gauges; safe as a SloMonitor
        ``pre_sample`` hook (never raises)."""
        if not self._enabled:
            return
        try:
            from koordinator_tpu import metrics
            with self._lock:
                self._digest_locked()
                items = [(k, sk.copy())
                         for k, sk in self._sketches.items()]
            for (t, q, s), sk in items:
                for quant, tag in ((0.5, "0.5"), (0.99, "0.99")):
                    v = sk.quantile(quant)
                    if v is not None:
                        metrics.pod_journey_latency_seconds.set(
                            v, labels={"tenant": t, "qos": str(q),
                                       "stage": s, "q": tag})
        except Exception:
            pass


def merge_snapshot_rows(rows: Iterable[dict]) -> dict:
    """Merge JSONL snapshot rows (possibly from many processes) into one
    ``(tenant, qos, stage) -> DDSketch`` table — the fleet-aggregation
    primitive behind tools/latency_report.py and soak_report."""
    merged: dict[tuple[str, int, str], DDSketch] = {}
    for row in rows:
        key = (str(row["tenant"]), int(row["qos"]), str(row["stage"]))
        sk = DDSketch.from_doc(row["sketch"])
        if key in merged:
            merged[key].merge(sk)
        else:
            merged[key] = sk
    return merged


LEDGER = JourneyLedger(enabled=os.environ.get("KOORD_JOURNEY", "1") != "0")
