"""koordinator-tpu: a TPU-native cluster co-location scheduling framework.

A ground-up rebuild of the capabilities of koordinator (QoS-based co-location
scheduling for Kubernetes) with the scheduling hot path redesigned TPU-first:
cluster state lives as device-resident tensors and every Filter/Score/quota/
gang/rebalance decision is a batched JAX solve over a
(pods x nodes x resource-dims) tensor, sharded across a TPU mesh.

Layer map (mirrors SURVEY.md section 1, rebuilt TPU-native):

- ``koordinator_tpu.api``        -- L1 protocol types (QoS, priority, resources, CRDs)
- ``koordinator_tpu.state``      -- device-resident cluster-state tensors
- ``koordinator_tpu.ops``        -- batched solver kernels (filter/score/assign/quota/gang)
- ``koordinator_tpu.parallel``   -- mesh construction + sharded solves (ICI/DCN)
- ``koordinator_tpu.scheduler``  -- L5/L6 framework shell + plugins
- ``koordinator_tpu.manager``    -- L4 central controllers (colocation math, NodeSLO)
- ``koordinator_tpu.descheduler``-- L7 rebalancing + migration
- ``koordinator_tpu.koordlet``   -- L3 node agent (informers, metrics, QoS enforcement)
- ``koordinator_tpu.utils``      -- shared utilities (cpuset, histogram, features...)
"""

__version__ = "0.1.0"
