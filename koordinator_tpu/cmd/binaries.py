"""The six binaries' parsers + assembly (reference cmd/ tree).

Each ``main_<binary>(argv)`` parses flags, applies feature gates, and
returns the assembled component graph as a small namespace object —
callers (tests, the driver, a real deployment shim) wire transports and
call ``run()`` themselves. Flags mirror the reference commands:

- koordlet            (cmd/koordlet/main.go)
- koord-scheduler     (cmd/koord-scheduler/app/server.go)
- koord-manager       (cmd/koord-manager/main.go)
- koord-descheduler   (cmd/koord-descheduler)
- koord-runtime-proxy (cmd/koord-runtime-proxy/main.go)
- koord-device-daemon (cmd/koord-device-daemon/main.go)
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Any, Optional

from koordinator_tpu.cmd import (
    add_common_flags,
    add_leader_election_flags,
    apply_feature_gates,
    build_elector,
    build_self_telemetry,
)


@dataclasses.dataclass
class Assembled:
    """What a binary main() hands back: the component graph + metadata."""

    name: str
    args: argparse.Namespace
    component: Any
    elector: Optional[Any] = None
    server: Optional[Any] = None   # transport RpcServer when one was opened
    gateway: Optional[Any] = None  # HTTP/JSON gateway when one was opened
    state_sync: Optional[Any] = None  # StateSyncService (sidecar assembly)
    #: parsed component config (Scheduler/DeschedulerComponentConfig) so
    #: the embedding shell wires data-dependent plugins with file args
    component_config: Optional[Any] = None
    #: process self-telemetry sampler (selftelemetry.SelfTelemetry) —
    #: every binary registers the same leak-watch gauges under its own
    #: binary label
    telemetry: Optional[Any] = None
    #: warm-restart checkpoint writer (drills.checkpoint.CheckpointWriter)
    #: when --checkpoint-path is set; stop() writes a final cut
    checkpointer: Optional[Any] = None

    def stop(self) -> None:
        """Tear down whatever this binary opened (sockets, gateway, the
        component's own lifecycle); a leading elector releases its lease
        so a follower acquires without waiting out the duration."""
        if self.checkpointer is not None:
            self.checkpointer.stop()
        # journey-ledger fleet snapshot (ISSUE 20): every binary flushes
        # its sketch table on teardown when KOORD_JOURNEY_JSONL names a
        # path — tools/latency_report.py merges the per-process files
        # into one fleet-wide journey table (merge = bucket-wise add)
        journey_path = os.environ.get("KOORD_JOURNEY_JSONL")
        if journey_path:
            try:
                from koordinator_tpu import journey

                if journey.LEDGER.enabled:
                    journey.LEDGER.write_jsonl(journey_path)
            except Exception:
                pass
        if self.telemetry is not None:
            self.telemetry.stop()
        if self.elector is not None:
            self.elector.release()
        if self.gateway is not None:
            self.gateway.stop()
        if self.server is not None:
            self.server.stop()
        stop = getattr(self.component, "stop", None)
        if callable(stop):
            stop()


class ReconnectingSidecarClient:
    """Lazy + reconnecting RPC client for a scheduler-sidecar socket —
    ONE error policy shared by the koordlet's reporters and the
    manager's colocation loop (two hand-rolled copies had already
    diverged on RpcRemoteError handling, r5 review):

    - dials lazily on first use: no boot-order constraint between
      binaries (a missing sidecar costs the call/tick, not the process);
    - dial failures drive a circuit breaker (transport.retry): a dead
      sidecar gets backoff+jitter-paced probes — O(log) dials over an
      outage, not one per caller tick — and callers inside an open
      window fail fast with ``RpcError`` instead of re-dialing;
    - ``on_connect(client)`` runs after every (re)dial — the manager's
      ``sync.bootstrap`` rides here so its watch view resumes from
      last_rv after a sidecar restart; a failed hook closes the fresh
      client (no fd/reader-thread leak), counts as a dial failure for
      the breaker, and surfaces;
    - REMOTE errors (the peer rejecting one request over a healthy
      connection, e.g. unknown node before an upsert lands) pass
      through WITHOUT tearing the shared connection down — closing
      would kill other threads' in-flight calls and, for a watch
      client, force a needless full resync.  Exception: an ERROR with
      ``resync: true`` re-runs ``on_connect`` first (the server says
      the WATCH VIEW is stale — re-HELLO now, then let the caller's
      next tick retry its push against the fresh view);
    - transport errors drop only the client the caller saw fail (a
      racing caller may already have reconnected).
    """

    def __init__(self, addr: str, on_push=None, on_connect=None,
                 timeout: float = 10.0, breaker=None, retry_policy=None,
                 faults=None, fault_domain: str = ""):
        import threading

        from koordinator_tpu.transport.retry import CircuitBreaker

        self.addr = addr
        self.on_push = on_push
        self.on_connect = on_connect
        self.timeout = timeout
        self.faults = faults
        self.fault_domain = fault_domain
        #: pass breaker=False to disable pacing entirely (tests that
        #: want a dial per call); None builds the shared default
        self.breaker = (None if breaker is False
                        else breaker if breaker is not None
                        else CircuitBreaker(target=addr,
                                            policy=retry_policy))
        if self.faults is not None and self.breaker is not None:
            # heal seam: FaultInjector.heal() resets the breaker so the
            # healed sidecar is probed immediately, not after the
            # remaining (chaos-grown) open window
            register = getattr(self.faults, "register_breaker", None)
            if register is not None:
                register(self.breaker)
        self.resyncs = 0
        self._client = None
        self._lock = threading.Lock()

    def ensure(self):
        """Connected client, (re)dialing if needed (breaker-paced)."""
        from koordinator_tpu import metrics
        from koordinator_tpu.transport import RpcClient
        from koordinator_tpu.transport.channel import RpcError

        with self._lock:
            if self._client is None or not self._client.connected:
                if self.breaker is not None and not self.breaker.allow():
                    metrics.dial_attempts_total.inc(
                        labels={"outcome": "open"})
                    raise RpcError(
                        f"sidecar circuit open ({self.breaker.describe()})")
                self._close_locked()
                client = RpcClient(self.addr, on_push=self.on_push,
                                   timeout=self.timeout,
                                   faults=self.faults,
                                   fault_domain=self.fault_domain)
                try:
                    client.connect()
                except OSError as e:
                    if self.breaker is not None:
                        self.breaker.record_failure()
                    metrics.dial_attempts_total.inc(
                        labels={"outcome": "refused"})
                    raise RpcError(f"sidecar unreachable: {e}") from e
                if self.on_connect is not None:
                    try:
                        self.on_connect(client)
                    except BaseException:
                        # the sidecar ACCEPTED the dial but the bootstrap
                        # (HELLO/resync hook) failed: a reachable-but-
                        # unhealthy peer.  Same breaker pacing, but a
                        # distinct outcome — an operator paging on
                        # 'refused' would investigate networking/process
                        # liveness when the process is up fine
                        client.close()
                        if self.breaker is not None:
                            self.breaker.record_failure()
                        metrics.dial_attempts_total.inc(
                            labels={"outcome": "bootstrap_failed"})
                        raise
                if self.breaker is not None:
                    self.breaker.record_success()
                metrics.dial_attempts_total.inc(labels={"outcome": "ok"})
                self._client = client
            return self._client

    def call(self, *call_args, **call_kwargs):
        # the lock covers only connect/reconnect/close: RpcClient.call
        # is concurrency-safe (per-request waiter map), and holding the
        # lock across a call would serialize caller threads behind a
        # wedged sidecar for the full timeout each
        from koordinator_tpu import metrics
        from koordinator_tpu.transport.channel import (
            RpcError,
            RpcRemoteError,
        )

        client = self.ensure()
        try:
            return client.call(*call_args, **call_kwargs)
        except RpcRemoteError as e:
            if e.resync and self.on_connect is not None:
                # server-directed resync: our watch view is stale (e.g.
                # it restarted and lost the node this push named).
                # Re-HELLO on the still-healthy connection; the failed
                # call still surfaces (its state may be gone for real)
                # and the caller's next tick runs against the new view.
                self.resyncs += 1
                metrics.sync_resyncs_total.inc()
                try:
                    if client.connected:
                        self.on_connect(client)
                except Exception:
                    pass  # resync is best effort; reconnect path remains
            raise
        except (RpcError, OSError):
            with self._lock:
                if self._client is client:
                    self._close_locked()
            raise

    # koordlint: guarded-by(self._lock)
    def _close_locked(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


# ---- koordlet --------------------------------------------------------------

def build_koordlet_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koordlet")
    add_common_flags(parser)
    parser.add_argument("--cgroup-root-dir", default="/sys/fs/cgroup")
    parser.add_argument("--proc-root-dir", default="/proc")
    parser.add_argument("--sys-root-dir", default="/sys")
    parser.add_argument("--cgroup-driver-systemd", action="store_true")
    parser.add_argument("--cgroup-v2", action="store_true")
    parser.add_argument("--audit-log-dir", default="")
    parser.add_argument("--collect-interval-seconds", type=float, default=1.0)
    parser.add_argument(
        "--http-port", type=int, default=None,
        help="serve the HTTP/JSON gateway (incl. /v1/podresources when "
             "the PodResourcesProxy gate is on); omit to disable")
    parser.add_argument(
        "--runtime-hook-server-addr", default="",
        help="serve the runtimehooks plugins to a runtime proxy over this "
             "address (unix path or tcp://host:port) — the nri/server.go "
             "/ proxyserver seam; empty disables")
    parser.add_argument(
        "--kubelet-addr", default="",
        help="poll this kubelet's /pods as the pod informer "
             "(states_pods.go); empty keeps the shell-fed informer")
    parser.add_argument("--kubelet-port", type=int, default=10250)
    parser.add_argument("--kubelet-scheme", default="https",
                        choices=("https", "http"))
    parser.add_argument("--kubelet-token-file", default="")
    parser.add_argument("--kubelet-ca-file", default="")
    parser.add_argument("--kubelet-insecure-skip-verify",
                        action="store_true")
    parser.add_argument("--kubelet-timeout-seconds", type=float,
                        default=5.0)
    parser.add_argument("--informer-sync-interval-seconds", type=float,
                        default=30.0)
    parser.add_argument(
        "--scheduler-sidecar-addr", default="",
        help="push this node's NodeMetric usage to a solver sidecar "
             "over STATE_PUSH node_usage frames (the states_nodemetric "
             "report loop's wire form); requires --node-name")
    parser.add_argument("--node-name", default="")
    parser.add_argument("--nodemetric-report-interval-seconds", type=float,
                        default=60.0)
    parser.add_argument(
        "--device-report-interval-seconds", type=float, default=60.0,
        help="Device-CR report cadence (the device heartbeat that also "
             "repairs server-side inventory clears)")
    return parser


def main_koordlet(argv: list[str], device_report_fn=None,
                  pod_resources_upstream_fn=None,
                  node_info_fn=None) -> Assembled:
    """``device_report_fn(Device)`` is the deployment shell's Device-CR
    sink (apiserver client / StateSyncService.upsert_node devices=...);
    None disables the in-agent reporting tick.
    ``pod_resources_upstream_fn()`` is the kubelet pod-resources stub the
    PodResourcesProxy enriches; None serves koord allocations only.
    ``node_info_fn() -> NodeInfo`` is the shell's Node watch (the
    states_node informer); it registers as the 'node' informer the
    kubelet pods informer depends on."""
    from koordinator_tpu.features import KOORDLET_GATES
    from koordinator_tpu.koordlet.daemon import Daemon
    from koordinator_tpu.koordlet.system.config import SystemConfig

    args = build_koordlet_parser().parse_args(argv)
    apply_feature_gates(args.feature_gates, KOORDLET_GATES)
    cfg = SystemConfig(
        cgroup_root=args.cgroup_root_dir,
        proc_root=args.proc_root_dir,
        sys_root=args.sys_root_dir,
        use_cgroup_v2=args.cgroup_v2,
        cgroup_driver_systemd=args.cgroup_driver_systemd,
    )
    daemon = Daemon(cfg=cfg, audit_dir=args.audit_log_dir or None,
                    device_report_fn=device_report_fn,
                    pod_resources_upstream_fn=pod_resources_upstream_fn,
                    informer_sync_interval_seconds=(
                        args.informer_sync_interval_seconds),
                    device_report_interval_seconds=(
                        args.device_report_interval_seconds))
    if node_info_fn is not None:
        from koordinator_tpu.koordlet.statesinformer import CallbackInformer

        daemon.informers.register(CallbackInformer(
            "node", lambda states: states.set_node(node_info_fn())))
    if args.kubelet_addr:
        from koordinator_tpu.koordlet.kubelet_stub import KubeletStub
        from koordinator_tpu.koordlet.statesinformer import (
            CallbackInformer,
            KubeletPodsInformer,
        )

        stub = KubeletStub.connect(
            args.kubelet_addr, args.kubelet_port,
            scheme=args.kubelet_scheme,
            token_file=args.kubelet_token_file or None,
            ca_file=args.kubelet_ca_file or None,
            insecure_skip_verify=args.kubelet_insecure_skip_verify,
            timeout=args.kubelet_timeout_seconds,
        )
        if node_info_fn is None:
            # the pods informer depends on 'node'; without a shell Node
            # watch, a no-op placeholder satisfies the ordering (the
            # agent's node identity then comes from set_node callers)
            daemon.informers.register(CallbackInformer(
                "node", lambda states: None))
        daemon.informers.register(KubeletPodsInformer(stub))
        daemon.kubelet_stub = stub
    if args.scheduler_sidecar_addr:
        if not args.node_name:
            raise SystemExit(
                "--scheduler-sidecar-addr requires --node-name (the "
                "node_usage event is keyed by node)")
        import numpy as _np

        from koordinator_tpu.api.resources import resource_vector
        from koordinator_tpu.koordlet.statesinformer import (
            NodeMetricReporter,
        )
        from koordinator_tpu.transport.wire import FrameType

        sidecar = ReconnectingSidecarClient(args.scheduler_sidecar_addr)
        daemon.sidecar_client = sidecar

        def push_usage(status) -> None:
            # a degraded report (collectors silent) must not zero the
            # sidecar's view — skip and let the last usage stand
            if getattr(status, "degraded", False):
                return
            usage = resource_vector({
                "cpu": status.node_usage.cpu_milli,
                "memory": status.node_usage.memory_bytes >> 20,  # MiB
            })
            agg = None
            aggregated = status.aggregated_node_usage
            if aggregated is not None and aggregated.cpu_milli_p:
                # p95 percentile feeds the aggregated-threshold filter
                # (loadaware Aggregated args); fall back to the highest
                # recorded percentile
                pct = 0.95 if 0.95 in aggregated.cpu_milli_p else max(
                    aggregated.cpu_milli_p)
                agg = resource_vector({
                    "cpu": aggregated.cpu_milli_p[pct],
                    "memory": aggregated.memory_bytes_p.get(pct, 0) >> 20,
                })
            arrays = {"usage": _np.asarray(usage, _np.int32)}
            if agg is not None:
                arrays["agg_usage"] = _np.asarray(agg, _np.int32)
            # the colocation formula's inputs ride along (SURVEY §3.2:
            # Batch = Total - SafetyMargin - max(System, Reserved) -
            # HP.Used): system daemon usage, and the HP (Prod+Mid)
            # pod-usage sum — is_hp_band is the ONE definition shared
            # with the manager's _hp_used_cpu NodeMetric fallback
            from koordinator_tpu.api.priority import (
                PriorityClass,
                is_hp_band,
                priority_class_of,
            )

            arrays["sys_usage"] = _np.asarray(resource_vector({
                "cpu": status.system_usage.cpu_milli,
                "memory": status.system_usage.memory_bytes >> 20,
            }), _np.int32)
            hp_cpu = hp_mem = prod_cpu = prod_mem = 0
            for p in status.pods_metrics:
                if is_hp_band(p.qos_class, p.priority):
                    hp_cpu += p.usage.cpu_milli
                    hp_mem += p.usage.memory_bytes >> 20
                # prod-band usage feeds loadaware's prod-usage mode
                # (NodeSpec.prod_usage -> node_prod_usage rows)
                if priority_class_of(p.priority) is PriorityClass.PROD:
                    prod_cpu += p.usage.cpu_milli
                    prod_mem += p.usage.memory_bytes >> 20
            arrays["hp_usage"] = _np.asarray(resource_vector({
                "cpu": hp_cpu, "memory": hp_mem}), _np.int32)
            arrays["prod_usage"] = _np.asarray(resource_vector({
                "cpu": prod_cpu, "memory": prod_mem}), _np.int32)
            # request/maxUsageRequest calculate-policy inputs: the HP
            # pods' REQUEST sum and per-pod max(request, usage) sum —
            # one is_hp_band walk over the informer's pod requests.
            # Without these the manager's wire-fed NodeRecords compute
            # batch capacity as if HP pods had requested nothing and
            # silently over-advertise under those policies.
            usage_by_uid = {p.uid: p.usage for p in status.pods_metrics}
            req_cpu = req_mem = max_cpu = max_mem = 0
            for meta in daemon.states.get_all_pods():
                if not meta.is_running:
                    continue
                if not is_hp_band(meta.qos_class.name, meta.priority):
                    continue
                r_cpu = int(meta.requests.get("cpu", 0))
                r_mem = int(meta.requests.get("memory", 0)) >> 20
                req_cpu += r_cpu
                req_mem += r_mem
                used = usage_by_uid.get(meta.uid)
                u_cpu = used.cpu_milli if used is not None else 0
                u_mem = (used.memory_bytes >> 20) if used is not None else 0
                max_cpu += max(r_cpu, u_cpu)
                max_mem += max(r_mem, u_mem)
            arrays["hp_request"] = _np.asarray(resource_vector({
                "cpu": req_cpu, "memory": req_mem}), _np.int32)
            arrays["hp_max_used_req"] = _np.asarray(resource_vector({
                "cpu": max_cpu, "memory": max_mem}), _np.int32)
            sidecar.call(FrameType.STATE_PUSH,
                         {"kind": "node_usage", "name": args.node_name,
                          # the report's OWN timestamp: consumers date the
                          # usage by when the koordlet measured it, not by
                          # when the delta applied (degrade windows must
                          # survive manager restarts + snapshot replay)
                          "usage_time": float(status.update_time)},
                         arrays)

        daemon.reporters.append(NodeMetricReporter(
            daemon.states, push_usage,
            report_interval_seconds=(
                args.nodemetric_report_interval_seconds),
            clock=daemon.clock,
        ))

        if device_report_fn is None:
            # default Device-CR sink when a sidecar is wired: the
            # inventory rides node_devices frames (device daemon report
            # loop in wire form); shell-provided sinks still win
            from koordinator_tpu.koordlet.devices import (
                device_infos_to_inventory,
            )

            import threading as _threading

            device_push_inflight = _threading.Event()
            daemon.device_push_failures = 0

            def push_devices(device) -> None:
                inventory = device_infos_to_inventory(list(device.devices))
                # push EVERY interval, empty or not (heartbeat): the
                # server drops unchanged pushes without log churn
                # (update_node_devices dedups against the stored doc),
                # the periodic re-push restores inventory a server-side
                # re-upsert may have cleared, and the empty push clears
                # tensors for vanished hardware EVEN ACROSS a koordlet
                # restart (any in-process last-push cache would skip the
                # clear when the devices disappeared while we were down)
                # one in-flight push: a wedged sidecar must not pile up
                # threads (the next report interval retries)
                if device_push_inflight.is_set():
                    return
                device_push_inflight.set()

                def send() -> None:
                    try:
                        sidecar.call(
                            FrameType.STATE_PUSH,
                            {"kind": "node_devices",
                             # the daemon's registered identity, same as
                             # push_usage — a Device-CR node_name that
                             # differs is an unknown node upstream
                             "name": args.node_name,
                             "devices": inventory})
                    except Exception:  # noqa: BLE001 — COUNTED, next
                        daemon.device_push_failures += 1  # interval retries
                    finally:
                        device_push_inflight.clear()

                # off the enforcement thread, like the usage reporter
                _threading.Thread(target=send, daemon=True).start()

            daemon.device_report_fn = push_devices
    if args.http_port is not None:
        from koordinator_tpu.transport.http_gateway import HttpGateway

        daemon.gateway = HttpGateway(
            port=args.http_port,
            dispatcher=None,
            pod_resources=(daemon.pod_resources
                           if daemon.pod_resources.enabled() else None),
            auditor=(daemon.auditor
                     if KOORDLET_GATES.enabled("AuditEventsHTTPHandler")
                     else None),
        )
        daemon.gateway.start()
    if args.runtime_hook_server_addr:
        from koordinator_tpu.koordlet.runtimehooks.server import (
            RegistryHookServer,
        )
        from koordinator_tpu.runtimeproxy import Dispatcher, HookType
        from koordinator_tpu.transport import RpcServer
        from koordinator_tpu.transport.services import HookService

        hook_dispatcher = Dispatcher()
        hook_dispatcher.register(
            RegistryHookServer(daemon.hook_registry), list(HookType))
        daemon.hook_server = RpcServer(args.runtime_hook_server_addr,
                                       service="koordlet")
        HookService(hook_dispatcher).attach(daemon.hook_server)
        daemon.hook_server.start()
    return Assembled(name="koordlet", args=args, component=daemon,
                     telemetry=build_self_telemetry(args, "koordlet"))


# ---- koord-scheduler -------------------------------------------------------

def build_scheduler_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-scheduler")
    add_common_flags(parser)
    add_leader_election_flags(parser, default_lease="koord-scheduler")
    parser.add_argument("--node-capacity", type=int, default=1024,
                        help="initial padded node-state capacity")
    parser.add_argument("--gang-passes", type=int, default=2)
    parser.add_argument("--batch-solver-threshold", type=int, default=1024,
                        help="queue size at which rounds switch from the "
                             "exact greedy scan to the data-parallel "
                             "propose/accept engine")
    parser.add_argument("--enable-preemption", action="store_true")
    parser.add_argument("--sync-barrier-timeout", type=float, default=30.0,
                        help="app/sync_barrier.go wait budget")
    parser.add_argument(
        "--staleness-threshold-seconds", type=float, default=0.0,
        help="sync-feed silence (seconds) after which rounds flip into "
             "stale-state degraded mode: BE/batch-dim admission suspends "
             "and solves go full-pass until a resync re-warms the feed; "
             "0 disables the watchdog")
    parser.add_argument("--listen-socket", default="",
                        help="unix socket for the solve/state-sync RPC "
                             "services (empty = in-process only)")
    parser.add_argument(
        "--http-port", type=int, default=None,
        help="serve the HTTP/JSON gateway (solve, state push, leases, "
             "diagnosis) — the zero-client-code sidecar surface; omit "
             "to disable")
    parser.add_argument(
        "--config", default="",
        help="KubeSchedulerConfiguration YAML with per-plugin args "
             "(LoadAwareScheduling, NodeResourcesFitPlus, "
             "ScarceResourceAvoidance, Coscheduling) — the reference's "
             "versioned component config; defaults apply where unset")
    parser.add_argument(
        "--no-explain", action="store_true",
        help="disable placement explainability: the device-side "
             "reject-reason accounting (ops/explain.py), the "
             "/debug/explain/<pod> explanations, and the "
             "unschedulable_pods/filter_reject_fraction/capacity_slack "
             "rollups all go dark; Diagnose falls back to the per-pod "
             "host recompute")
    parser.add_argument(
        "--no-timeline", action="store_true",
        help="disable the critical-path observatory (timeline.py): no "
             "per-cycle segment recording, host-wait attribution, "
             "critical-path solving, or /debug/timeline bodies — the "
             "kill switch for suspected self-overhead (decisions are "
             "bit-identical either way; KOORD_TIMELINE=0 is the env "
             "equivalent)")
    parser.add_argument(
        "--no-journey", action="store_true",
        help="disable the pod-journey ledger (journey.py): no per-pod "
             "arrival/enqueue/bind latency sketches, /debug/latency "
             "answers 501, and the pod_journey_latency_seconds gauges "
             "go dark — the kill switch for suspected self-overhead "
             "(scheduling decisions and quota charges are bit-identical "
             "either way; KOORD_JOURNEY=0 is the env equivalent)")
    parser.add_argument(
        "--trace-pods", action="store_true",
        help="open a root trace span for EVERY enqueued pod (pods whose "
             "submitter propagated a trace context are always traced); "
             "spans land in the in-process ring (/debug/trace/<pod>) "
             "and any KOORD_TRACE_JSONL exporter")
    parser.add_argument(
        "--slo-sample-interval-seconds", type=float, default=0.0,
        help="background SLO burn-rate sampling cadence: every interval "
             "the registry metrics are sampled into the in-process "
             "time-series and the SLO specs' fast/slow burn windows are "
             "evaluated (breach -> alert counter + flight-recorder "
             "dump).  0 (default) = on-demand only: each GET /debug/slo "
             "request samples + evaluates; production sidecars should "
             "set e.g. 5")
    parser.add_argument(
        "--slo-latency-threshold-seconds", type=float, default=0.2,
        help="the scheduling-latency SLO's per-observation bound (the "
             "paper's p99 target: 0.2)")
    parser.add_argument(
        "--flight-ring-size", type=int, default=256,
        help="round flight-recorder ring capacity: a long soak's report "
             "joins trend verdicts to rounds, so size this to cover the "
             "report window (round_flight_overwritten_total counts the "
             "records a too-small ring silently evicts)")
    parser.add_argument(
        "--trend-window-seconds", type=float, default=1800.0,
        help="the /debug/steady trend engine's default evaluation "
             "window: slopes over the self-telemetry/queue-depth series "
             "are fitted over this much history and classified "
             "steady/drifting/leaking (?window=N overrides per request)")
    parser.add_argument(
        "--tenants", type=int, default=1,
        help="multiplex N clusters onto this scheduler's mesh "
             "(scheduler/tenancy.py): each tenant gets its own "
             "snapshot/quota/degraded state and sync binding (extra "
             "tenants listen at <listen-socket>.<tenant>), all sharing "
             "ONE compiled solver; rounds run as pipelined (or "
             "tenant-axis batched) cycles with weighted-fair admission")
    parser.add_argument(
        "--tenant-weights", default="",
        help="comma-separated weighted-fair admission weights, one per "
             "tenant (short lists pad with 1.0)")
    parser.add_argument(
        "--tenant-cycle-pod-budget", type=int, default=4096,
        help="pods admitted per multi-tenant cycle across all tenants "
             "(the weighted deficit-round-robin quantum)")
    parser.add_argument(
        "--quality-mode", choices=("off", "lp", "auto"), default="off",
        help="solve-quality mode (quality/lp_pack): off = the greedy "
             "top-k path exactly; lp = every eligible round solves "
             "with the LP-relaxation packing engine (dual-price "
             "ascent + iterative masked rounding, feasibility-checked "
             "by the greedy path's own capacity/quota kernels); auto "
             "= escalate only rounds whose result leaves min-over-dims "
             "capacity_slack_fraction above --quality-slack-threshold. "
             "Gangs with topology requirements additionally plan "
             "through the rank-aware minimal-diameter planner "
             "(quality/topo_gang) whenever the mode is not off")
    parser.add_argument(
        "--quality-slack-threshold", type=float, default=0.3,
        help="auto-mode escalation bar: when the MINIMUM "
             "capacity_slack_fraction over provisioned dims left by a "
             "round exceeds this, the next round solves on the "
             "quality path (every dimension must have headroom worth "
             "winning back)")
    parser.add_argument(
        "--forecast-mode", choices=("off", "admit", "full"), default="off",
        help="forecast plane (forecast/): off = today's solve exactly "
             "(bit-identical acceptance decisions and quota charges); "
             "admit = the forecast-headroom reserve — the predicted LS "
             "peak growth not yet visible in observed usage — charges "
             "into every round's filter/score accounting; full = "
             "admission plus the predictive-colocation and "
             "proactive-rebalance drivers where the deployment shell "
             "wires them.  Any mode other than off attaches a "
             "ForecastPlane fed from the round prelude and serves "
             "/debug/forecast")
    parser.add_argument(
        "--forecast-horizon-seconds", type=float, default=120.0,
        help="the forecast plane's base prediction horizon; stretches "
             "with the diurnal trend slope (plane.horizon_for) up to "
             "4x")
    parser.add_argument(
        "--enable-profile-endpoint", action="store_true",
        help="arm /debug/profile?seconds=N (on-demand jax.profiler "
             "capture); OFF by default — the endpoint answers 403 "
             "until an operator enables it here")
    parser.add_argument(
        "--profile-dir", default="",
        help="directory for /debug/profile trace captures (default: a "
             "fresh temp dir per capture)")
    parser.add_argument(
        "--checkpoint-path", default="",
        help="warm-restart checkpoint file (docs/robustness.md): "
             "restored on boot when present, rewritten every "
             "--checkpoint-interval-seconds and once on stop; empty "
             "disables checkpointing (behavior is bit-identical either "
             "way — the checkpoint is host state + the replay cursor, "
             "never solver state)")
    parser.add_argument(
        "--checkpoint-interval-seconds", type=float, default=30.0,
        help="cadence of the background checkpoint writer")
    return parser


def main_koord_scheduler(argv: list[str],
                         lease_store=None, preempt_fn=None) -> Assembled:
    """``preempt_fn(victim, preemptor)`` is the deployment shell's
    eviction transport; required when preemption is enabled (the flag or
    the config file), because nominating victims without evicting them
    frees accounting for pods that keep running."""
    from koordinator_tpu.features import SCHEDULER_GATES
    from koordinator_tpu.scheduler import ClusterSnapshot, Scheduler
    from koordinator_tpu.scheduler.explanation import (
        ExplanationStore,
        WorkloadAuditor,
    )

    from koordinator_tpu.scheduler.cpu_manager import CPUManager
    from koordinator_tpu.scheduler.device_manager import DeviceManager

    args = build_scheduler_parser().parse_args(argv)
    apply_feature_gates(args.feature_gates, SCHEDULER_GATES)
    if args.no_timeline:
        from koordinator_tpu import timeline

        timeline.RECORDER.set_enabled(False)
    if args.no_journey:
        from koordinator_tpu import journey

        journey.LEDGER.set_enabled(False)
    from koordinator_tpu.cmd.component_config import (
        SchedulerComponentConfig,
        load_scheduler_config,
    )

    # always go through the component config so every default (gang
    # timeout, scoring) has exactly one home — the dataclass
    component_config = (load_scheduler_config(args.config) if args.config
                        else SchedulerComponentConfig())
    snapshot = ClusterSnapshot(capacity=args.node_capacity)
    elector = build_elector(args, lease_store)
    # precedence: an explicit CLI flag wins over the config file, which
    # wins over built-in defaults (matching the reference's flag
    # layering).  Tri-state is preserved: an explicit `enablePreemption:
    # false` in the config must reach the Scheduler as False, not
    # collapse to None (which would auto-enable when preempt_fn is
    # wired).
    enable_preemption = (True if args.enable_preemption
                         else component_config.enable_preemption)
    if enable_preemption and preempt_fn is None:
        raise SystemExit(
            "preemption enabled (flag or config) but no eviction "
            "transport wired: pass preempt_fn to main_koord_scheduler — "
            "nominating victims without evicting them double-books nodes")
    sched_kwargs = dict(
        config=component_config.scoring,
        gang_passes=args.gang_passes,
        gang_default_timeout_sec=component_config.gang_default_timeout_sec,
        batch_solver_threshold=args.batch_solver_threshold,
        enable_preemption=enable_preemption,
        preempt_fn=preempt_fn,
        elector=elector,
        staleness_threshold_sec=(args.staleness_threshold_seconds
                                 if args.staleness_threshold_seconds > 0
                                 else None),
        trace_pods=args.trace_pods,
        explain=not args.no_explain,
        flight_ring_size=args.flight_ring_size,
        quality_mode=args.quality_mode,
        quality_slack_threshold=args.quality_slack_threshold,
        forecast_mode=args.forecast_mode,
    )
    tenant_front = None
    if args.tenants > 1:
        # multi-tenant assembly (ISSUE 11): one TenantScheduler front
        # multiplexes N per-tenant Schedulers — each with its OWN
        # explanation store / auditor / fine-grained managers — onto one
        # shared SolverKit.  Leadership gates the WHOLE cycle at the
        # front (a standby must not decide for any tenant), so the
        # per-tenant schedulers run ungated.
        from koordinator_tpu.scheduler.tenancy import (
            TenantScheduler,
            TenantSpec,
        )

        # positions matter: an empty item (trailing/doubled comma) must
        # fail LOUDLY, not silently shift later tenants' weights; short
        # lists pad with 1.0, longer-than---tenants lists are an error
        weights = ([float(w) for w in args.tenant_weights.split(",")]
                   if args.tenant_weights.strip() else [])
        if len(weights) > args.tenants:
            raise SystemExit(
                f"--tenant-weights names {len(weights)} weights for "
                f"--tenants {args.tenants}")
        tenant_front = TenantScheduler(
            cycle_pod_budget=args.tenant_cycle_pod_budget)
        tenant_front.elector = elector
        for i in range(args.tenants):
            kw = dict(sched_kwargs)
            kw.update(elector=None,
                      explanations=ExplanationStore(),
                      auditor=WorkloadAuditor(),
                      cpu_manager=CPUManager(),
                      device_manager=DeviceManager())
            if i == 0:
                kw["snapshot"] = snapshot
            tenant_front.add_tenant(
                TenantSpec(name=f"t{i}",
                           weight=(weights[i] if i < len(weights)
                                   else 1.0),
                           node_capacity=args.node_capacity), **kw)
        scheduler = tenant_front.primary
    else:
        scheduler = Scheduler(
            snapshot,
            explanations=ExplanationStore(),
            auditor=WorkloadAuditor(),
            cpu_manager=CPUManager(),
            device_manager=DeviceManager(),
            **sched_kwargs,
        )
    # -- self-observability: SLO burn-rate engine + solver introspection
    from koordinator_tpu import journey as _journey
    from koordinator_tpu.ops.introspection import ProfilerCapture
    from koordinator_tpu.slo_monitor import (
        SloMonitor,
        default_specs,
        tenant_slo_specs,
    )
    from koordinator_tpu.trend import TrendEngine

    # self-telemetry rides the SLO sampler (every sweep — background OR
    # on-demand /debug/slo//debug/steady — refreshes RSS/fds/threads
    # first), so the scheduler needs no second sampling thread
    telemetry = build_self_telemetry(args, "koord-scheduler")
    slo_specs = default_specs(
        latency_threshold_s=args.slo_latency_threshold_seconds,
        staleness_threshold_s=(args.staleness_threshold_seconds
                               if args.staleness_threshold_seconds > 0
                               else 30.0))
    if tenant_front is not None:
        # per-tenant p99 specs slice the shared latency histogram by
        # its {tenant=...} label, so one tenant's breach pages AS that
        # tenant instead of diluting into the global p99
        slo_specs += tenant_slo_specs(
            [t.name for t in tenant_front.tenants()],
            latency_threshold_s=args.slo_latency_threshold_seconds)
    slo_monitor = SloMonitor(
        specs=slo_specs,
        sample_interval_s=(args.slo_sample_interval_seconds
                           if args.slo_sample_interval_seconds > 0 else 5.0),
        # a fast-burn breach dumps the latest round's flight record with
        # the offending SLO named — the "why" artifact next to the alert
        on_breach=lambda spec, doc: scheduler.flight_recorder.dump_now(
            f"slo:{spec.name}"),
        # the journey ledger's quantile gauges refresh in the SAME sweep
        # that evaluates the SLO windows, so burn rates compute from true
        # per-pod e2e quantiles instead of round-bucket interpolation
        pre_sample=[telemetry.sample, _journey.LEDGER.publish_gauges],
    )
    scheduler.slo_monitor = slo_monitor
    # the trend engine shares the SLO monitor's sample cache: one
    # sampling pass feeds burn rates AND the long-horizon leak watch
    scheduler.trend_engine = TrendEngine(
        slo_monitor.cache, window_s=args.trend_window_seconds)
    if tenant_front is not None:
        tenant_front.slo_monitor = slo_monitor
        tenant_front.trend_engine = scheduler.trend_engine
    if args.slo_sample_interval_seconds > 0:
        slo_monitor.start()   # stopped via Assembled.stop -> Scheduler.stop
    if args.enable_profile_endpoint:
        scheduler.profile_capture = ProfilerCapture(
            enabled=True, out_dir=args.profile_dir or None)
    if args.forecast_mode != "off":
        # the forecast plane (ISSUE 15): fed from the round prelude,
        # pinned under the solver mesh's node sharding when active, and
        # served at /debug/forecast on both surfaces.  Multi-tenant
        # assemblies attach one plane per tenant — each tenant's usage
        # history is its own signal.
        from koordinator_tpu.forecast.plane import ForecastPlane

        planes = (
            [(t.scheduler, t.scheduler.snapshot)
             for t in tenant_front.tenants()]
            if tenant_front is not None else [(scheduler, snapshot)])
        for sched, snap in planes:
            sched.attach_forecast_plane(ForecastPlane(
                snap.capacity,
                base_horizon_s=args.forecast_horizon_seconds,
                mesh=(sched.mesh if snap.solver_sharding_active
                      else None)))
    server = None
    sync_service = None
    if args.listen_socket or args.http_port is not None:
        # the SIDECAR assembly: state enters over STATE_PUSH frames or
        # POST /v1/state, lands in the sync service, and applies to the
        # scheduler synchronously through an in-process binding — the
        # same commit->binding path remote sync clients ride, minus the
        # socket loop.  Remote replicas can still HELLO the same service
        # for snapshots/deltas.
        from koordinator_tpu.transport.deltasync import (
            SchedulerBinding,
            StateSyncService,
        )

        sync_service = StateSyncService()
        sync_service.attach_binding(SchedulerBinding(scheduler))
        if tenant_front is not None:
            # per-tenant sync bindings: every EXTRA tenant gets its own
            # StateSyncService (its informer feed, its staleness clock —
            # isolation is per feed) served on its own socket below;
            # the primary tenant rides the main socket/gateway
            tenant_front.tenant_syncs = {}
            for t in tenant_front.tenants()[1:]:
                svc = StateSyncService()
                svc.attach_binding(SchedulerBinding(t.scheduler))
                tenant_front.tenant_syncs[t.name] = svc
    # the lease surface (frames + HTTP) must share the elector's store:
    # a private store would let a remote contender "acquire" a lease the
    # local elector also holds in the real one — split-brain
    shared_lease_store = (elector.store if elector is not None
                          else lease_store)
    if shared_lease_store is None:
        from koordinator_tpu.ha import InMemoryLeaseStore

        shared_lease_store = InMemoryLeaseStore()
    if args.listen_socket:
        from koordinator_tpu.ha import LeaseService
        from koordinator_tpu.transport import RpcServer
        from koordinator_tpu.transport.services import SolveService

        server = RpcServer(args.listen_socket, service="scheduler")
        # a multi-tenant assembly solves CYCLES: the solve frame drives
        # the front-end (weighted admission + pipelined/batched rounds
        # across every tenant), not one tenant's round
        SolveService(tenant_front if tenant_front is not None
                     else scheduler).attach(server)
        sync_service.attach(server)
        LeaseService(store=shared_lease_store).attach(server)
        server.start()
        if tenant_front is not None:
            for name, svc in tenant_front.tenant_syncs.items():
                extra = RpcServer(f"{args.listen_socket}.{name}",
                                  service="scheduler")
                svc.attach(extra)
                extra.start()
                tenant_front.closers.append(extra.stop)
    gateway = None
    if args.http_port is not None:
        from koordinator_tpu.transport.http_gateway import HttpGateway

        gateway = HttpGateway(port=args.http_port, scheduler=scheduler,
                              state_sync=sync_service,
                              lease_store=shared_lease_store)
        gateway.start()
    checkpointer = None
    if args.checkpoint_path:
        import os as _os

        from koordinator_tpu.drills import checkpoint as _ckpt

        if _os.path.exists(args.checkpoint_path):
            # warm restart: restore the host-side cut before any state
            # arrives, so informer replay / remote deltas land on the
            # restored generations instead of re-placing the world
            _ckpt.restore(args.checkpoint_path, scheduler)
        checkpointer = _ckpt.CheckpointWriter(
            args.checkpoint_path, scheduler,
            interval_s=args.checkpoint_interval_seconds).start()
    return Assembled(name="koord-scheduler", args=args,
                     component=(tenant_front if tenant_front is not None
                                else scheduler),
                     elector=elector, server=server,
                     gateway=gateway, state_sync=sync_service,
                     component_config=component_config,
                     telemetry=telemetry, checkpointer=checkpointer)


# ---- koord-manager ---------------------------------------------------------

def build_manager_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-manager")
    add_common_flags(parser)
    add_leader_election_flags(parser, default_lease="koord-manager")
    parser.add_argument("--sync-period", type=float, default=0.0)
    parser.add_argument("--config-namespace", default="koordinator-system")
    parser.add_argument("--slo-config-name", default="slo-controller-config")
    parser.add_argument(
        "--sloconfig-file", default="",
        help="bootstrap the slo-controller-config ConfigMap DATA from a "
             "YAML file (same keys: colocation-config, "
             "resource-threshold-config, ...) until the watched CM "
             "arrives; rejected loudly when invalid")
    parser.add_argument(
        "--scheduler-sidecar-addr", default="",
        help="scheduler sidecar socket: watch node state + koordlet "
             "usage reports from its sync service and push the "
             "noderesource reconcile's batch/mid allocatable back as "
             "node_allocatable events (the §3.2 colocation loop's "
             "manager leg in wire form)")
    parser.add_argument(
        "--http-port", type=int, default=None,
        help="serve the HTTP/JSON gateway (/healthz, /metrics over all "
             "component registries) — the manager's scrape surface; "
             "omit to disable")
    return parser


def main_koord_manager(argv: list[str], lease_store=None) -> Assembled:
    import types

    from koordinator_tpu.features import SCHEDULER_GATES  # manager+scheduler
    from koordinator_tpu.manager.nodemetric import NodeMetricController
    from koordinator_tpu.manager.nodeslo import NodeSLOController
    from koordinator_tpu.manager.noderesource_controller import (
        NodeResourceController,
    )
    from koordinator_tpu.manager.quota_profile import QuotaProfileController
    from koordinator_tpu.manager.recommendation import (
        RecommendationController,
    )
    from koordinator_tpu.manager.node_webhook import (
        NodeMutatingWebhook,
        NodeValidatingWebhook,
    )
    from koordinator_tpu.manager.quota_webhook import QuotaTopologyValidator
    from koordinator_tpu.manager.webhook import (
        MultiQuotaTreeAffinity,
        PodMutatingWebhook,
        PodValidatingWebhook,
    )

    args = build_manager_parser().parse_args(argv)
    apply_feature_gates(args.feature_gates, SCHEDULER_GATES)
    from koordinator_tpu.manager import sloconfig

    config_data: dict[str, str] = {}
    colocation = None
    if args.sloconfig_file:
        try:
            config_data = sloconfig.load_config_file(args.sloconfig_file)
        except ValueError as e:
            raise SystemExit(str(e)) from e
        # only override the controller's enable-by-default colocation
        # config when the file actually carries that key — bootstrapping
        # an unrelated key must not silently disable colocation
        if sloconfig.KEY_COLOCATION in config_data:
            colocation = sloconfig.parse_colocation_config(config_data)
    component = types.SimpleNamespace(
        nodemetric=NodeMetricController(),
        nodeslo=NodeSLOController(config_data=config_data or None),
        noderesource=NodeResourceController(config=colocation),
        pod_mutating=PodMutatingWebhook(),
        pod_validating=PodValidatingWebhook(),
        node_mutating=NodeMutatingWebhook(),
        node_validating=NodeValidatingWebhook(),
        quota_validating=QuotaTopologyValidator(
            enable_update_resource_key=SCHEDULER_GATES.enabled(
                "ElasticQuotaEnableUpdateResourceKey"),
            guarantee_usage=SCHEDULER_GATES.enabled(
                "ElasticQuotaGuaranteeUsage"),
        ),
        quota_profile=QuotaProfileController(),
        recommendation=RecommendationController(),
        # gated like the reference's multi-quota-tree webhook registration
        multi_tree_affinity=(MultiQuotaTreeAffinity()
                             if SCHEDULER_GATES.enabled("MultiQuotaTree")
                             else None),
    )

    def update_sloconfig(new_data) -> list[str]:
        """The watched-CM seam: when the live slo-controller-config CM
        changes, the deployment shell calls this — NodeSLOs re-render
        and the colocation math follows, so a --sloconfig-file bootstrap
        really is only 'until the watched CM arrives'."""
        errors = sloconfig.validate_config_data(new_data)
        if errors:
            return []   # the reference keeps the last good config
        changed = component.nodeslo.update_config(new_data)
        if sloconfig.KEY_COLOCATION in new_data:
            component.noderesource.config = (
                sloconfig.parse_colocation_config(new_data))
        return changed

    component.update_sloconfig = update_sloconfig

    if args.scheduler_sidecar_addr:
        import numpy as _np

        from koordinator_tpu.manager.colocation_loop import (
            ColocationLoop,
            ManagerSyncBinding,
        )
        from koordinator_tpu.transport import StateSyncClient
        from koordinator_tpu.transport.wire import FrameType

        binding = ManagerSyncBinding()
        sync = StateSyncClient(binding)

        def bootstrap_watch(client):
            # bind_client first: a detected rv gap on THIS stream can
            # then self-heal by severing it (the next tick's ensure
            # re-dials and lands back here to re-HELLO from last_rv)
            sync.bind_client(client)
            sync.bootstrap(client)

        # lazy like the koordlet's reporters: a manager deployed before
        # the scheduler binary must not crash at assembly — the first
        # tick's ensure_fn dials (and re-bootstraps the watch from
        # last_rv after any reconnect)
        sidecar = ReconnectingSidecarClient(
            args.scheduler_sidecar_addr, on_push=sync.on_push,
            on_connect=bootstrap_watch)

        def push_allocatable(name: str, allocatable) -> None:
            sidecar.call(
                FrameType.STATE_PUSH,
                {"kind": "node_allocatable", "name": name},
                {"allocatable": _np.asarray(allocatable, _np.int32)})

        component.sync_binding = binding
        component.sync = sync
        component.sync_client = sidecar
        component.colocation_loop = ColocationLoop(
            component.noderesource, binding, push_allocatable,
            ensure_fn=sidecar.ensure)

        def stop() -> None:
            component.colocation_loop.stop()
            sidecar.close()

        component.stop = stop

    gateway = None
    if args.http_port is not None:
        from koordinator_tpu.transport.http_gateway import HttpGateway

        gateway = HttpGateway(port=args.http_port)
        gateway.start()
    return Assembled(name="koord-manager", args=args, component=component,
                     elector=build_elector(args, lease_store),
                     gateway=gateway,
                     telemetry=build_self_telemetry(args, "koord-manager"))


# ---- koord-descheduler -----------------------------------------------------

#: upstream ports that can't assemble from flags alone (they need a nodes_fn)
_NEEDS_NODES_FN = {
    "RemovePodsViolatingNodeAffinity",
    "RemovePodsViolatingNodeTaints",
    "RemovePodsViolatingTopologySpreadConstraint",
    "HighNodeUtilization",
}


def _flag_selectable_descheduler_plugins() -> list[str]:
    """Lower-cased names accepted by --deschedule-plugins, derived from the
    upstream.PLUGINS registry so the help text can never drift from what the
    selector below actually accepts (unknown names are a hard SystemExit)."""
    from koordinator_tpu.descheduler import upstream

    return [name.lower() for name in upstream.PLUGINS
            if name not in _NEEDS_NODES_FN]


def build_descheduler_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-descheduler")
    add_common_flags(parser)
    add_leader_election_flags(parser, default_lease="koord-descheduler")
    parser.add_argument("--descheduling-interval-seconds", type=float,
                        default=120.0)
    parser.add_argument("--max-evictions-per-round", type=int, default=None,
                        help="0 = unlimited; omit to defer to the config")
    parser.add_argument("--evict-system-critical", action="store_true")
    parser.add_argument("--evict-local-storage-pods", action="store_true")
    parser.add_argument("--priority-threshold", type=int, default=None)
    parser.add_argument(
        "--deschedule-plugins", default="",
        help="comma list of DESCHEDULE plugins for the default profile: "
             + ",".join(sorted(_flag_selectable_descheduler_plugins())))
    parser.add_argument("--pod-lifetime-max-seconds", type=float,
                        default=None)
    parser.add_argument("--pod-restart-threshold", type=int, default=None)
    parser.add_argument(
        "--config", default="",
        help="DeschedulerConfiguration YAML with profile plugin "
             "enablement + per-plugin args (LowNodeLoad thresholds, "
             "MigrationController limits, DefaultEvictor, ...) — the "
             "reference's versioned component config; explicit CLI "
             "flags override")
    return parser


def main_koord_descheduler(argv: list[str], pods_fn=None,
                           lease_store=None) -> Assembled:
    from koordinator_tpu.descheduler.framework import (
        Descheduler,
        Evictor,
        EvictorFilter,
        Profile,
    )

    from koordinator_tpu.cmd.descheduler_config import (
        DeschedulerComponentConfig,
        load_descheduler_config,
    )

    args = build_descheduler_parser().parse_args(argv)
    component = (load_descheduler_config(args.config) if args.config
                 else DeschedulerComponentConfig())
    # precedence: explicit CLI flag > config file > built-in default
    # (booleans or-combine; None-defaulted flags defer to the config)
    priority_threshold = (args.priority_threshold
                          if args.priority_threshold is not None
                          else component.priority_threshold)
    max_evictions = (args.max_evictions_per_round
                     if args.max_evictions_per_round is not None
                     else component.max_evictions_per_round)
    lifetime_max = (args.pod_lifetime_max_seconds
                    if args.pod_lifetime_max_seconds is not None
                    else component.pod_lifetime_max_seconds
                    or 7 * 24 * 3600.0)
    restart_threshold = (args.pod_restart_threshold
                         if args.pod_restart_threshold is not None
                         else component.pod_restart_threshold or 100)
    evictor_filter = EvictorFilter(
        evict_system_critical=(args.evict_system_critical
                               or component.evict_system_critical),
        evict_local_storage=(args.evict_local_storage_pods
                             or component.evict_local_storage_pods),
        priority_threshold=priority_threshold,
    )
    # upstream-port plugins selectable by name, derived from the single
    # upstream.PLUGINS registry (the reference's profile pluginConfig).
    # Plugins needing a nodes_fn can't assemble from flags alone and are
    # excluded; per-plugin required kwargs come from the flag table.
    from koordinator_tpu.descheduler import upstream

    flag_kwargs = {
        "PodLifeTime": lambda: {"max_seconds": lifetime_max},
        "RemovePodsHavingTooManyRestarts": lambda: {
            "pod_restart_threshold": restart_threshold},
    }
    available = {
        name.lower(): (cls, flag_kwargs.get(name, dict))
        for name, cls in upstream.PLUGINS.items()
        if name not in _NEEDS_NODES_FN
    }
    deschedule_plugins = []
    balance_plugins = []
    #: args-in-the-file, data-callables-from-the-shell plugins: the
    #: loader validates their args (exposed via Assembled.component_
    #: config), but only the embedding shell can construct them
    shell_wired = {"lownodeload", "fragmentationaware"} | {
        n.lower() for n in _NEEDS_NODES_FN}
    requested: list[tuple[str, bool]] = []   # (name, from_config)
    seen: set[str] = set()
    for raw, from_config in (
            [(r.strip(), False)
             for r in args.deschedule_plugins.split(",") if r.strip()]
            + [(n, True) for n in (component.deschedule_enabled
                                   + component.balance_enabled)]):
        if raw.lower() in seen:
            continue   # duplicates must not instantiate a plugin twice
        seen.add(raw.lower())
        requested.append((raw, from_config))
    for raw, from_config in requested:
        name = raw.lower()
        if name in shell_wired:
            if from_config:
                continue   # shell reads asm.component_config and wires it
            raise SystemExit(
                f"plugin {raw} needs data callables the CLI cannot "
                f"provide; the embedding shell must wire it (its config "
                f"args load via --config)")
        entry = available.get(name)
        if entry is None:
            raise SystemExit(f"unknown deschedule plugin: {raw}")
        cls, kwargs = entry
        plugin = cls(**kwargs())
        # upstream ports come in both kinds; route by interface
        if hasattr(plugin, "deschedule"):
            deschedule_plugins.append(plugin)
        else:
            balance_plugins.append(plugin)
    profile = Profile(
        name="default",
        deschedule_plugins=deschedule_plugins,
        balance_plugins=balance_plugins,
        evictor_filter=evictor_filter,
        evictor=Evictor(),
        max_evictions_per_round=max_evictions,
    )
    elector = build_elector(args, lease_store)
    descheduler = Descheduler(
        [profile], pods_fn=pods_fn or (lambda: []),
        interval_seconds=args.descheduling_interval_seconds,
        elector=elector,
    )
    return Assembled(name="koord-descheduler", args=args,
                     component=descheduler, elector=elector,
                     component_config=component,
                     telemetry=build_self_telemetry(
                         args, "koord-descheduler"))


# ---- koord-runtime-proxy ---------------------------------------------------

def build_runtime_proxy_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-runtime-proxy")
    add_common_flags(parser)
    parser.add_argument("--remote-runtime-service-endpoint",
                        default="/var/run/containerd/containerd.sock")
    parser.add_argument("--koord-runtime-proxy-endpoint",
                        default="/var/run/koord-runtimeproxy/runtimeproxy.sock")
    parser.add_argument("--hook-server-socket", default="",
                        help="serve the hook dispatch over this unix socket")
    return parser


def main_koord_runtime_proxy(argv: list[str],
                             backend: dict | None = None) -> Assembled:
    from koordinator_tpu.runtimeproxy import CRIProxy, Dispatcher, FailoverStore

    args = build_runtime_proxy_parser().parse_args(argv)
    dispatcher = Dispatcher()
    store = FailoverStore()
    proxy = CRIProxy(dispatcher, store, backend or {})
    server = None
    if args.hook_server_socket:
        from koordinator_tpu.transport import RpcServer
        from koordinator_tpu.transport.services import HookService

        server = RpcServer(args.hook_server_socket,
                           service="runtime-proxy")
        HookService(dispatcher).attach(server)
        server.start()
    return Assembled(name="koord-runtime-proxy", args=args, component=proxy,
                     server=server,
                     telemetry=build_self_telemetry(
                         args, "koord-runtime-proxy"))


# ---- koord-device-daemon ---------------------------------------------------

def build_device_daemon_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="koord-device-daemon")
    add_common_flags(parser)
    parser.add_argument("--node-name", required=True)
    parser.add_argument("--sys-root-dir", default="/sys")
    parser.add_argument("--report-interval-seconds", type=float, default=30.0)
    return parser


def main_koord_device_daemon(argv: list[str]) -> Assembled:
    from koordinator_tpu.device_daemon import DeviceDaemon

    args = build_device_daemon_parser().parse_args(argv)
    daemon = DeviceDaemon(node_name=args.node_name,
                          sys_root=args.sys_root_dir)
    return Assembled(name="koord-device-daemon", args=args, component=daemon,
                     telemetry=build_self_telemetry(
                         args, "koord-device-daemon"))


MAINS = {
    "koordlet": main_koordlet,
    "koord-scheduler": main_koord_scheduler,
    "koord-manager": main_koord_manager,
    "koord-descheduler": main_koord_descheduler,
    "koord-runtime-proxy": main_koord_runtime_proxy,
    "koord-device-daemon": main_koord_device_daemon,
}
