"""Per-binary CLI entry points.

The reference ships six binaries, each a cobra/pflag command
(SURVEY.md §2.1: koord-scheduler, koord-manager, koordlet,
koord-descheduler, koord-runtime-proxy, koord-device-daemon) sharing a
flag vocabulary: ``--feature-gates A=true,B=false`` (k8s component-base),
leader-election flags (``cmd/koord-manager/main.go``), address/interval
knobs, and component-specific options. This package is that layer:
``koordinator_tpu.cmd.<binary>`` exposes ``build_parser()`` and
``main(argv)``; ``main`` assembles the component graph from flags and
returns it (callers/tests drive it; pass ``--run`` to loop).
"""

from __future__ import annotations

import argparse

from koordinator_tpu.features import FeatureGates
from koordinator_tpu.ha import InMemoryLeaseStore, LeaderElector


def add_common_flags(parser: argparse.ArgumentParser) -> None:
    """Flags every binary shares (component-base + controller-runtime)."""
    parser.add_argument(
        "--feature-gates", default="", metavar="A=true,B=false",
        help="comma-separated feature gate overrides")
    parser.add_argument(
        "--metrics-addr", default=":8080",
        help="prometheus metrics bind address")
    parser.add_argument(
        "--enable-pprof", action="store_true",
        help="enable the profiling endpoint")
    parser.add_argument(
        "--v", type=int, default=2, help="log verbosity (klog -v)")
    parser.add_argument(
        "--self-telemetry-interval-seconds", type=float, default=0.0,
        help="background cadence for the process self-telemetry gauges "
             "(RSS, fds, threads, alloc blocks, gc — the trend engine's "
             "leak-watch inputs, labeled binary=<name>); 0 disables the "
             "thread (the scheduler still refreshes them on every SLO "
             "sample sweep)")


def build_self_telemetry(args: argparse.Namespace, binary: str):
    """A started SelfTelemetry when the cadence flag asks for one, else
    an unstarted instance (callers may still hook .sample) — ONE wiring
    shared by every binary main."""
    from koordinator_tpu.selftelemetry import SelfTelemetry

    telemetry = SelfTelemetry(binary)
    interval = getattr(args, "self_telemetry_interval_seconds", 0.0)
    if interval and interval > 0:
        telemetry.start(interval)
    return telemetry


def add_leader_election_flags(parser: argparse.ArgumentParser,
                              default_lease: str) -> None:
    """cmd/koord-manager/main.go:66-73 equivalents."""
    parser.add_argument(
        "--enable-leader-election", dest="enable_leader_election",
        action="store_true", default=True)
    parser.add_argument(
        "--disable-leader-election", dest="enable_leader_election",
        action="store_false")
    parser.add_argument("--leader-election-namespace",
                        default="koordinator-system")
    parser.add_argument("--leader-elect-lease-name", default=default_lease)
    parser.add_argument("--leader-elect-lease-duration", type=float,
                        default=15.0)
    parser.add_argument("--leader-elect-retry-period", type=float,
                        default=2.0)
    parser.add_argument("--identity", default="",
                        help="holder identity (defaults to hostname+pid)")


def apply_feature_gates(spec: str, gates: FeatureGates) -> None:
    if spec:
        gates.set_from_spec(spec)


def build_elector(args: argparse.Namespace,
                  store: InMemoryLeaseStore | None = None
                  ) -> LeaderElector | None:
    """None when disabled (leader_gated treats None as always-leader)."""
    if not getattr(args, "enable_leader_election", False):
        return None
    import os
    import socket

    identity = args.identity or f"{socket.gethostname()}-{os.getpid()}"
    return LeaderElector(
        store if store is not None else InMemoryLeaseStore(),
        lease_name=(f"{args.leader_election_namespace}/"
                    f"{args.leader_elect_lease_name}"),
        identity=identity,
        lease_duration=args.leader_elect_lease_duration,
        retry_period=args.leader_elect_retry_period,
    )
