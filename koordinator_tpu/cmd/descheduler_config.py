"""Versioned component config for the descheduler.

The reference's descheduler loads a DeschedulerConfiguration with
per-profile plugin enablement and plugin args
(``pkg/descheduler/apis/config/types.go`` + ``types_loadaware.go`` +
``v1alpha2/`` defaulting + ``validation/``); flags cannot express
per-resource thresholds.  ``koord-descheduler --config FILE`` loads

    apiVersion: descheduler/v1alpha2
    kind: DeschedulerConfiguration
    profiles:
    - name: koord-descheduler
      plugins:
        deschedule:
          enabled: [PodLifeTime, RemovePodsHavingTooManyRestarts]
      pluginConfig:
      - name: LowNodeLoad
        args:
          lowThresholds: {cpu: 40, memory: 50}
          highThresholds: {cpu: 70, memory: 85}
          useDeviationThresholds: false
          anomalyCondition: {consecutiveAbnormalities: 5}
      - name: PodLifeTime
        args: {maxPodLifeTimeSeconds: 86400}
      - name: RemovePodsHavingTooManyRestarts
        args: {podRestartThreshold: 50}
      - name: MigrationController
        args:
          maxMigratingPerNode: 2
          maxMigratingPerNamespace: 10
          maxMigratingPerWorkload: "10%"
          maxUnavailablePerWorkload: 2
      - name: DefaultEvictor
        args: {priorityThreshold: 8000, evictLocalStoragePods: true,
               maxNoOfPodsToEvictPerNode: 5}

with the same loud-validation posture as the scheduler's loader
(cmd/component_config.py): unknown names/keys/resources and
out-of-range values are startup errors.  Data-dependent plugins
(LowNodeLoad, FragmentationAware) get their ARGS from the file; their
state/usage callables still come from the embedding shell, like the
reference's informer wiring.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from koordinator_tpu.cmd.component_config import (
    ComponentConfigError,
    _check_keys,
    _int_vector,
)
from koordinator_tpu.descheduler.lownodeload import LowNodeLoadArgs
from koordinator_tpu.descheduler.migration import ArbitrationLimits


@dataclasses.dataclass
class DeschedulerComponentConfig:
    #: plugin names per extension point (framework Profile lists)
    deschedule_enabled: list[str] = dataclasses.field(default_factory=list)
    balance_enabled: list[str] = dataclasses.field(default_factory=list)
    lownodeload: LowNodeLoadArgs = dataclasses.field(
        default_factory=LowNodeLoadArgs.default)
    pod_lifetime_max_seconds: float | None = None
    pod_restart_threshold: int | None = None
    migration_limits: ArbitrationLimits = dataclasses.field(
        default_factory=ArbitrationLimits)
    # DefaultEvictor args
    priority_threshold: int | None = None
    evict_system_critical: bool = False
    evict_local_storage_pods: bool = False
    max_evictions_per_round: int = 0


def _positive_number(value, where: str) -> float:
    if not isinstance(value, (int, float)) or isinstance(value, bool) \
            or value <= 0:
        raise ComponentConfigError(
            f"{where}: expected a positive number, got {value!r}")
    return float(value)


def _positive_int(value, where: str) -> int:
    """Loud about fractional values — int() truncation would silently
    keep a different number than the file says."""
    if not isinstance(value, int) or isinstance(value, bool) or value <= 0:
        raise ComponentConfigError(
            f"{where}: expected a positive integer, got {value!r}")
    return value


def _int_or_percent(value, where: str):
    if value is None:
        return None
    if isinstance(value, int) and not isinstance(value, bool):
        if value < 0:
            raise ComponentConfigError(f"{where}: negative limit {value}")
        return value
    if isinstance(value, str) and value.endswith("%"):
        try:
            pct = int(value[:-1])
        except ValueError:
            raise ComponentConfigError(
                f"{where}: bad percent {value!r}") from None
        if not 0 <= pct <= 100:
            raise ComponentConfigError(
                f"{where}: percent {value!r} outside [0%, 100%]")
        return value
    raise ComponentConfigError(
        f"{where}: expected an int or 'N%', got {value!r}")


def _apply_lownodeload(out: DeschedulerComponentConfig,
                       args: dict) -> None:
    _check_keys(args, {"lowThresholds", "highThresholds",
                       "useDeviationThresholds", "anomalyCondition"},
                "LowNodeLoad")
    lnl = out.lownodeload
    if "lowThresholds" in args:
        lnl = lnl.replace(low_thresholds=_int_vector(
            jnp.full_like(lnl.low_thresholds, -1), args["lowThresholds"],
            "LowNodeLoad.lowThresholds", hi=100))
    if "highThresholds" in args:
        lnl = lnl.replace(high_thresholds=_int_vector(
            jnp.full_like(lnl.high_thresholds, -1),
            args["highThresholds"], "LowNodeLoad.highThresholds", hi=100))
    if "useDeviationThresholds" in args:
        if not isinstance(args["useDeviationThresholds"], bool):
            raise ComponentConfigError(
                "LowNodeLoad.useDeviationThresholds: expected a bool")
        lnl = lnl.replace(
            use_deviation=jnp.asarray(args["useDeviationThresholds"]))
    if "anomalyCondition" in args:
        cond = args["anomalyCondition"]
        _check_keys(cond, {"consecutiveAbnormalities"},
                    "LowNodeLoad.anomalyCondition")
        rounds = cond.get("consecutiveAbnormalities", 3)
        if not isinstance(rounds, int) or isinstance(rounds, bool) \
                or rounds < 1:
            raise ComponentConfigError(
                "LowNodeLoad.anomalyCondition.consecutiveAbnormalities: "
                f"expected a positive integer, got {rounds!r}")
        lnl = lnl.replace(anomaly_rounds=jnp.int32(rounds))
    out.lownodeload = lnl


def _apply_migration(out: DeschedulerComponentConfig, args: dict) -> None:
    _check_keys(args, {"maxMigratingPerNode", "maxMigratingPerNamespace",
                       "maxMigratingPerWorkload",
                       "maxUnavailablePerWorkload"}, "MigrationController")
    limits = out.migration_limits
    if "maxMigratingPerNode" in args:
        limits = dataclasses.replace(
            limits, max_migrating_per_node=_positive_int(
                args["maxMigratingPerNode"],
                "MigrationController.maxMigratingPerNode"))
    if "maxMigratingPerNamespace" in args:
        limits = dataclasses.replace(
            limits, max_migrating_per_namespace=_positive_int(
                args["maxMigratingPerNamespace"],
                "MigrationController.maxMigratingPerNamespace"))
    if "maxMigratingPerWorkload" in args:
        limits = dataclasses.replace(
            limits, max_migrating_per_workload=_int_or_percent(
                args["maxMigratingPerWorkload"],
                "MigrationController.maxMigratingPerWorkload"))
    if "maxUnavailablePerWorkload" in args:
        limits = dataclasses.replace(
            limits, max_unavailable_per_workload=_int_or_percent(
                args["maxUnavailablePerWorkload"],
                "MigrationController.maxUnavailablePerWorkload"))
    out.migration_limits = limits


def _apply_evictor(out: DeschedulerComponentConfig, args: dict) -> None:
    _check_keys(args, {"priorityThreshold", "evictSystemCriticalPods",
                       "evictLocalStoragePods",
                       "maxNoOfPodsToEvictPerNode"}, "DefaultEvictor")
    if "priorityThreshold" in args:
        value = args["priorityThreshold"]
        if not isinstance(value, int) or isinstance(value, bool):
            raise ComponentConfigError(
                "DefaultEvictor.priorityThreshold: expected an integer")
        out.priority_threshold = value
    for key, attr in (("evictSystemCriticalPods", "evict_system_critical"),
                      ("evictLocalStoragePods", "evict_local_storage_pods")):
        if key in args:
            if not isinstance(args[key], bool):
                raise ComponentConfigError(
                    f"DefaultEvictor.{key}: expected a bool")
            setattr(out, attr, args[key])
    if "maxNoOfPodsToEvictPerNode" in args:
        out.max_evictions_per_round = _positive_int(
            args["maxNoOfPodsToEvictPerNode"],
            "DefaultEvictor.maxNoOfPodsToEvictPerNode")


def load_descheduler_config(path: str,
                            profile_name: str = "koord-descheduler",
                            ) -> DeschedulerComponentConfig:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict):
        raise ComponentConfigError(f"{path}: not a config document")
    kind = doc.get("kind", "DeschedulerConfiguration")
    if kind != "DeschedulerConfiguration":
        raise ComponentConfigError(f"{path}: unexpected kind {kind!r}")

    profile = None
    for p in doc.get("profiles") or []:
        if p.get("name", "koord-descheduler") == profile_name:
            profile = p
            break
    if profile is None:
        raise ComponentConfigError(f"{path}: no profile {profile_name!r}")

    out = DeschedulerComponentConfig()
    plugins = profile.get("plugins") or {}
    _check_keys(plugins, {"deschedule", "balance"}, "plugins")
    for point, attr in (("deschedule", "deschedule_enabled"),
                        ("balance", "balance_enabled")):
        spec = plugins.get(point) or {}
        _check_keys(spec, {"enabled"}, f"plugins.{point}")
        names = spec.get("enabled") or []
        if not isinstance(names, list) or not all(
                isinstance(n, str) for n in names):
            raise ComponentConfigError(
                f"plugins.{point}.enabled: expected a list of names")
        setattr(out, attr, names)

    appliers = {
        "LowNodeLoad": _apply_lownodeload,
        "MigrationController": _apply_migration,
        "DefaultEvictor": _apply_evictor,
    }
    for entry in profile.get("pluginConfig") or []:
        name = entry.get("name")
        args = entry.get("args") or {}
        if name in appliers:
            appliers[name](out, args)
        elif name == "PodLifeTime":
            _check_keys(args, {"maxPodLifeTimeSeconds"}, "PodLifeTime")
            if "maxPodLifeTimeSeconds" in args:
                out.pod_lifetime_max_seconds = _positive_number(
                    args["maxPodLifeTimeSeconds"],
                    "PodLifeTime.maxPodLifeTimeSeconds")
        elif name == "RemovePodsHavingTooManyRestarts":
            _check_keys(args, {"podRestartThreshold"},
                        "RemovePodsHavingTooManyRestarts")
            if "podRestartThreshold" in args:
                out.pod_restart_threshold = _positive_int(
                    args["podRestartThreshold"],
                    "RemovePodsHavingTooManyRestarts.podRestartThreshold")
        else:
            raise ComponentConfigError(
                f"{path}: unknown pluginConfig name {name!r} (supported: "
                f"{sorted(appliers) + ['PodLifeTime', 'RemovePodsHavingTooManyRestarts']})")
    return out
