"""Versioned component config for scheduler plugin args.

The reference configures plugin behavior through a KubeSchedulerConfiguration
file with per-plugin args, defaulting, and validation
(``pkg/scheduler/apis/config/types.go:31-396`` + ``v1/`` defaulting +
``validation/``); CLI flags alone cannot express per-resource weights or
thresholds.  This module is that mechanism for the rebuild:
``koord-scheduler --config FILE`` loads the same YAML shape

    apiVersion: kubescheduler.config.k8s.io/v1
    kind: KubeSchedulerConfiguration
    profiles:
    - schedulerName: koord-scheduler
      pluginConfig:
      - name: LoadAwareScheduling
        args:
          resourceWeights: {cpu: 1, memory: 1}
          usageThresholds: {cpu: 65, memory: 95}
          aggregated: {usageThresholds: {cpu: 70}}
          estimatedScalingFactors: {cpu: 85, memory: 70}
      - name: NodeResourcesFitPlus
        args: {resources: {cpu: {weight: 2, type: MostAllocated}}}
      - name: ScarceResourceAvoidance
        args: {resources: [gpu], weight: 1}
      - name: Coscheduling
        args: {defaultTimeout: 300s, enablePreemption: true}

into a :class:`SchedulerComponentConfig`: a ScoringConfig built by
DEFAULTING from ``ScoringConfig.default()`` and overlaying only the
given args, plus the scheduler-level knobs, with the reference's
validation posture — unknown plugin names, unknown arg keys, unknown
resource names, out-of-range percentages, and unsupported scoring
strategies are hard errors, not silent drops (a typo'd threshold that
silently kept the default would be worse than a crash at startup).
"""

from __future__ import annotations

import dataclasses
import re

import jax.numpy as jnp

from koordinator_tpu.api.resources import RESOURCE_NAMES, ResourceDim
from koordinator_tpu.ops.assignment import ScoringConfig


class ComponentConfigError(ValueError):
    """Invalid component config — fail at startup, loudly."""


@dataclasses.dataclass
class SchedulerComponentConfig:
    #: defaults live HERE and nowhere else: a no-config assembly and a
    #: config-without-that-plugin assembly must agree
    scoring: ScoringConfig = dataclasses.field(
        default_factory=ScoringConfig.default)
    gang_default_timeout_sec: float = 600.0
    enable_preemption: bool | None = None


def _resource_dim(name: str, where: str) -> int:
    # the reference keys args by k8s resource names
    # (kubernetes.io/batch-cpu); bare dim names (gpu, batch_cpu) are
    # accepted too, like resource_vector's keyword form
    dim = RESOURCE_NAMES.get(name)
    if dim is None:
        try:
            dim = ResourceDim[name.upper().replace("-", "_")]
        except KeyError:
            raise ComponentConfigError(
                f"{where}: unknown resource name {name!r} "
                f"(known: {sorted(RESOURCE_NAMES)} or bare dim names "
                f"{[d.name.lower() for d in ResourceDim]})") from None
    return int(dim)


def _int_vector(base, mapping, where: str, lo: int = 0,
                hi: int | None = None):
    if not isinstance(mapping, dict):
        raise ComponentConfigError(f"{where}: expected a mapping")
    out = base
    for name, value in mapping.items():
        if not isinstance(value, int) or isinstance(value, bool):
            raise ComponentConfigError(
                f"{where}[{name}]: expected an integer, got {value!r}")
        if value < lo or (hi is not None and value > hi):
            raise ComponentConfigError(
                f"{where}[{name}]: {value} outside [{lo}, {hi}]")
        out = out.at[_resource_dim(name, where)].set(value)
    return out


def _check_keys(args: dict, allowed: set[str], plugin: str) -> None:
    unknown = set(args) - allowed
    if unknown:
        raise ComponentConfigError(
            f"pluginConfig {plugin}: unknown args {sorted(unknown)} "
            f"(allowed: {sorted(allowed)})")


_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h)$")
_DURATION_SCALE = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def _parse_duration(value, where: str) -> float:
    """metav1.Duration strings ("600s", "10m") or bare seconds; must be
    positive (a non-positive gang timeout would reject every gang on its
    first transient failure)."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        seconds = float(value)
    else:
        m = _DURATION.match(str(value))
        if not m:
            raise ComponentConfigError(
                f"{where}: bad duration {value!r} "
                f"(want e.g. '600s', '10m')")
        seconds = float(m.group(1)) * _DURATION_SCALE[m.group(2)]
    if seconds <= 0:
        raise ComponentConfigError(
            f"{where}: duration must be positive, got {value!r}")
    return seconds


def _apply_loadaware(cfg: ScoringConfig, args: dict) -> ScoringConfig:
    _check_keys(args, {"resourceWeights", "dominantResourceWeight",
                       "usageThresholds", "aggregated",
                       "estimatedScalingFactors"}, "LoadAwareScheduling")
    if "resourceWeights" in args:
        cfg = cfg.replace(loadaware_resource_weights=_int_vector(
            jnp.zeros_like(cfg.loadaware_resource_weights),
            args["resourceWeights"],
            "LoadAwareScheduling.resourceWeights"))
    if "dominantResourceWeight" in args:
        w = args["dominantResourceWeight"]
        if not isinstance(w, int) or isinstance(w, bool) or w < 0:
            raise ComponentConfigError(
                "LoadAwareScheduling.dominantResourceWeight: "
                f"expected a non-negative integer, got {w!r}")
        cfg = cfg.replace(loadaware_dominant_weight=jnp.int32(w))
    if "usageThresholds" in args:
        cfg = cfg.replace(usage_thresholds=_int_vector(
            jnp.zeros_like(cfg.usage_thresholds),
            args["usageThresholds"],
            "LoadAwareScheduling.usageThresholds", hi=100))
    if "aggregated" in args:
        agg = args["aggregated"]
        _check_keys(agg, {"usageThresholds"},
                    "LoadAwareScheduling.aggregated")
        cfg = cfg.replace(agg_usage_thresholds=_int_vector(
            jnp.zeros_like(cfg.agg_usage_thresholds),
            agg.get("usageThresholds", {}),
            "LoadAwareScheduling.aggregated.usageThresholds", hi=100))
    if "estimatedScalingFactors" in args:
        cfg = cfg.replace(estimator_factors=_int_vector(
            cfg.estimator_factors, args["estimatedScalingFactors"],
            "LoadAwareScheduling.estimatedScalingFactors", hi=100))
    return cfg


def _apply_fitplus(cfg: ScoringConfig, args: dict) -> ScoringConfig:
    _check_keys(args, {"resources"}, "NodeResourcesFitPlus")
    weights = jnp.zeros_like(cfg.fitplus_resource_weights)
    most = jnp.zeros_like(cfg.fitplus_most_allocated)
    for name, spec in (args.get("resources") or {}).items():
        if not isinstance(spec, dict):
            raise ComponentConfigError(
                f"NodeResourcesFitPlus.resources[{name}]: expected "
                f"{{weight, type}}")
        _check_keys(spec, {"weight", "type"},
                    f"NodeResourcesFitPlus.resources[{name}]")
        strategy = spec.get("type", "LeastAllocated")
        if strategy not in ("LeastAllocated", "MostAllocated"):
            raise ComponentConfigError(
                f"NodeResourcesFitPlus.resources[{name}]: unsupported "
                f"scoring strategy {strategy!r} (LeastAllocated or "
                f"MostAllocated)")
        dim = _resource_dim(name, "NodeResourcesFitPlus.resources")
        weight = spec.get("weight", 1)
        if not isinstance(weight, int) or isinstance(weight, bool) \
                or weight < 0:
            raise ComponentConfigError(
                f"NodeResourcesFitPlus.resources[{name}].weight: "
                f"expected a non-negative integer, got {weight!r}")
        weights = weights.at[dim].set(weight)
        most = most.at[dim].set(strategy == "MostAllocated")
    return cfg.replace(fitplus_resource_weights=weights,
                       fitplus_most_allocated=most)


def _apply_scarce(cfg: ScoringConfig, args: dict) -> ScoringConfig:
    _check_keys(args, {"resources", "weight"}, "ScarceResourceAvoidance")
    dims = jnp.zeros_like(cfg.scarce_dims)
    for name in args.get("resources") or []:
        dims = dims.at[_resource_dim(
            name, "ScarceResourceAvoidance.resources")].set(True)
    weight = args.get("weight", 1)
    if not isinstance(weight, int) or isinstance(weight, bool) \
            or weight < 0:
        raise ComponentConfigError(
            f"ScarceResourceAvoidance.weight: expected a non-negative "
            f"integer, got {weight!r}")
    return cfg.replace(scarce_dims=dims,
                       scarce_plugin_weight=jnp.int32(weight))


def load_scheduler_config(path: str,
                          scheduler_name: str = "koord-scheduler",
                          ) -> SchedulerComponentConfig:
    """Parse + default + validate one profile's pluginConfig."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict):
        raise ComponentConfigError(f"{path}: not a config document")
    kind = doc.get("kind", "KubeSchedulerConfiguration")
    if kind != "KubeSchedulerConfiguration":
        raise ComponentConfigError(f"{path}: unexpected kind {kind!r}")

    profiles = doc.get("profiles") or []
    profile = None
    for p in profiles:
        if p.get("schedulerName", "koord-scheduler") == scheduler_name:
            profile = p
            break
    if profile is None:
        raise ComponentConfigError(
            f"{path}: no profile named {scheduler_name!r}")

    out = SchedulerComponentConfig()
    appliers = {
        "LoadAwareScheduling": _apply_loadaware,
        "NodeResourcesFitPlus": _apply_fitplus,
        "ScarceResourceAvoidance": _apply_scarce,
    }
    for entry in profile.get("pluginConfig") or []:
        name = entry.get("name")
        args = entry.get("args") or {}
        if name in appliers:
            out.scoring = appliers[name](out.scoring, args)
        elif name == "Coscheduling":
            _check_keys(args, {"defaultTimeout", "enablePreemption"},
                        "Coscheduling")
            if "defaultTimeout" in args:
                out.gang_default_timeout_sec = _parse_duration(
                    args["defaultTimeout"], "Coscheduling.defaultTimeout")
            if "enablePreemption" in args:
                if not isinstance(args["enablePreemption"], bool):
                    raise ComponentConfigError(
                        "Coscheduling.enablePreemption: expected a bool")
                out.enable_preemption = args["enablePreemption"]
        else:
            raise ComponentConfigError(
                f"{path}: unknown pluginConfig name {name!r} "
                f"(supported: {sorted(appliers) + ['Coscheduling']})")
    return out
