"""Process self-telemetry: the gauges the trend engine watches.

Every binary registers the same small set of process-health gauges —
RSS, open fds, live threads, interpreter allocation blocks, gc-tracked
objects, gc collections — labeled by ``binary`` so one aggregated
``/metrics`` scrape (or one shared soak-harness process) keeps the
series distinguishable.  The long-horizon trend engine
(:mod:`koordinator_tpu.trend`) fits slopes over exactly these series to
answer "is this thing leaking under hours of churn" (ISSUE 9); the SLO
monitor's sampler picks them up like any other registry instrument.

Collection is deliberately O(1)-ish per sample: ``/proc/self/statm``
for RSS, one ``listdir`` for fds, ``sys.getallocatedblocks()`` (a
counter the allocator already maintains), ``len(gc.get_objects(0))``
(generation 0 only — a full ``gc.get_objects()`` walk is O(heap) and
would be the soak's own leak of CPU).  Platforms without procfs skip
the procfs-backed gauges rather than publishing zeros a trend fit
would read as a cliff.
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time

from koordinator_tpu import metrics

_PAGE_SIZE = float(os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf")
                   else 4096)


def rss_bytes() -> float | None:
    """Current resident set from ``/proc/self/statm`` (field 2, pages);
    None where procfs is absent — CURRENT, not the peak ru_maxrss,
    because a trend fit over a high-water mark can never see recovery."""
    try:
        with open("/proc/self/statm") as f:
            return float(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        return None


def open_fds() -> float | None:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return None


class SelfTelemetry:
    """Samples the process gauges under one ``binary`` label.

    Drive it with :meth:`sample` (the SLO monitor's ``pre_sample`` hook
    and tests) or :meth:`start` (a background thread for binaries that
    run no SLO monitor — koordlet, manager).
    """

    def __init__(self, binary: str, clock=time.time):
        self.binary = binary
        self.clock = clock
        self.labels = {"binary": binary}
        self.samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample(self) -> None:
        rss = rss_bytes()
        if rss is not None:
            metrics.process_rss_bytes.set(rss, labels=self.labels)
        fds = open_fds()
        if fds is not None:
            metrics.process_open_fds.set(fds, labels=self.labels)
        metrics.process_threads.set(float(threading.active_count()),
                                    labels=self.labels)
        metrics.process_alloc_blocks.set(float(sys.getallocatedblocks()),
                                         labels=self.labels)
        # generation-0 tracked objects: cheap, and a container leak
        # churns through gen0 before it tenures
        metrics.process_gc_objects.set(float(len(gc.get_objects(0))),
                                       labels=self.labels)
        try:
            collections = sum(s.get("collections", 0)
                              for s in gc.get_stats())
        except Exception:  # noqa: BLE001 — stats shape is impl detail
            collections = 0
        metrics.process_gc_collections.set(float(collections),
                                           labels=self.labels)
        self.samples += 1

    # -- background sampler --------------------------------------------------

    def start(self, interval_s: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.sample()
                except Exception:  # noqa: BLE001 — observer thread
                    pass

        self.sample()   # one sample up front: the trend window starts now
        self._thread = threading.Thread(
            target=loop, name=f"self-telemetry-{self.binary}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)
