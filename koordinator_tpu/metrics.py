"""Prometheus-style metrics (reference: ``pkg/scheduler/metrics/``,
``pkg/koordlet/metrics/`` external+internal registries,
``pkg/util/metrics/``, ``pkg/descheduler/metrics/``).

A minimal dependency-free implementation: Counter / Gauge / Histogram with
labels, per-component registries, and the text exposition format, so the
same scrape endpoints and metric names exist for dashboards
(``dashboards/scheduling.json`` equivalents).
"""

from __future__ import annotations

import threading
import time
from typing import Mapping, Optional, Sequence


def _label_key(labels: Mapping[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(value: str) -> str:
    """Text-exposition label escaping (the spec's three escapes, in
    this order so the backslash pass can't double-escape the others):
    backslash, double-quote, line feed."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """# HELP line escaping per the text format: backslash and line
    feed (quotes are legal in HELP text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


def _render_exemplar(ex: dict | None) -> str:
    """OpenMetrics exemplar suffix (`` # {labels} value timestamp``);
    empty for classic-format exposition (ex is None)."""
    if not ex:
        return ""
    labels = ",".join(f'{k}="{_escape_label_value(v)}"'
                      for k, v in sorted(ex["labels"].items()))
    return f" # {{{labels}}} {ex['value']:g} {ex['time']:.3f}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()

    def _header(self) -> list[str]:
        return [f"# HELP {self.name} {_escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}"]

    def expose(self) -> str:
        raise NotImplementedError

    def reset_for_tests(self) -> None:
        """Zero the recorded values (keep the registration + help)."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0,
            labels: Mapping[str, str] | None = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labels: Mapping[str, str] | None = None) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def items(self) -> list[tuple[dict, float]]:
        """Snapshot of (labels, value) per label set (the SLO sampler's
        read surface; also handy for per-label-set test assertions)."""
        with self._lock:
            return [(dict(key), value)
                    for key, value in self._values.items()]

    def expose(self) -> str:
        lines = self._header()
        with self._lock:
            for key, value in sorted(self._values.items()):
                lines.append(f"{self.name}{_render_labels(key)} {value:g}")
        return "\n".join(lines)

    def reset_for_tests(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, labels: Mapping[str, str] | None = None) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        #: latest exemplar per (label key, bucket le): an observation
        #: annotated with e.g. {"trace_id": ...} lands on its SMALLEST
        #: containing bucket, so an outlier's exemplar survives on the
        #: tail bucket instead of being overwritten by every fast round
        #: (the OpenMetrics attachment rule)
        self._exemplars: dict[tuple, dict] = {}

    def observe(self, value: float,
                labels: Mapping[str, str] | None = None,
                exemplar: Mapping[str, str] | None = None) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            bucket_le = "+Inf"
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    if bucket_le == "+Inf":
                        bucket_le = f"{bound:g}"
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1
            if exemplar:
                self._exemplars[(key, bucket_le)] = {
                    "labels": dict(exemplar), "value": float(value),
                    "time": time.time(),
                }

    def exemplars(self, labels: Mapping[str, str] | None = None
                  ) -> dict[str, dict]:
        """{bucket le -> {labels, value, time}} for one label set (the
        /debug linkage from latency outliers to trace ids)."""
        key = _label_key(labels)
        with self._lock:
            return {le: dict(ex) for (k, le), ex in self._exemplars.items()
                    if k == key}

    def quantile(self, q: float,
                 labels: Mapping[str, str] | None = None) -> float:
        """Quantile estimate from exposition state with Prometheus-style
        linear interpolation inside the containing bucket (the SLO
        engine's p99 and tests compute from the same math —
        :func:`quantile_from_buckets`).  Observations in the +Inf bucket
        clamp to the highest finite bound; no data returns 0.0."""
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            # copy under the lock: a concurrent observe() mutates the
            # cumulative list bucket by bucket, and a torn read could
            # momentarily look non-cumulative
            counts = list(counts) if counts else None
            total = self._totals.get(key, 0)
        if not counts or total == 0:
            return 0.0
        return quantile_from_buckets(self.buckets, counts, total, q)

    def state(self) -> list[tuple[dict, list[int], int, float]]:
        """Snapshot per label set: (labels, cumulative finite-bucket
        counts, total incl. +Inf, sum) — the public read surface the
        SLO sampler uses instead of reaching into the lock-guarded
        internals."""
        with self._lock:
            return [(dict(key), list(self._counts[key]),
                     self._totals.get(key, 0), self._sums.get(key, 0.0))
                    for key in self._counts]

    def expose(self, openmetrics: bool = False) -> str:
        """Classic text format by default; ``openmetrics=True`` appends
        exemplar suffixes on bucket lines (classic Prometheus parsers
        reject the `` # {...}`` syntax, so it is strictly opt-in)."""
        lines = self._header()
        with self._lock:
            for key in sorted(self._counts):
                counts = self._counts[key]
                for bound, count in zip(self.buckets, counts):
                    le = f"{bound:g}"
                    bucket_key = key + (("le", le),)
                    ex = (_render_exemplar(self._exemplars.get((key, le)))
                          if openmetrics else "")
                    lines.append(
                        f"{self.name}_bucket{_render_labels(bucket_key)} "
                        f"{count}{ex}"
                    )
                inf_key = key + (("le", "+Inf"),)
                ex = (_render_exemplar(self._exemplars.get((key, "+Inf")))
                      if openmetrics else "")
                lines.append(
                    f"{self.name}_bucket{_render_labels(inf_key)} "
                    f"{self._totals[key]}{ex}"
                )
                lines.append(
                    f"{self.name}_sum{_render_labels(key)} {self._sums[key]:g}"
                )
                lines.append(
                    f"{self.name}_count{_render_labels(key)} {self._totals[key]}"
                )
        return "\n".join(lines)

    def reset_for_tests(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()
            self._exemplars.clear()


class Registry:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _full(self, name: str) -> str:
        return f"{self.prefix}_{name}" if self.prefix else name

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, lambda n: Counter(n, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, lambda n: Gauge(n, help_text), Gauge)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, lambda n: Histogram(n, help_text, buckets), Histogram
        )

    def _get_or_create(self, name: str, factory, expected_type):
        full = self._full(name)
        with self._lock:
            metric = self._metrics.get(full)
            if metric is None:
                metric = self._metrics[full] = factory(full)
            elif not isinstance(metric, expected_type):
                raise ValueError(f"metric {full} already registered as "
                                 f"{type(metric).__name__}")
            return metric

    def items(self) -> list[tuple[str, _Metric]]:
        """Snapshot of (full name, instrument) registrations — the
        public read surface for registry walkers (the SLO sampler, the
        dashboard drift checker) so they stay off the lock-guarded
        internals, mirroring Counter.items/Histogram.state."""
        with self._lock:
            return list(self._metrics.items())

    def expose(self, openmetrics: bool = False) -> str:
        """The /metrics scrape body."""
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(
            m.expose(openmetrics) if isinstance(m, Histogram) else m.expose()
            for m in metrics) + "\n"

    def reset_for_tests(self) -> None:
        """Zero every metric's recorded values WITHOUT dropping the
        registrations (module-level instrument handles stay valid) —
        the per-test isolation hook ``tests/conftest.py`` applies so
        counters stop bleeding across tests within one process."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset_for_tests()


# Component registries (the reference's per-component metric packages).
SCHEDULER = Registry("koord_scheduler")
KOORDLET = Registry("koordlet")
MANAGER = Registry("koord_manager")
DESCHEDULER = Registry("koord_descheduler")
TRANSPORT = Registry("koord_transport")
#: process self-telemetry (selftelemetry.py): the same gauges in every
#: binary, labeled {binary=...} — the trend engine's leak-watch inputs
PROCESS = Registry("koord_process")

ALL_REGISTRIES = (SCHEDULER, KOORDLET, MANAGER, DESCHEDULER, TRANSPORT,
                  PROCESS)


def expose_all(openmetrics: bool = False) -> str:
    """One scrape body over every component registry — the aggregate
    /metrics surface each binary's HTTP gateway serves (a koordlet
    process still exposes its transport metrics, a scheduler its
    koordlet-registry zeros, and so on: scrape configs stay uniform).

    The OpenMetrics body ends with the mandatory ``# EOF`` terminator —
    a scraper negotiating openmetrics via Accept would otherwise reject
    the whole exposition as truncated."""
    body = "".join(r.expose(openmetrics) for r in ALL_REGISTRIES)
    if openmetrics:
        body += "# EOF\n"
    return body


def quantile_from_buckets(bounds: Sequence[float],
                          cum_counts: Sequence[float],
                          total: float, q: float) -> float:
    """Prometheus ``histogram_quantile`` bucket interpolation over
    cumulative finite-bucket counts.

    ``cum_counts[i]`` is the number of observations <= ``bounds[i]``;
    ``total`` includes the +Inf bucket.  Observations landing past the
    last finite bound (the +Inf bucket) clamp to the highest finite
    bound — the quantile of data the buckets cannot resolve is the best
    bound they CAN name, exactly Prometheus's behavior.  Empty data
    returns the 0.0 sentinel."""
    if total <= 0 or not bounds:
        return 0.0
    q = min(max(q, 0.0), 1.0)
    rank = q * total
    for i, bound in enumerate(bounds):
        if cum_counts[i] >= rank:
            lower = bounds[i - 1] if i > 0 else 0.0
            below = cum_counts[i - 1] if i > 0 else 0.0
            in_bucket = cum_counts[i] - below
            if in_bucket <= 0:
                return bound
            return lower + (bound - lower) * (rank - below) / in_bucket
    return bounds[-1]   # rank falls in the +Inf bucket


def count_at_or_below(bounds: Sequence[float],
                      cum_counts: Sequence[float],
                      total: float, x: float) -> float:
    """Estimated observations <= ``x`` by linear interpolation within
    the containing bucket (the burn-rate engine's "good events" count
    for thresholds that are not exact bucket bounds).

    Observations in the +Inf bucket are NEVER counted at-or-below a
    finite ``x`` — the buckets cannot prove anything about them, and a
    threshold at/above the last finite bound must not silently bless a
    60s solve as meeting a 10s SLO (they count as bad, the conservative
    direction for an error budget)."""
    if total <= 0 or not bounds:
        return 0.0
    if x >= bounds[-1]:
        return float(cum_counts[-1])
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in zip(bounds, cum_counts):
        if x < bound:
            width = bound - prev_bound
            if width <= 0:
                return float(cum)
            frac = max(0.0, (x - prev_bound)) / width
            return prev_cum + (cum - prev_cum) * frac
        prev_bound, prev_cum = bound, cum
    return float(cum_counts[-1])


def parse_openmetrics_flag(value) -> bool:
    """One parser for the ``openmetrics`` query/param flag across the
    debug surfaces: only explicit truthy spellings enable it (JSON
    ``false`` and the string "false" must NOT — an exemplar-suffixed
    body breaks classic Prometheus parsers)."""
    return str(value).strip().lower() in ("1", "true", "yes", "on")


def reset_all_for_tests() -> None:
    for registry in ALL_REGISTRIES:
        registry.reset_for_tests()

# Canonical instruments (names mirror the reference's).
scheduling_latency = SCHEDULER.histogram(
    "scheduling_duration_seconds",
    "Scheduling-cycle latency per phase (label: phase); aggregate by (le, "
    "phase)")
solver_batch_latency = SCHEDULER.histogram(
    "solver_batch_duration_seconds", "Batched filter/score/assign solve latency")
solver_device_latency = SCHEDULER.histogram(
    "solver_device_duration_seconds",
    "Device-side share of the batch solve: time spent blocking on the "
    "jitted solves' results (label: path=incremental|full_*) — wall "
    "minus this is host batch-build/dispatch/bookkeeping overhead")
round_flight_dumps = SCHEDULER.counter(
    "round_flight_dumps_total",
    "Round flight records dumped by the recorder (label: "
    "reason=slow|degraded)")
pending_pods = SCHEDULER.gauge("pending_pods", "Pods waiting to be scheduled")
incremental_dirty_fraction = SCHEDULER.gauge(
    "incremental_dirty_fraction",
    "Dirty fraction the incremental solve saw this round (label: "
    "kind=nodes|pods); drives the full-pass fallback flip")
incremental_solve_total = SCHEDULER.counter(
    "incremental_solve_rounds_total",
    "Batch solve rounds by path (label: path=incremental|full_cold|"
    "full_fallback|full_gang|full_dense|disabled) — full_fallback means "
    "the dirty fraction crossed the threshold, full_cold that no valid "
    "candidate cache existed, full_dense that a dense (hinted/topology) "
    "feasibility mask forced the full path")
incremental_dirty_pods = SCHEDULER.gauge(
    "incremental_dirty_pods",
    "Pods fully rescored by the last incremental round (new/changed pods "
    "plus pods whose cached candidates touched a dirty node)")
state_staleness_seconds = SCHEDULER.gauge(
    "state_staleness_seconds",
    "Age of the last applied sync event (delta or heartbeat) as of the "
    "last scheduling round; drives the degraded-mode flip")
degraded_mode = SCHEDULER.gauge(
    "degraded_mode",
    "1 while the scheduler is in stale-state degraded mode (BE admission "
    "suspended, full-pass solves), else 0")
degraded_transitions_total = SCHEDULER.counter(
    "degraded_transitions_total",
    "Degraded-mode flips (label: phase=enter|exit)")
degraded_suspended_pods = SCHEDULER.gauge(
    "degraded_suspended_pods",
    "Pods held out of the last round because degraded mode suspends "
    "BE/batch-dim admission")
solve_deadline_shed_total = SCHEDULER.counter(
    "solve_deadline_shed_total",
    "SOLVE_REQUESTs shed because their deadline expired before the solve "
    "could start (the caller already timed out; running it helps nobody)")
round_flight_overwritten = SCHEDULER.counter(
    "round_flight_overwritten_total",
    "Flight records evicted by ring overwrite (dump reasons are "
    "counted; silent eviction was not, ISSUE 5).  A full ring evicts "
    "one record per round: size the ring so this rate times your "
    "/debug/rounds polling interval stays well under the ring capacity, "
    "or evicted rounds were never observable")

# -- SLO burn-rate engine (slo_monitor.py) --
slo_burn_rate = SCHEDULER.gauge(
    "slo_burn_rate",
    "Error-budget burn rate per SLO and window (labels: slo, "
    "window=fast|slow); 1.0 = burning exactly the budget, >>1 = paging")
slo_breached = SCHEDULER.gauge(
    "slo_breached",
    "1 while the SLO's fast-burn alert is firing (label: slo); cleared "
    "with hysteresis once the fast window cools")
slo_alerts_total = SCHEDULER.counter(
    "slo_alerts_total",
    "SLO alert transitions (labels: slo, phase=fire|clear)")

# -- steady-state observatory (trend.py / selftelemetry.py, ISSUE 9) --
pods_enqueued_total = SCHEDULER.counter(
    "pods_enqueued_total",
    "Pods admitted into the scheduling queue (rsv:: reserve-pods "
    "included) — rate() of this is the arrival rate the churn load "
    "generator drives and the steady-state dashboards plot")
trend_verdict = SCHEDULER.gauge(
    "trend_verdict",
    "Long-horizon trend verdict per watched series (labels: series "
    "plus the series' own labels): -1 no_data, 0 steady, 1 drifting, "
    "2 leaking — set by each TrendEngine.evaluate and served at "
    "/debug/steady")
trend_slope_per_hour = SCHEDULER.gauge(
    "trend_slope_per_hour",
    "Fitted windowed slope per watched series, scaled to units/hour "
    "(labels: series plus the series' own labels)")

# -- multi-tenant round pipeline (scheduler/tenancy.py, ISSUE 11) --
tenant_count = SCHEDULER.gauge(
    "tenant_count",
    "Clusters multiplexed onto this scheduler's mesh by the tenancy "
    "front-end (0 = single-tenant scheduler, no front-end attached)")
tenant_admission_share = SCHEDULER.gauge(
    "tenant_admission_share",
    "Observed share of the last cycle's admitted pods per tenant "
    "(label: tenant) — under sustained overload this converges to the "
    "tenant's weight fraction (weighted deficit-round-robin admission)")
tenant_admitted = SCHEDULER.counter(
    "tenant_pods_admitted_total",
    "Pods admitted into solve rounds by the weighted-fair admission "
    "gate, per tenant (label: tenant); rate ratios between tenants are "
    "the fairness observable")
tenant_cycles = SCHEDULER.counter(
    "tenant_cycles_total",
    "Multi-tenant scheduling cycles by dispatch mode (label: "
    "mode=pipelined|batched|serial) — batched means one tenant-axis "
    "vmapped program solved every tenant, pipelined that per-tenant "
    "device solves overlapped host commits, serial the fallback")
tenant_cycle_latency = SCHEDULER.histogram(
    "tenant_cycle_duration_seconds",
    "Wall time of one multi-tenant scheduling cycle (every tenant's "
    "round, device and host halves)")
pipeline_host_wait_fraction = SCHEDULER.gauge(
    "pipeline_host_wait_fraction",
    "Share of the last cycle's wall the host spent BLOCKED on device "
    "solve results (sum of block waits / cycle wall).  Serial "
    "single-tenant-at-a-time operation pins this near the device's "
    "share of the round; the pipelined overlap drives it toward zero "
    "because solves execute while other tenants' commits run")

# -- critical-path observatory (timeline.py, ISSUE 18) --
host_wait_attribution = SCHEDULER.gauge(
    "host_wait_attribution",
    "Decomposition of the last cycle's WHOLE wall into fractions that "
    "sum to 1.0 (label: cause — timeline.ATTRIBUTION_CAUSES).  The "
    "device_block bucket equals pipeline_host_wait_fraction by "
    "construction (same block_until_ready intervals); the remaining "
    "causes (dispatch, deltasync_apply, build_batch, bind_commit, "
    "json_codec, lock_wait, host_other) decompose its complement, and "
    "unattributed is the explicit residual the phase-accounting "
    "invariant test pins under 5%")
device_idle_fraction = SCHEDULER.gauge(
    "device_idle_fraction",
    "Share of the last cycle's wall with NO solve in flight on the "
    "device, derived from the dispatch/block edges of every tenant's "
    "round — the headroom the pipelined overlap has not yet claimed")
critical_path_seconds = SCHEDULER.gauge(
    "critical_path_seconds",
    "Seconds of the last cycle's critical-path covering chain per "
    "cause (label: cause); topk(1, ...) names the dominant cause the "
    "ROADMAP item-5 perf attack should aim at.  Every cause is "
    "republished each cycle so cleared ones read 0")

# -- pod-journey ledger (journey.py, ISSUE 20) --
pod_journey_latency_seconds = SCHEDULER.gauge(
    "pod_journey_latency_seconds",
    "Per-pod scheduling-journey latency quantiles from the always-on "
    "journey ledger's mergeable log-bucketed sketches (labels: tenant, "
    "qos, stage=e2e|ingest|queue_wait|solve|commit, q=0.5|0.99).  "
    "Unlike the round-scoped scheduling_duration histogram these are "
    "TRUE per-pod arrival->bind quantiles with <=1% relative error, "
    "published by the SloMonitor pre-sample hook each sweep")

# -- bench probe arming (bench_prober.py, ROADMAP item 1) --
bench_probe_attempts = SCHEDULER.counter(
    "bench_probe_attempts_total",
    "Device-probe attempts by outcome (label: outcome=ok|"
    "no_devices_enumerated|probe_kernel_hung|transfer_stall|"
    "probe_error) — the background prober's retry cadence")
bench_probe_duration = SCHEDULER.histogram(
    "bench_probe_duration_seconds",
    "Wall time of each device-probe attempt; a probe pinned at its "
    "deadline means the backend hangs rather than errors",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 180.0, 300.0))
bench_probe_hung = SCHEDULER.gauge(
    "bench_probe_hung",
    "1 while the latest device probe overran its deadline (hung "
    "kernel/transfer) rather than failing fast; the bench_probe_hang "
    "SLO burns against this, so a wedged tunnel pages with a flight "
    "record instead of silently retrying")
bench_probe_window_open = SCHEDULER.gauge(
    "bench_probe_window_open",
    "1 once a probe has succeeded this armer's run (the tunnel-up "
    "window the staged capture publishes into)")

# -- process self-telemetry (selftelemetry.py) --
process_rss_bytes = PROCESS.gauge(
    "rss_bytes", "Resident set size (proc statm; label: binary)")
process_open_fds = PROCESS.gauge(
    "open_fds", "Open file descriptors (label: binary)")
process_threads = PROCESS.gauge(
    "threads", "Live Python threads (label: binary)")
process_alloc_blocks = PROCESS.gauge(
    "alloc_blocks",
    "Interpreter-allocated memory blocks (sys.getallocatedblocks; "
    "label: binary) — a cheap, monotone-under-leak heap signal")
process_gc_objects = PROCESS.gauge(
    "gc_objects",
    "Generation-0 gc-tracked objects (label: binary)")
process_gc_collections = PROCESS.gauge(
    "gc_collections",
    "Cumulative gc collections across generations (label: binary)")

# -- JAX solver introspection (ops/introspection.py) --
solver_recompiles = SCHEDULER.counter(
    "solver_recompiles_total",
    "Jit-cache misses (trace+compile) of the solver's jitted entry "
    "points per shape bucket (labels: fn, shape) — a steady-state "
    "scheduler should sit at zero rate; increments mean shape churn")
solver_jit_cache_size = SCHEDULER.gauge(
    "solver_jit_cache_size",
    "Live jit-cache entries per instrumented solver entry point "
    "(label: fn); bounded by the power-of-two shape bucketing")
solver_device_bytes = SCHEDULER.gauge(
    "solver_device_bytes",
    "Device-resident bytes of the solver's persistent tensors (label: "
    "kind=cluster_state|candidate_cache; per-device rows additionally "
    "carry shard=<device id> when the solve mesh is active)")
solver_shard_count = SCHEDULER.gauge(
    "solver_shard_count",
    "Nodes-axis size of the active solver mesh (1 = single-device "
    "solve; parallel/sharded.py shard_map path engaged when > 1)")
solver_axis_shard_count = SCHEDULER.gauge(
    "solver_axis_shard_count",
    "Per-axis size of the active 2-D solver mesh (label: "
    "axis=pods|nodes; both 1 for a single-device solve) — the split "
    "solver_shard_count can't express once the pods axis is > 1")
solver_batch_padding_waste = SCHEDULER.gauge(
    "solver_batch_padding_waste",
    "Padding-waste fraction of the last PodBatch: (capacity - live "
    "pods) / capacity — the device memory and FLOPs spent on rows the "
    "power-of-two bucketing padded in")

# -- placement explainability (ops/explain.py, ISSUE 6) --
unschedulable_pods = SCHEDULER.gauge(
    "unschedulable_pods",
    "Pods the last round left unplaced (or suspended/gang-parked), by "
    "attributed top reject reason (label: reason — ops/explain."
    "REASON_NAMES: per-dim fit, usage_threshold, affinity, plus the "
    "pod-level gates quota/gang_barrier/degraded_suspended); every "
    "reason label is republished each round so cleared reasons read 0")
filter_reject_fraction = SCHEDULER.histogram(
    "filter_reject_fraction",
    "Fraction of cluster nodes each filter stage rejected, averaged "
    "over a round's unplaced pods (label: reason) — which constraint "
    "is actually binding when pods go unschedulable",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0))
capacity_slack = SCHEDULER.gauge(
    "capacity_slack_fraction",
    "Request-free capacity fraction per resource dimension over valid "
    "nodes: sum(allocatable - requested) / sum(allocatable) (label: "
    "dim) — the per-dim headroom left before fit_<dim> rejections "
    "dominate")

# -- solve-quality mode (quality/lp_pack + quality/topo_gang, ISSUE 13) --
solver_quality_mode = SCHEDULER.gauge(
    "solver_quality_mode",
    "Configured solve-quality mode: 0=off (greedy only), 1=lp (every "
    "eligible round solves with the LP-relaxation packing engine), "
    "2=auto (escalate only rounds whose result leaves "
    "capacity_slack_fraction above the threshold)")
quality_rounds = SCHEDULER.counter(
    "quality_rounds_total",
    "Rounds solved on the LP-relaxation quality path (labels: "
    "mode=lp|auto, outcome=complete|partial — partial means the round "
    "still diagnosed failures after the quality solve and the exact "
    "rescue pass)")
quality_iterations = SCHEDULER.histogram(
    "quality_iterations",
    "Rounding phases the LP quality solve executed per round (bounded "
    "by the engine's rounding_iters — a round pinned at the bound "
    "means contention never cleared and the final prefix resolution "
    "did the placing)",
    buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
quality_slack_recovered = SCHEDULER.gauge(
    "quality_slack_recovered_fraction",
    "Fraction of total allocatable capacity the last quality round "
    "turned from free slack into placements, per resource dimension "
    "(label: dim): (free_before - free_after) / allocatable")

# -- forecast plane (forecast/, ISSUE 15) --
forecast_horizon_seconds = SCHEDULER.gauge(
    "forecast_horizon_seconds",
    "The forecast plane's current prediction horizon: the base horizon "
    "stretched by the diurnal trend slope (plane.horizon_for) — a "
    "ramping cluster looks further ahead")
forecast_error_fraction = SCHEDULER.gauge(
    "forecast_error_fraction",
    "Forecast error of the previous prediction window, per resource "
    "dimension (label: dim): sum|predicted - realized peak| / "
    "sum(realized peak) over nodes that saw usage")
forecast_admission_reserved_fraction = SCHEDULER.gauge(
    "forecast_admission_reserved_fraction",
    "Fraction of cluster allocatable the predictive-admission reserve "
    "charged into the last forecast round's filter/score accounting "
    "(forecast growth not yet visible in observed usage)")
forecast_evictions_prestaged = SCHEDULER.counter(
    "forecast_evictions_prestaged_total",
    "Reservation-first migrations pre-staged off nodes FORECAST to "
    "cross the LowNodeLoad high threshold (proactive rebalance) — "
    "each one is a reactive emergency eviction that never had to "
    "happen")

# -- failure drills (drills/, ISSUE 17) --
drill_active = SCHEDULER.gauge(
    "drill_active",
    "1 while a failure drill scenario is running against this control "
    "plane (label: scenario) — correlates every other panel's wobble "
    "with the drill that injected it; zero in production")
drill_recovery_duration_seconds = SCHEDULER.histogram(
    "drill_recovery_duration_seconds",
    "Measured RTO per drill: inject (kill/storm/restart) to the verdict "
    "engine's reconvergence fixpoint (all live pods bound, degraded "
    "mode exited, watch views caught up to the service rv)",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0))
leader_failovers_total = SCHEDULER.counter(
    "leader_failovers_total",
    "Observed scheduler leadership hand-offs (a different identity "
    "holds the lease than the previous observation) — drills assert "
    "exactly the scripted number happened")
checkpoint_restore_duration_seconds = SCHEDULER.histogram(
    "checkpoint_restore_duration_seconds",
    "Warm-restart checkpoint restore time (drills/checkpoint.restore): "
    "load + apply of the host snapshot and replay cursor, EXCLUDING "
    "the deltasync catch-up that follows",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))

be_suppress_cpu_cores = KOORDLET.gauge(
    "be_suppress_cpu_cores", "CPU cores currently allowed for BE")
pod_eviction_total = KOORDLET.counter(
    "pod_eviction_total", "Node-side evictions by reason")
cpu_burst_total = KOORDLET.counter(
    "cpu_burst_total", "CPU burst quota adjustments")
container_cpi = KOORDLET.gauge("container_cpi", "Cycles per instruction")
psi_cpu_some_avg10 = KOORDLET.gauge("psi_cpu_some_avg10", "CPU PSI some avg10")

batch_resource_allocatable = MANAGER.gauge(
    "batch_resource_allocatable", "Batch allocatable per node/resource")
node_metric_expired = MANAGER.gauge(
    "node_metric_expired", "1 when a node's metric report is stale")
colocation_patches_total = MANAGER.counter(
    "colocation_patches_total",
    "node_allocatable patches pushed by the colocation loop")
colocation_push_failures_total = MANAGER.counter(
    "colocation_push_failures_total",
    "colocation-loop pushes lost to a wedged sidecar (retried next tick)")
colocation_connect_failures_total = MANAGER.counter(
    "colocation_connect_failures_total",
    "colocation-loop sidecar reconnect attempts that failed")

rpc_deadline_shed_total = TRANSPORT.counter(
    "rpc_deadline_shed_total",
    "Requests shed at the channel layer because deadline_ms had already "
    "expired at dispatch (label: type=frame type)")
breaker_state = TRANSPORT.gauge(
    "circuit_breaker_state",
    "Dial circuit breaker state per target: 0=closed, 1=half-open, 2=open")
breaker_transitions_total = TRANSPORT.counter(
    "circuit_breaker_transitions_total",
    "Breaker state transitions (labels: target, to)")
dial_attempts_total = TRANSPORT.counter(
    "dial_attempts_total",
    "Reconnecting-client dial attempts (label: outcome=ok|refused|"
    "bootstrap_failed|open — refused means the dial itself failed, "
    "bootstrap_failed that the peer accepted but the on_connect "
    "bootstrap did not, open that the circuit refused to dial at all)")
faults_injected_total = TRANSPORT.counter(
    "faults_injected_total",
    "Injected transport faults by kind (chaos harness only; zero in "
    "production)")
sync_gap_resyncs_total = TRANSPORT.counter(
    "sync_gap_resyncs_total",
    "Watch-stream rv gaps detected by a sync client (a lost/reordered "
    "delta): the client tears its connection down and re-HELLOs")
sync_binding_backlog = TRANSPORT.gauge(
    "sync_binding_backlog",
    "Committed deltasync events queued for local-binding apply right "
    "now (StateSyncService._binding_queue depth) — bindings drain it "
    "behind the scheduler lock, so sustained growth means solve rounds "
    "can no longer keep up with the arrival process")
sync_binding_backlog_peak = TRANSPORT.gauge(
    "sync_binding_backlog_peak",
    "High-water mark of the local-binding backlog since process start "
    "(the watermark the steady-state soak bounds and the trend engine "
    "watches)")
sync_resyncs_total = TRANSPORT.counter(
    "sync_resyncs_total",
    "Server-requested resyncs honored by a reconnecting client (ERROR "
    "frame with resync: true — e.g. a push for a node the restarted "
    "service no longer knows)")
wire_codec_seconds = TRANSPORT.histogram(
    "wire_codec_duration_seconds",
    "JSON+array payload codec wall time per operation (label: "
    "op=encode|decode) — the json_codec slice of the host-wait "
    "attribution (ISSUE 18); rising encode p99 at flat payload bytes "
    "means the control doc grew, not the tensors",
    buckets=(0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
             0.01, 0.025, 0.05, 0.1, 0.25, 1.0))
wire_payload_bytes = TRANSPORT.histogram(
    "wire_payload_bytes",
    "Encoded frame payload size in bytes per operation (label: "
    "op=encode|decode): json section + raw array section together",
    buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304,
             16777216, 67108864))

descheduler_evictions_total = DESCHEDULER.counter(
    "pod_evictions_total", "Descheduler evictions by profile/reason")
migration_jobs = DESCHEDULER.gauge(
    "migration_jobs", "PodMigrationJobs by phase")
