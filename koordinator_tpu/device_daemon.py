"""koord-device-daemon: heterogeneous device reporter (reference:
``cmd/koord-device-daemon``, ``pkg/device-daemon/`` — produces per-node
Device info: GPU partitions, NUMA topology, health).

Probers are pluggable (the reference uses NVML/ghw; this environment probes
sysfs and supports TPU chips as first-class accelerators). The daemon merges
prober outputs into one :class:`~koordinator_tpu.api.crds.Device` CR and the
GPU partition templates consumed by the deviceshare scheduler plugin.
"""

from __future__ import annotations

import glob
import os
from typing import Optional, Protocol

from koordinator_tpu.api import crds, extension as ext


class DeviceProber(Protocol):
    def probe(self) -> list[crds.DeviceInfo]: ...


class SysfsGPUProber:
    """NVIDIA device discovery from sysfs (the NVML-less fallback path);
    real deployments swap in an NVML-backed prober."""

    def __init__(self, sys_root: str = "/sys"):
        self.sys_root = sys_root

    def probe(self) -> list[crds.DeviceInfo]:
        out = []
        pattern = os.path.join(
            self.sys_root, "bus", "pci", "drivers", "nvidia", "0000:*"
        )
        for i, pci_dir in enumerate(sorted(glob.glob(pattern))):
            busid = os.path.basename(pci_dir)
            numa = -1
            try:
                with open(os.path.join(pci_dir, "numa_node")) as f:
                    numa = int(f.read().strip())
            except (OSError, ValueError):
                pass
            out.append(crds.DeviceInfo(
                type="gpu", minor=i, busid=busid, numa_node=numa,
                resources={ext.RESOURCE_GPU_CORE: 100,
                           ext.RESOURCE_GPU_MEMORY_RATIO: 100},
            ))
        return out


class TPUProber:
    """TPU chips as schedulable accelerators (accel sysfs class)."""

    def __init__(self, sys_root: str = "/sys"):
        self.sys_root = sys_root

    def probe(self) -> list[crds.DeviceInfo]:
        out = []
        for i, dev in enumerate(sorted(
            glob.glob(os.path.join(self.sys_root, "class", "accel", "accel*"))
        )):
            out.append(crds.DeviceInfo(
                type="xpu", minor=i, uuid=os.path.basename(dev),
                labels={"xpu.vendor": "tpu"},
            ))
        return out


class RDMAProber:
    """RDMA NICs from sysfs infiniband class."""

    def __init__(self, sys_root: str = "/sys"):
        self.sys_root = sys_root

    def probe(self) -> list[crds.DeviceInfo]:
        out = []
        for i, dev in enumerate(sorted(
            glob.glob(os.path.join(self.sys_root, "class", "infiniband", "*"))
        )):
            name = os.path.basename(dev)
            numa = -1
            try:
                with open(os.path.join(dev, "device", "numa_node")) as f:
                    numa = int(f.read().strip())
            except (OSError, ValueError):
                pass
            out.append(crds.DeviceInfo(
                type="rdma", minor=i, uuid=name, numa_node=numa,
            ))
        return out


#: GPU partition templates (gpu_shared_resource_templates): the allowed
#: fractional slices of one physical GPU, keyed by template name.
DEFAULT_GPU_PARTITION_TEMPLATES: dict[str, dict[str, int]] = {
    "1/8": {ext.RESOURCE_GPU_CORE: 12, ext.RESOURCE_GPU_MEMORY_RATIO: 12},
    "1/4": {ext.RESOURCE_GPU_CORE: 25, ext.RESOURCE_GPU_MEMORY_RATIO: 25},
    "1/2": {ext.RESOURCE_GPU_CORE: 50, ext.RESOURCE_GPU_MEMORY_RATIO: 50},
    "full": {ext.RESOURCE_GPU_CORE: 100, ext.RESOURCE_GPU_MEMORY_RATIO: 100},
}


class DeviceDaemon:
    def __init__(self, node_name: str,
                 probers: Optional[list[DeviceProber]] = None,
                 sys_root: str = "/sys"):
        self.node_name = node_name
        self.probers = probers if probers is not None else [
            SysfsGPUProber(sys_root), TPUProber(sys_root), RDMAProber(sys_root),
        ]

    def collect(self) -> crds.Device:
        """One reporting pass: merge all probers into the Device CR.
        First prober wins per (type, minor) — probers normally read
        disjoint roots, but a double-observed chip must not duplicate."""
        devices: list[crds.DeviceInfo] = []
        seen: set[tuple[str, int]] = set()
        for prober in self.probers:
            try:
                for info in prober.probe():
                    key = (info.type, info.minor)
                    if key in seen:
                        continue
                    seen.add(key)
                    devices.append(info)
            except OSError:
                continue
        import json

        annotations = {}
        if any(d.type == "gpu" for d in devices):
            annotations["scheduling.koordinator.sh/gpu-partitions"] = json.dumps(
                DEFAULT_GPU_PARTITION_TEMPLATES, sort_keys=True
            )
        return crds.Device(
            node_name=self.node_name,
            devices=tuple(devices),
            annotations=annotations,
        )
