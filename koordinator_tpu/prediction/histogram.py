"""Vectorized decaying exponential histograms.

Semantics from the reference's ``pkg/util/histogram`` (a VPA-style histogram):

- exponential bucket starts: s_0 = 0, s_i = first * (ratio^i - 1) / (ratio - 1)
  (``exponential_histogram_options.go``); FindBucket is the log inverse.
- ``Percentile(p)`` walks buckets from the first whose weight >= epsilon,
  accumulating until partialSum >= p * totalWeight, and returns the *next*
  bucket's start (upper bound of the matched bucket); the last bucket returns
  its own start (``histogram.go:158``).
- decaying histograms weight a sample at time t by 2^((t - ref) / halfLife)
  (``decaying_histogram.go:34``); shifting ref rescales all weights, done here
  whenever the multiplier grows past 2^32 to keep float32 in range.

The bank holds U models as one (U, B) float32 weight matrix; adds are
scatter-adds and percentile queries answer all models in one pass.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

DEFAULT_BUCKET_GROWTH = 0.05  # DefaultHistogramBucketSizeGrowth
EPSILON = 1e-10               # epsilon in predict_server.go


@dataclasses.dataclass(frozen=True)
class ExponentialBuckets:
    """Static bucket layout (hashable; safe as a jit static arg)."""

    first_bucket_size: float
    ratio: float
    num_buckets: int

    @classmethod
    def for_range(cls, max_value: float, first_bucket_size: float,
                  ratio: float) -> "ExponentialBuckets":
        """NewExponentialHistogramOptions: enough buckets to cover max_value."""
        # s_n >= max_value  <=>  n >= log(1 + max*(r-1)/first) / log(r)
        n = int(math.ceil(
            math.log1p(max_value * (ratio - 1.0) / first_bucket_size)
            / math.log(ratio)
        )) + 1
        return cls(first_bucket_size, ratio, n)

    def starts(self) -> np.ndarray:
        i = np.arange(self.num_buckets, dtype=np.float64)
        return (self.first_bucket_size * (self.ratio**i - 1.0)
                / (self.ratio - 1.0)).astype(np.float32)

    def find_bucket(self, values: jnp.ndarray) -> jnp.ndarray:
        """Vectorized FindBucket: index of the bucket containing each value."""
        v = jnp.maximum(values.astype(jnp.float32), 0.0)
        idx = jnp.floor(
            jnp.log1p(v * (self.ratio - 1.0) / self.first_bucket_size)
            / math.log(self.ratio)
        ).astype(jnp.int32)
        return jnp.clip(idx, 0, self.num_buckets - 1)


def default_cpu_buckets() -> ExponentialBuckets:
    """predict_server.go:207 — 0.025 to 1024 cores at 5% growth (values in
    milli-cores here: first bucket 25 mcores, max 1024000)."""
    return ExponentialBuckets.for_range(1024_000.0, 25.0, 1.0 + DEFAULT_BUCKET_GROWTH)


def default_memory_buckets() -> ExponentialBuckets:
    """predict_server.go:216 — 5 MiB to 2 TiB at 5% growth (values in MiB:
    first bucket 5, max 2^21)."""
    return ExponentialBuckets.for_range(float(1 << 21), 5.0, 1.0 + DEFAULT_BUCKET_GROWTH)


@struct.dataclass
class HistogramBank:
    """U decaying histograms over one shared bucket layout."""

    weights: jax.Array        # (U, B) float32 decayed bucket weights
    total: jax.Array          # (U,) float32 decayed total weight
    ref_time: jax.Array       # () float32 — decay reference timestamp (sec)
    half_life: jax.Array      # () float32 — seconds

    @classmethod
    def zeros(cls, num_models: int, buckets: ExponentialBuckets,
              half_life_sec: float) -> "HistogramBank":
        return cls(
            weights=jnp.zeros((num_models, buckets.num_buckets), jnp.float32),
            total=jnp.zeros((num_models,), jnp.float32),
            ref_time=jnp.float32(0.0),
            half_life=jnp.float32(half_life_sec),
        )

    @property
    def capacity(self) -> int:
        return self.weights.shape[0]


def _decay_factor(bank: HistogramBank, t: jnp.ndarray) -> jnp.ndarray:
    return jnp.exp2((t - bank.ref_time) / bank.half_life)


def add_samples(
    bank: HistogramBank,
    buckets: ExponentialBuckets,
    uids: jnp.ndarray,     # (K,) int32 model rows
    values: jnp.ndarray,   # (K,) float32 sample values
    t: jnp.ndarray,        # () float32 sample timestamp (sec)
    weight: float = 1.0,
    mask: jnp.ndarray | None = None,  # (K,) bool — which samples count
) -> HistogramBank:
    """Scatter a batch of samples into their models with decay weighting."""
    # Renormalize FIRST when the decay multiplier would get large: shift
    # ref_time forward by whole half-lives and scale existing weights down
    # 2^-k (the reference's shiftReferenceTimestamp, applied bank-wide) so the
    # per-sample factor below stays within float32.
    shift = jnp.floor(jnp.maximum(t - bank.ref_time, 0.0) / bank.half_life)
    need = shift >= 32.0
    scale = jnp.where(need, jnp.exp2(-shift), 1.0)
    bank = bank.replace(
        weights=bank.weights * scale,
        total=bank.total * scale,
        ref_time=jnp.where(need, bank.ref_time + shift * bank.half_life,
                           bank.ref_time),
    )

    w = _decay_factor(bank, t) * weight
    k = uids.shape[0]
    sample_w = jnp.full((k,), 1.0, jnp.float32) * w
    if mask is not None:
        sample_w = jnp.where(mask, sample_w, 0.0)
    b = buckets.find_bucket(values)
    weights = bank.weights.at[uids, b].add(sample_w)
    total = bank.total.at[uids].add(sample_w)
    return bank.replace(weights=weights, total=total)


def percentile(
    bank: HistogramBank, buckets: ExponentialBuckets, p: float
) -> jnp.ndarray:
    """(U,) float32: the p-percentile of every model (histogram.go:158).

    Empty histograms return 0.
    """
    starts = jnp.asarray(buckets.starts())          # (B,)
    w = bank.weights                                # (U, B)
    nb = buckets.num_buckets

    significant = w >= EPSILON
    any_sig = jnp.any(significant, axis=1)
    min_bucket = jnp.argmax(significant, axis=1)    # first >= eps (0 if none)
    # last significant bucket; 0 if none
    rev = jnp.argmax(significant[:, ::-1], axis=1)
    max_bucket = jnp.where(any_sig, nb - 1 - rev, 0)

    idx = jnp.arange(nb)[None, :]
    in_range = idx >= min_bucket[:, None]
    partial = jnp.cumsum(jnp.where(in_range, w, 0.0), axis=1)  # (U, B)
    threshold = p * bank.total                      # (U,)

    # first bucket (>= min) where partial >= threshold, else max_bucket
    hit = in_range & (partial >= threshold[:, None]) & (idx <= max_bucket[:, None])
    bucket = jnp.where(jnp.any(hit, axis=1), jnp.argmax(hit, axis=1), max_bucket)
    # return the next bucket's start (upper bound), last bucket its own start
    out = jnp.where(bucket < nb - 1, starts[jnp.minimum(bucket + 1, nb - 1)],
                    starts[bucket])
    return jnp.where(any_sig, out, 0.0)


def save_bank(bank: HistogramBank, path: str) -> None:
    """Checkpoint (prediction/checkpoint.go equivalent). Atomic: a crash
    mid-write must never leave a truncated archive at ``path``."""
    import os

    tmp = path + ".tmp.npz"
    np.savez_compressed(
        tmp,
        weights=np.asarray(bank.weights),
        total=np.asarray(bank.total),
        ref_time=np.asarray(bank.ref_time),
        half_life=np.asarray(bank.half_life),
    )
    os.replace(tmp, path)


def load_bank(path: str) -> HistogramBank:
    z = np.load(path)
    return HistogramBank(
        weights=jnp.asarray(z["weights"]),
        total=jnp.asarray(z["total"]),
        ref_time=jnp.asarray(z["ref_time"]),
        half_life=jnp.asarray(z["half_life"]),
    )
