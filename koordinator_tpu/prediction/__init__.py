"""Usage prediction: decaying-histogram peak forecasting.

Mirrors ``pkg/koordlet/prediction`` + ``pkg/util/histogram`` (SURVEY.md
section 2.5), rebuilt as a *bank*: instead of one Go histogram object per UID, all
models live in one (models x buckets) weight matrix so sample ingestion is a
scatter-add and percentile queries answer every model at once.
"""

from koordinator_tpu.prediction.histogram import (
    ExponentialBuckets,
    HistogramBank,
    default_cpu_buckets,
    default_memory_buckets,
)
from koordinator_tpu.prediction.predictor import (
    pod_reclaimable,
    priority_reclaimable,
)

__all__ = [
    "ExponentialBuckets",
    "HistogramBank",
    "default_cpu_buckets",
    "default_memory_buckets",
    "pod_reclaimable",
    "priority_reclaimable",
]
