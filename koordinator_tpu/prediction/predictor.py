"""Peak predictors: reclaimable-resource forecasts from the histogram bank.

Semantics from ``pkg/koordlet/prediction/peak_predictor.go``:

- podReclaimablePredictor (:154): per reclaimable prod pod,
    peak_cpu = p95(cpu) * (100 + safetyMargin)/100
    peak_mem = p98(mem) * (100 + safetyMargin)/100
    reclaimable += max(request - peak, 0);  unReclaimable += peak
  cold-start pods (younger than coldStartDuration) contribute 0;
  result = min(nodeAllocatable - unReclaimable (clamped >= 0), reclaimable).
- priorityReclaimablePredictor (:274): band-level histograms — peak of the
  priority tier plus system, reclaimable = tierRequest - peak.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.prediction.histogram import (
    ExponentialBuckets,
    HistogramBank,
    percentile,
)


def _apply_safety_margin(peak: jnp.ndarray, safety_margin_pct) -> jnp.ndarray:
    return peak * (100.0 + safety_margin_pct) / 100.0


def pod_reclaimable(
    cpu_bank: HistogramBank,
    mem_bank: HistogramBank,
    cpu_buckets: ExponentialBuckets,
    mem_buckets: ExponentialBuckets,
    pod_request_cpu: jnp.ndarray,   # (U,) float32 mcores
    pod_request_mem: jnp.ndarray,   # (U,) float32 MiB
    reclaimable_mask: jnp.ndarray,  # (U,) bool: prod, past cold start, running
    node_allocatable_cpu: jnp.ndarray,  # () float32
    node_allocatable_mem: jnp.ndarray,  # () float32
    safety_margin_pct: float = 10.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Node-level prod reclaimable (cpu, mem) from per-pod models.

    Returns two () float32 scalars (what NodeMetric reports as
    ProdReclaimableMetric, feeding mid_allocatable).
    """
    peak_cpu = _apply_safety_margin(
        percentile(cpu_bank, cpu_buckets, 0.95), safety_margin_pct
    )
    peak_mem = _apply_safety_margin(
        percentile(mem_bank, mem_buckets, 0.98), safety_margin_pct
    )
    m = reclaimable_mask
    reclaim_cpu = jnp.sum(
        jnp.where(m, jnp.maximum(pod_request_cpu - peak_cpu, 0.0), 0.0)
    )
    reclaim_mem = jnp.sum(
        jnp.where(m, jnp.maximum(pod_request_mem - peak_mem, 0.0), 0.0)
    )
    unreclaim_cpu = jnp.sum(jnp.where(m, peak_cpu, 0.0))
    unreclaim_mem = jnp.sum(jnp.where(m, peak_mem, 0.0))

    fix_cpu = jnp.maximum(node_allocatable_cpu - unreclaim_cpu, 0.0)
    fix_mem = jnp.maximum(node_allocatable_mem - unreclaim_mem, 0.0)
    return jnp.minimum(fix_cpu, reclaim_cpu), jnp.minimum(fix_mem, reclaim_mem)


def priority_reclaimable(
    cpu_bank: HistogramBank,
    mem_bank: HistogramBank,
    cpu_buckets: ExponentialBuckets,
    mem_buckets: ExponentialBuckets,
    tier_rows: jnp.ndarray,        # (K,) int32 rows of the tier + system models
    tier_request_cpu: jnp.ndarray, # () float32 sum of tier requests
    tier_request_mem: jnp.ndarray,
    node_allocatable_cpu: jnp.ndarray,  # () float32
    node_allocatable_mem: jnp.ndarray,  # () float32
    safety_margin_pct: float = 10.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Band-level reclaimable: tierRequest - (p95/p98 peak of tier+system),
    clamped by what the node can physically free —
    min(max(nodeAllocatable - peak, 0), reclaimable), peak_predictor.go:337-347.
    """
    peak_cpu = _apply_safety_margin(
        jnp.sum(percentile(cpu_bank, cpu_buckets, 0.95)[tier_rows]),
        safety_margin_pct,
    )
    peak_mem = _apply_safety_margin(
        jnp.sum(percentile(mem_bank, mem_buckets, 0.98)[tier_rows]),
        safety_margin_pct,
    )
    reclaim_cpu = jnp.maximum(tier_request_cpu - peak_cpu, 0.0)
    reclaim_mem = jnp.maximum(tier_request_mem - peak_mem, 0.0)
    fix_cpu = jnp.maximum(node_allocatable_cpu - peak_cpu, 0.0)
    fix_mem = jnp.maximum(node_allocatable_mem - peak_mem, 0.0)
    return jnp.minimum(fix_cpu, reclaim_cpu), jnp.minimum(fix_mem, reclaim_mem)
