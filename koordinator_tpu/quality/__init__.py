"""Solve-quality subsystem: the opt-in high-quality packing mode.

Two engines behind ``Scheduler(quality_mode=...)`` (ROADMAP item 4):

- :mod:`koordinator_tpu.quality.lp_pack` — an LP-relaxation of the
  pods x nodes x resource-dims packing problem (integer dual-price
  ascent + iterative masked rounding) that replaces the greedy top-k
  batch solve for escalated rounds, never admitting an assignment the
  greedy path's capacity/quota oracles would reject;
- :mod:`koordinator_tpu.quality.topo_gang` — rank-aware gang placement
  that scores candidate slot sets by network-topology distance so
  MPI-style gangs land on minimal-diameter subtrees.

See docs/solve_quality.md for the formulation and the feasibility
argument.
"""

from koordinator_tpu.quality.lp_pack import (  # noqa: F401
    ASCENT_ITERS,
    ROUNDING_ITERS,
    lp_pack_assign,
)
from koordinator_tpu.quality.topo_gang import (  # noqa: F401
    gang_topo_diameter,
    plan_gang_placement_quality,
    rank_candidates_quality,
)

QUALITY_MODES = ("off", "lp", "auto")
