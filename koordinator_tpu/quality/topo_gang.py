"""Rank-aware gang packing by network-topology distance (quality mode).

The baseline planner (ops/network_topology.plan_gang_placement) ranks
candidate subtrees by the reference's lexicographic rule — existing
peers first, then tighter fit, then score — and commits the first
candidate that distributes fully.  "Rank-Aware Resource Scheduling for
Tightly-Coupled MPI Workloads on Kubernetes" (PAPERS.md) shows gang
quality is dominated by network-topology DISTANCE between the ranks,
not by per-node score: a gang that fits one rack should never span two
because a peer pod happened to sit on the wider subtree.

This module adds the distance-first plan:

- :func:`gang_topo_diameter` — a jitted kernel scoring a slot set by
  its topology diameter (max pairwise hop distance through the lowest
  common ancestor), the metric the bench and the flight recorder
  report;
- :func:`rank_candidates_quality` — candidate ranking that puts
  minimal-diameter subtrees first: deeper layer (smaller subtree
  diameter bound), then tighter fit, then existing peers, then score —
  the baseline's existing-peers-first order demoted below distance;
- :func:`plan_gang_placement_quality` — the planner: rank candidates
  distance-first, realize plans for a small beam of satisfiable
  candidates through the SAME host-side distributor the baseline uses,
  and commit the plan with the smallest REALIZED diameter (tie: fewest
  distinct nodes, then candidate rank).  Feasibility is untouched —
  offer slots, layer multiples and eligibility all come from the
  baseline kernels, so a quality plan is always a plan the baseline
  solver would also have accepted.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.network_topology import (
    TopologyArrays,
    TopologyRequirements,
    _ancestor_chain_keys,
    _distribute_host,
    gang_candidate_prep,
)
from koordinator_tpu.state.cluster_state import ClusterState, PodBatch

#: how many satisfiable candidates the quality planner realizes before
#: committing the minimal-diameter plan — the distribution walk is
#: host-side O(T), so a small beam costs microseconds
PLAN_BEAM = 4


# koordlint: shape[ret0: MxM i32 0..64]
def _pairwise_lca_layer(paths: jnp.ndarray) -> jnp.ndarray:
    """(M, M) int32: the layer of each node pair's lowest common
    ancestor — the longest shared prefix of their (M, L) ancestor
    chains (``node_path`` rows are root-first, so a cumprod of
    per-layer equality counts the shared prefix)."""
    eq = paths[:, None, :] == paths[None, :, :]
    return jnp.cumprod(eq.astype(jnp.int32), axis=-1).sum(axis=-1) - 1


# koordlint: shape[node_rows: P i32 rep, valid: P bool rep]
def gang_topo_diameter(node_rows: jnp.ndarray, valid: jnp.ndarray,
                       topo: TopologyArrays) -> jnp.ndarray:
    """int32 scalar: the topology diameter of a placed slot set — the
    maximum pairwise hop distance ``2 * (leaf_layer - lca_layer)``
    over valid members.  0 for a single-node (or empty) placement.

    O(M^2 * L) on gang-sized M: the jitted quality observable behind
    ``bench_recall``'s gang metrics and the planner's realized-plan
    scoring.
    """
    n = topo.node_path.shape[0]
    rows = jnp.clip(node_rows, 0, n - 1)
    paths = topo.node_path[rows]                      # (M, L)
    lca = _pairwise_lca_layer(paths)
    leaf_layer = topo.num_layers - 1
    dist = 2 * (leaf_layer - lca)
    ok = valid & (node_rows >= 0) & (node_rows < n)
    pair_ok = ok[:, None] & ok[None, :]
    return jnp.max(jnp.where(pair_ok, dist, 0))


def rank_candidates_quality(
    topo: TopologyArrays,
    candidates: jnp.ndarray,
    topo_slots: jnp.ndarray,
    topo_scores: jnp.ndarray,
    topo_existing: jnp.ndarray,
) -> jnp.ndarray:
    """Topology-distance-first candidate order (best first).

    Primary: deeper layer — a deeper subtree root bounds the realized
    diameter tighter (``2 * (L-1 - layer)``).  Then tighter fit (fewer
    constrained slots — the packing term), then existing peers up the
    chain (the baseline's primary, demoted), then score, then id.
    """
    ex = _ancestor_chain_keys(topo, topo_existing)
    keys = [jnp.arange(topo.num_topo), -topo_scores]
    for layer in range(topo.num_layers - 1, -1, -1):
        keys.append(-ex[:, layer])
    keys.append(topo_slots)           # tighter fit first
    keys.append(-topo.topo_layer)     # deeper = smaller diameter bound
    keys.append(~candidates)          # candidates first (primary)
    return jnp.lexsort(keys)


def plan_diameter(plan: np.ndarray, topo: TopologyArrays) -> int:
    """Host-side diameter of a (P,) planned-node vector (-1 rows are
    non-members) — the realized-plan score the beam minimizes."""
    rows = np.asarray(plan)
    members = rows[rows >= 0]
    if members.size == 0:
        return 0
    paths = np.asarray(topo.node_path)[members]       # (M, L)
    eq = paths[:, None, :] == paths[None, :, :]
    lca = np.cumprod(eq, axis=-1).sum(axis=-1) - 1
    return int(2 * ((topo.num_layers - 1) - lca.min()))


def plan_gang_placement_quality(
    state: ClusterState,
    pods: PodBatch,
    gang_mask: np.ndarray,
    topo: TopologyArrays,
    req: TopologyRequirements,
    node_scores: jnp.ndarray | None = None,
    node_existing: jnp.ndarray | None = None,
    cfg=None,
    beam: int = PLAN_BEAM,
) -> np.ndarray:
    """Minimal-diameter placement plan for one gang: (P,) int32 planned
    node per member (-1 for non-members / infeasible).

    Pipeline parity with the baseline planner: the whole candidate
    prep runs through the SHARED ``gang_candidate_prep`` (offer slots,
    tree aggregation, layer-multiple rounding, eligibility), so every
    quality plan is feasible for the baseline solver.  Only the
    candidate order (distance-first) and the commit rule (best
    realized diameter over a small beam) differ.
    """
    member_idx, desired, mults, t_slots, t_scores, t_existing, cand = (
        gang_candidate_prep(state, pods, gang_mask, topo, req,
                            node_scores, node_existing, cfg))
    ranked = rank_candidates_quality(topo, cand, t_slots, t_scores,
                                     t_existing)

    plan = np.full(pods.capacity, -1, np.int32)
    cand_np = np.asarray(cand)
    if not cand_np.any():
        return plan
    parent_np = np.asarray(topo.topo_parent)
    layer_np = np.asarray(topo.topo_layer)
    t2n = np.asarray(topo.topo_to_node)
    slots_np = np.asarray(t_slots)
    scores_np = np.asarray(t_scores)
    exist_np = np.asarray(t_existing)
    mults_np = np.asarray(mults)

    # realize up to `beam` satisfiable candidates and keep the plan with
    # the smallest realized diameter (tie: fewest nodes, then rank)
    best: tuple | None = None
    realized = 0
    for rank_pos, tid in enumerate(np.asarray(ranked)):
        if not cand_np[tid] or realized >= beam:
            break
        nodes, counts = _distribute_host(
            parent_np, layer_np, t2n, slots_np, scores_np, exist_np,
            int(tid), desired, mults_np,
        )
        if not nodes:
            continue
        realized += 1
        trial = np.full(pods.capacity, -1, np.int32)
        flat = np.repeat(nodes, counts)[: len(member_idx)]
        trial[member_idx[: len(flat)]] = flat
        key = (plan_diameter(trial, topo), len(set(nodes)), rank_pos)
        if best is None or key < best[0]:
            best = (key, trial)
    return best[1] if best is not None else plan
