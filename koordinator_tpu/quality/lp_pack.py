"""LP-relaxation packing: dual-price ascent + iterative masked rounding.

The greedy/top-k batch solve (ops/batch_assign.py) is the throughput
path; this module is the QUALITY path ("Priority Matters: Optimising
Kubernetes Clusters Usage with Constraint-Based Pod Packing",
PAPERS.md).  It solves the LP relaxation of the packing problem

    max  sum_{p,n} x_{pn} * score_{pn}
    s.t. sum_p x_{pn} * req_{pr} <= free_{nr}     (capacity, per dim)
         sum_n x_{pn} <= 1,  x >= 0               (one node per pod)

by projected subgradient ascent on the DUAL of the capacity
constraints: each node carries an integer price, each pod's reduced
utility is its score minus the node's price, pods sit on their
argmax-utility feasible node, and prices rise on oversubscribed nodes
until contention clears (the tensor form of an auction/price-ascent
LP solver — every step is a masked integer tensor op, so results are
bit-identical across mesh shapes by construction).

Iterative masked rounding then fixes the HIGHEST-CONFIDENCE rows: a
pod whose chosen node is uncontended (the full active demand on that
node fits its headroom) is accepted and charged; contended pods stay
relaxed and keep ascending prices against the shrunk residual.  The
final iteration forces a priority-prefix resolution so bounded
iteration count is a hard guarantee, and EVERY acceptance — early or
final — goes through the exact same kernels the greedy path uses
(``ops/batch_assign._prefix_accept_choice`` for capacity,
``quota_admission_mask``/``_quota_prefix_accept``/``charge_quota_batch``
for quota), so this mode can never admit an assignment greedy's
oracle would reject.

Why it packs better than greedy at tight shapes: greedy fixes every
pod in one priority sweep against static scores, so a high-priority
pod happily takes the last node a lower-priority pod NEEDED (score
order is blind to who else fits where).  Price ascent makes contended
capacity expensive first, so pods WITH alternatives drain away from
nodes that are some pod's only option before anything is fixed.

The whole module is integer arithmetic end to end (int32 scores,
prices, demands): integer max/sum reductions are associative, which is
what makes the sharded twin (``parallel/sharded.sharded_lp_pack_assign``)
bit-identical to the single-device solve at every mesh width.  The
``axis`` parameter threads the two executions through ONE body: with
``axis=None`` the collectives degenerate to identities; under
``shard_map`` they are the same owner-psum / all-gather-merge patterns
the greedy sharded path proved exact (parallel/sharded.py docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from koordinator_tpu.ops import batch_assign as ba
from koordinator_tpu.ops.assignment import score_pods
from koordinator_tpu.quota.admission import (
    charge_quota_batch,
    quota_admission_mask,
)

#: price-ascent iterations per rounding phase — each is one O(P·N)
#: argmax + one integer demand reduction
ASCENT_ITERS = 8
#: rounding phases (the bounded-iteration guarantee): phase i fixes the
#: uncontended rows, the LAST phase forces priority-prefix resolution
ROUNDING_ITERS = 6
#: price bump per overloaded ascent step, scaled by the node's overload
#: fraction: bump = ceil(overload * PRICE_GAIN / allocatable)
PRICE_GAIN = 512
#: floor on the bump of any overloaded node — prices must move even when
#: the overload fraction rounds to zero
PRICE_MIN_STEP = 16
#: price ceiling: past 2*_SCORE_CLIP the node's utility is already
#: saturated at 0 for every pod, so higher prices change nothing; the
#: cap keeps price + clip arithmetic far from int32 limits
PRICE_CAP = 4 * ba._SCORE_CLIP
#: overload clamp before the PRICE_GAIN multiply (int32 headroom: a 50k
#: pod stampede's demand sum times the gain must not overflow)
_OVERLOAD_CLIP = 1 << 20


# koordlint: shape[ret0: PxN i32 -1..1073741823, ret1: PxN i32 0..1073741823]
def _priced_keys(base, fits, prices, rot_id, pos, n_valid, n_total):
    """(key, tb) ranking of price-adjusted utilities on the SAME packed /
    wide integer key scale as the greedy solver (``ba._rank_parts``:
    ``ba._packed_regime``/``ba._TB_BITS`` gate the packing identically).

    ``u = clip(base - prices, -CLIP, CLIP) + CLIP`` keeps the full
    ordering of priced-out columns (a plain clip at 0 would collapse
    them); ``u >> 1`` fits the packed key's quantized-score field.  One
    utility bit of precision is the entire cost.

    The tie-break rotates over COMPACTED valid-node positions (``pos``,
    modulus ``n_valid``) rather than raw padded row ids: with the greedy
    path's ``(ids - rot) % n_total`` form, heavy row padding parks most
    pods' preferred offsets in invalid-id space, which all wrap to the
    same low valid node — identical pods then herd onto one copy of an
    identical node and the price ascent limit-cycles between copies
    instead of splitting them.  With zero padding ``pos == ids`` and
    ``n_valid == n_total``, so this tb is bit-identical to
    ``_rank_parts``'s; greedy is immune either way (one sweep, no
    re-bidding), so its key stays untouched.
    """
    u = jnp.clip(base - prices[None, :], -ba._SCORE_CLIP,
                 ba._SCORE_CLIP) + ba._SCORE_CLIP
    rot = (rot_id.astype(jnp.int32) * 7919)[:, None]
    tb = (n_total - 1) - ((pos[None, :] - rot) % n_valid)
    q = u >> 1
    key = ((q << ba._TB_BITS) | tb) if ba._packed_regime(n_total) else q
    return jnp.where(fits, key, -1), tb


def _local_best(key, tb, node_ids):
    """Per-pod best LOCAL column by (key, tb) rank — the two-stage
    argmax of ``ba._choose_candidate``, returning the winning (key, tb,
    global node id) triple so winners can merge across shards on one
    scale.  Rank pairs are unique per pod (tb is a permutation of node
    ids), so the winner is order-deterministic in both key regimes."""
    bkey = jnp.max(key, axis=1)
    col = jnp.argmax(jnp.where(key == bkey[:, None], tb, -1), axis=1)
    return (bkey, jnp.take_along_axis(tb, col[:, None], axis=1)[:, 0],
            node_ids[col])


def _merge_best(bkey, btb, bnode, axis):
    """Cross-shard merge of per-shard winners: gather the (P,) triples
    to (P, D) and re-run the two-stage argmax.  The global best of a
    union of per-shard bests equals the global best of all columns, and
    (key, tb) pairs of distinct nodes are unique per pod, so the merged
    winner is bit-identical to a full-width argmax.  ``axis=None`` is
    the degenerate single-device merge (D = 1)."""
    if axis is None:
        g_key, g_tb, g_node = (bkey[:, None], btb[:, None], bnode[:, None])
    else:
        g_key = jax.lax.all_gather(bkey, axis, axis=1)
        g_tb = jax.lax.all_gather(btb, axis, axis=1)
        g_node = jax.lax.all_gather(bnode, axis, axis=1)
    wkey = jnp.max(g_key, axis=1)
    d = jnp.argmax(jnp.where(g_key == wkey[:, None], g_tb, -1), axis=1)
    node = jnp.take_along_axis(g_node, d[:, None], axis=1)[:, 0]
    return jnp.where(wkey >= 0, node, -1), wkey >= 0


# koordlint: shape[st_local: NxR i32 nodes]
def _lp_core(st_local, pods, quota, cfg, *, n_total, ascent_iters,
             rounding_iters, axis=None):
    """The shared single-device / shard-local LP solve body.

    ``st_local`` is the full state (``axis=None``) or one shard's node
    rows (under ``shard_map`` over the nodes axis); pods/quota are
    replicated.  Returns (assignments, requested_local, quota, iters):
    assignments/quota/iters replicated, requested node-sharded like the
    input state.
    """
    n_loc = st_local.capacity
    off = (jnp.int32(0) if axis is None
           else jax.lax.axis_index(axis).astype(jnp.int32) * n_loc)
    node_ids = off + jnp.arange(n_loc, dtype=jnp.int32)
    p = pods.capacity
    rot = pods.rot_id

    def psum(x):
        return x if axis is None else jax.lax.psum(x, axis)

    # compacted global valid-node positions for the tie-break rotation
    # (see _priced_keys): exclusive local cumsum + this shard's global
    # offset.  All-integer and globally consistent, so mesh invariance
    # holds; capacity is static so the gather shape is too.
    valid_i = st_local.node_valid.astype(jnp.int32)
    loc_cnt = jnp.sum(valid_i)
    if axis is None:
        shard_off = jnp.int32(0)
        n_valid = loc_cnt
    else:
        counts = jax.lax.all_gather(loc_cnt, axis)          # (D,)
        d = jax.lax.axis_index(axis)
        shard_off = jnp.sum(jnp.where(
            jnp.arange(counts.shape[0]) < d, counts, 0)).astype(jnp.int32)
        n_valid = jnp.sum(counts).astype(jnp.int32)
    pos = shard_off + jnp.cumsum(valid_i) - valid_i
    n_valid = jnp.maximum(n_valid, 1)

    scores, feasible = score_pods(st_local, pods, cfg)     # (P, n_loc)
    base = jnp.clip(scores, 0, ba._SCORE_CLIP)
    order = jnp.lexsort((jnp.arange(p), -pods.priority))
    req = pods.requests
    alloc_den = jnp.maximum(st_local.node_allocatable, 1)

    def seg_demand(choice_loc, own_act):
        """(n_loc, R) active demand on this shard's nodes — exact
        integer segment sum (unowned/inactive rows hit the overflow
        bucket)."""
        seg = jnp.where(own_act, choice_loc, n_loc)
        req_act = jnp.where(own_act[:, None], req, 0)
        return jax.ops.segment_sum(req_act, seg,
                                   num_segments=n_loc + 1)[:n_loc]

    def outer_body(carry):
        i, prices, requested, assignments, active, qstate = carry
        free_loc = jnp.where(
            st_local.node_valid[:, None],
            st_local.node_allocatable - requested, 0)
        # the residual problem's feasible-fit mask: capacity only ever
        # shrinks within a solve, so a pod with no fitting column now
        # can never gain one — drop it so the loop converges early
        fits = feasible & jnp.all(
            (req[:, None, :] <= free_loc[None, :, :])
            | (req[:, None, :] == 0), axis=-1)
        active = active & (psum(jnp.any(fits, axis=1).astype(jnp.int32))
                           > 0)

        qmask = (jnp.ones(p, bool) if qstate is None
                 else quota_admission_mask(qstate, req, pods.quota_id,
                                           pods.non_preemptible))

        def choose(prices_now):
            key, tb = _priced_keys(base, fits, prices_now, rot,
                                   pos, n_valid, n_total)
            choice, has = _merge_best(*_local_best(key, tb, node_ids),
                                      axis)
            loc = choice - off
            own = (loc >= 0) & (loc < n_loc)
            return choice, has, jnp.clip(loc, 0, n_loc - 1), own

        def ascent_body(_, prices_now):
            choice, has, loc_c, own = choose(prices_now)
            act = active & has & qmask
            demand = seg_demand(loc_c, own & act)
            over = jnp.clip(demand - free_loc, 0, _OVERLOAD_CLIP)
            bump_r = (over * PRICE_GAIN + alloc_den - 1) // alloc_den
            bump = jnp.max(bump_r, axis=-1)
            bump = jnp.where(jnp.any(over > 0, axis=-1),
                             jnp.maximum(bump, PRICE_MIN_STEP), 0)
            return jnp.clip(prices_now + bump, 0, PRICE_CAP)

        prices = jax.lax.fori_loop(0, ascent_iters, ascent_body, prices)

        # -- masked rounding: fix the high-confidence (uncontended)
        # rows; the last phase forces priority-prefix resolution so the
        # iteration bound is hard
        choice, has, loc_c, own = choose(prices)
        act = active & has & qmask
        demand = seg_demand(loc_c, own & act)
        tot_choice = psum(jnp.where((own & act)[:, None],
                                    demand[loc_c], 0))       # (P, R)
        choice_free = psum(jnp.where((own & act)[:, None],
                                     free_loc[loc_c], 0))
        confident = ~jnp.any(tot_choice > choice_free, axis=-1)
        last = (i + 1) >= rounding_iters
        act_round = act & (confident | last)

        # the SAME acceptance oracle as the greedy rounds: priority
        # prefix fit against the owner-psum'd headroom, then the quota
        # chain's prefix admission
        round_free = psum(jnp.where((own & act_round)[:, None],
                                    free_loc[loc_c], 0))
        accept = ba._prefix_accept_choice(choice, req, round_free,
                                          n_total, order, act_round)
        if qstate is not None:
            accept = accept & ba._quota_prefix_accept(
                qstate, req, pods, order, act_round)

        add = jnp.where((accept & own)[:, None], req, 0)
        requested = requested.at[loc_c].add(add)
        new_quota = qstate
        if new_quota is not None:
            new_quota = charge_quota_batch(
                new_quota, req, pods.quota_id, accept,
                pods.non_preemptible)
        return (i + 1, prices,
                requested,
                jnp.where(accept, choice, assignments),
                active & ~accept,
                new_quota)

    def cond(carry):
        i, _, _, _, active, _ = carry
        return (i < rounding_iters) & jnp.any(active)

    active0 = pods.valid & (psum(jnp.any(feasible, axis=1)
                                 .astype(jnp.int32)) > 0)
    carry = (jnp.int32(0),
             jnp.zeros(n_loc, jnp.int32),
             st_local.node_requested,
             jnp.full(p, -1, jnp.int32),
             active0,
             quota)
    iters, _, requested, assignments, _, new_quota = jax.lax.while_loop(
        cond, outer_body, carry)
    return assignments, requested, new_quota, iters


def lp_pack_assign(state, pods, cfg, quota=None, *,
                   ascent_iters: int = ASCENT_ITERS,
                   rounding_iters: int = ROUNDING_ITERS):
    """High-quality batch assignment by LP-relaxation packing.

    Same contract as ``ops/batch_assign.batch_assign`` — returns
    (assignments, new_state, new_quota) plus the rounding-iteration
    count actually executed (the ``quality_iterations`` observable).
    ``assignments`` is (P,) int32 with -1 for unplaced pods; node and
    quota accounting are charged through the greedy path's own kernels,
    so feasibility is exact by construction.
    """
    a, requested, new_quota, iters = _lp_core(
        state, pods, quota, cfg, n_total=state.capacity,
        ascent_iters=ascent_iters, rounding_iters=rounding_iters,
        axis=None)
    return a, state.replace(node_requested=requested), new_quota, iters
