"""Pod-resources reverse proxy (PodResourcesProxy feature).

Kubelet's pod-resources API only reports devices allocated by device
plugins — koord-scheduler's fine-grained allocations live in the
``device-allocated`` pod annotation and are invisible to monitoring
agents (DCGM exporters etc.) that read that API.  The reference interposes
a gRPC proxy at the kubelet socket and enriches List responses with the
koordinator allocations
(`pkg/koordlet/statesinformer/impl/states_pod_resources.go:141 List`,
``:155 fillPodDevicesAllocatedByKoord``; the generic byte-level proxy is
`pkg/util/httputil/reverseproxy.go`).

This module is the same interposition for the repo: an informer plugin
that wraps an upstream pod-resources listing (the kubelet stub seam) and
merges each pod's annotation allocations into its first container's
device list, exactly where the reference splices them.  It serves over
the HTTP gateway (``GET /v1/podresources`` when attached) — the repo's
language-neutral boundary — instead of re-implementing kubelet's gRPC.

Response dialect (JSON-friendly mirror of podresources/v1):

    {"pod_resources": [{"name", "namespace",
                        "containers": [{"name", "devices": [
                            {"resource_name", "device_ids": [...]}]}]}]}
"""

from __future__ import annotations

from typing import Callable, Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.features import KOORDLET_GATES

#: DeviceType -> resource name (device_share.go:44,48)
DEVICE_RESOURCE_NAMES = {
    "gpu": "nvidia.com/gpu",
    "rdma": "koordinator.sh/rdma",
}


class PodResourcesProxy:
    """Enrich an upstream pod-resources listing with koord allocations.

    ``upstream_list_fn`` returns the kubelet response dict (empty dict
    when kubelet is unreachable — the proxy then still reports
    koord-allocated devices for known pods); ``states`` is the
    StatesInformer whose pods carry the device-allocated annotation.
    """

    def __init__(self, states,
                 upstream_list_fn: Optional[Callable[[], dict]] = None):
        self.states = states
        self.upstream_list_fn = upstream_list_fn or (lambda: {})

    def enabled(self) -> bool:
        return KOORDLET_GATES.enabled("PodResourcesProxy")

    def list(self) -> dict:
        response = self.upstream_list_fn() or {}
        # DEEP-copy the container/device structure: the upstream fn may
        # return a cached long-lived dict (kubelet stubs do), and merging
        # in place would duplicate koord devices on every call and race
        # concurrent gateway requests
        entries = [
            {**e, "containers": [
                {**c, "devices": [dict(d) for d in c.get("devices", [])]}
                for c in e.get("containers", [])
            ]}
            for e in response.get("pod_resources", [])
        ]
        by_key = {(e.get("namespace", ""), e.get("name", "")): e
                  for e in entries}
        merged: set[tuple[str, str]] = set()
        for pod in self.states.get_all_pods():
            allocations = ext.get_device_allocations(pod.annotations or {})
            if not allocations:
                continue
            key = (pod.namespace, pod.name)
            if key in merged:
                # pod recreation can briefly hold two uids under one
                # (namespace, name); merging both would double-report the
                # same container's devices — keep the first
                continue
            merged.add(key)
            entry = by_key.get(key)
            if entry is None:
                # kubelet hasn't listed the pod (yet): surface the koord
                # allocation anyway so monitoring never misses a device
                entry = {"name": pod.name, "namespace": pod.namespace,
                         "containers": [{"name": "", "devices": []}]}
                by_key[key] = entry
                entries.append(entry)
            containers = entry.setdefault("containers", [])
            if not containers:
                containers.append({"name": "", "devices": []})
            devices = containers[0].setdefault("devices", [])
            for device_type, allocs in sorted(allocations.items()):
                ids = []
                for alloc in allocs:
                    # RDMA virtual functions report bus ids, full devices
                    # their id/minor (fillPodDevicesAllocatedByKoord)
                    vfs = (alloc.get("extension") or {}).get(
                        "virtual_functions") or []
                    if vfs:
                        ids.extend(str(vf.get("bus_id", "")) for vf in vfs)
                    else:
                        ids.append(str(alloc.get(
                            "id", alloc.get("minor", ""))))
                devices.append({
                    "resource_name": DEVICE_RESOURCE_NAMES.get(
                        device_type, device_type),
                    "device_ids": ids,
                })
            devices.sort(key=lambda d: d["resource_name"])
        # extra top-level upstream fields pass through untouched
        return {**response, "pod_resources": entries}
