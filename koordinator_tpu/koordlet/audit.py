"""Node-local audit trail of every resource mutation (reference:
``pkg/koordlet/audit/auditor.go:53`` — rotating log files + HTTP query).

Events are JSON lines in size-rotated files under the agent's var-run dir;
:meth:`Auditor.query` serves the reader path (newest first), which the debug
HTTP endpoint exposes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterator


class Auditor:
    def __init__(self, log_dir: str, max_file_bytes: int = 1 << 20,
                 max_files: int = 8, clock=time.time):
        self.log_dir = log_dir
        self.max_file_bytes = max_file_bytes
        self.max_files = max_files
        self._clock = clock
        self._lock = threading.Lock()
        os.makedirs(log_dir, exist_ok=True)

    @property
    def _active(self) -> str:
        return os.path.join(self.log_dir, "audit.log")

    def _rotated(self, i: int) -> str:
        return os.path.join(self.log_dir, f"audit.log.{i}")

    def log(self, group: str, operation: str, target: str, detail: dict | None = None):
        """Append one event; rotates when the active file passes the cap."""
        # detail first: canonical fields always win on key collision.
        event = {
            **(detail or {}),
            "time": self._clock(),
            "group": group,          # e.g. "cgroup", "resctrl", "eviction"
            "operation": operation,  # e.g. "update", "evict"
            "target": target,        # e.g. cgroup path or pod uid
        }
        line = json.dumps(event, sort_keys=True) + "\n"
        with self._lock:
            try:
                size = os.path.getsize(self._active)
            except OSError:
                size = 0
            if size + len(line) > self.max_file_bytes and size > 0:
                self._rotate()
            with open(self._active, "a") as f:
                f.write(line)

    def _rotate(self) -> None:
        for i in range(self.max_files - 1, 0, -1):
            src = self._rotated(i - 1) if i > 1 else self._active
            if os.path.exists(src):
                os.replace(src, self._rotated(i))

    def _iter_lines(self) -> Iterator[str]:
        files = [self._active] + [
            self._rotated(i) for i in range(1, self.max_files)
        ]
        for path in files:
            try:
                # open directly instead of exists-then-open: a concurrent
                # _rotate renames files between the two, and the resulting
                # FileNotFoundError escaped to query callers (the file's
                # lines are still served under their rotated name)
                f = open(path)
            except FileNotFoundError:
                continue
            with f:
                for line in reversed(f.readlines()):
                    yield line

    def query(self, limit: int = 100, group: str | None = None) -> list[dict]:
        """Newest-first events, optionally filtered by group."""
        out: list[dict] = []
        for line in self._iter_lines():
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if group is not None and event.get("group") != group:
                continue
            out.append(event)
            if len(out) >= limit:
                break
        return out
