"""koordlet: the per-node agent.

TPU-native rebuild of the reference's ``pkg/koordlet/`` (46.5k LoC Go).
The agent is I/O-bound kernel programming — cgroups, procfs, resctrl — so it
stays host-side Python + a C++ fast path (``native/``), while all the math it
feeds (suppression levels, percentile aggregation, batch allocatable) reuses
the same tensor kernels as the central solver.

Module map (reference parity):

- ``system``          <- pkg/koordlet/util/system (L0 cgroup/resctrl/PSI layer)
- ``resourceexecutor``<- pkg/koordlet/resourceexecutor (cached, audited writer)
- ``metriccache``     <- pkg/koordlet/metriccache (TSDB)
- ``metricsadvisor``  <- pkg/koordlet/metricsadvisor (collectors)
- ``statesinformer``  <- pkg/koordlet/statesinformer (state registry + fan-out)
- ``qosmanager``      <- pkg/koordlet/qosmanager (strategy loops)
- ``runtimehooks``    <- pkg/koordlet/runtimehooks (container lifecycle hooks)
- ``pleg``            <- pkg/koordlet/pleg
- ``audit``           <- pkg/koordlet/audit
- ``daemon``          <- pkg/koordlet/koordlet.go (assembly)
"""
