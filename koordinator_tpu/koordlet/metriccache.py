"""Node-local metric store (reference: ``pkg/koordlet/metriccache/`` — an
embedded Prometheus TSDB ``tsdb_storage.go:29`` plus an in-memory KV
``metric_cache.go:58-76``).

TPU-native redesign: instead of a general TSDB, each (metric, label-set)
series is a fixed-capacity numpy ring buffer of (ts, value). Windowed queries
return contiguous views, and the aggregators (avg/latest/count/percentiles)
are vectorized — the NodeMetric reporter's p50/p90/p95/p99 aggregation
(``statesinformer/impl/states_nodemetric.go``) is one ``np.quantile`` call.
The same buffers feed the prediction histograms without copies.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterable, Mapping, Optional

import numpy as np

# Canonical metric names (metric_resources.go equivalents).
NODE_CPU_USAGE = "node_cpu_usage"            # cores (float)
NODE_MEMORY_USAGE = "node_memory_usage"      # bytes
POD_CPU_USAGE = "pod_cpu_usage"              # labels: pod_uid
POD_MEMORY_USAGE = "pod_memory_usage"
CONTAINER_CPU_USAGE = "container_cpu_usage"  # labels: pod_uid, container_id
CONTAINER_MEMORY_USAGE = "container_memory_usage"
CONTAINER_CPU_THROTTLED = "container_cpu_throttled_ratio"
BE_CPU_USAGE = "be_cpu_usage"
SYS_CPU_USAGE = "sys_cpu_usage"
SYS_MEMORY_USAGE = "sys_memory_usage"
NODE_PERCPU_USAGE = "node_percpu_usage"      # cores; labels: cpu
NODE_CPI_FIELD = "node_cpi"
POD_CPI = "pod_cpi"                          # labels: pod_uid
CONTAINER_CPI = "container_cpi"              # labels: pod_uid, container_id
PSI_CPU_SOME_AVG10 = "psi_cpu_some_avg10"
PSI_MEM_FULL_AVG10 = "psi_mem_full_avg10"
PSI_IO_FULL_AVG10 = "psi_io_full_avg10"
COLD_PAGE_BYTES = "cold_page_bytes"
PAGE_CACHE_BYTES = "page_cache_bytes"
HOST_APP_CPU_USAGE = "host_app_cpu_usage"    # labels: app
HOST_APP_MEMORY_USAGE = "host_app_memory_usage"
NODE_DISK_READ_RATE = "node_disk_read_bytes_rate"    # labels: device
NODE_DISK_WRITE_RATE = "node_disk_write_bytes_rate"  # labels: device
NODE_DISK_IO_UTIL = "node_disk_io_util_pct"          # labels: device
RESCTRL_LLC_OCCUPANCY = "resctrl_llc_occupancy"      # labels: group
RESCTRL_MBM_TOTAL_RATE = "resctrl_mbm_total_bytes_rate"  # labels: group
ACCEL_CORE_USAGE = "accel_core_usage_pct"    # labels: minor, uuid, type
ACCEL_MEM_USED = "accel_mem_used_bytes"      # labels: minor, uuid, type
HAMI_VGPU_CORE_USAGE = "hami_vgpu_core_usage_pct"  # labels: uuid, pod_uid
HAMI_VGPU_MEM_USED = "hami_vgpu_mem_used_bytes"    # labels: uuid, pod_uid
#: KV keys (metric_cache KV store)
KV_NODE_CPU_INFO = "node_cpu_info"
KV_NODE_NUMA_INFO = "node_numa_info"


def _series_key(metric: str, labels: Mapping[str, str] | None) -> tuple:
    return (metric, tuple(sorted((labels or {}).items())))


class _Ring:
    __slots__ = ("ts", "values", "head", "count")

    def __init__(self, capacity: int):
        self.ts = np.zeros(capacity, np.float64)
        self.values = np.zeros(capacity, np.float64)
        self.head = 0
        self.count = 0

    def append(self, ts: float, value: float) -> None:
        cap = len(self.ts)
        self.ts[self.head] = ts
        self.values[self.head] = value
        self.head = (self.head + 1) % cap
        self.count = min(self.count + 1, cap)

    def window(self, start: float, end: float) -> tuple[np.ndarray, np.ndarray]:
        ts, vals = self.chronological()
        mask = (ts >= start) & (ts <= end)
        return ts[mask], vals[mask]

    def oldest_ts(self) -> float | None:
        """O(1) timestamp of the oldest live sample; None when empty."""
        if not self.count:
            return None
        cap = len(self.ts)
        return float(self.ts[self.head] if self.count == cap
                     else self.ts[0])

    def chronological(self) -> tuple[np.ndarray, np.ndarray]:
        """Oldest-first views of the live samples."""
        cap = len(self.ts)
        if self.count < cap:
            return self.ts[: self.count], self.values[: self.count]
        idx = np.arange(self.head, self.head + cap) % cap
        return self.ts[idx], self.values[idx]

    def drain_older(self, cutoff: float) -> tuple[np.ndarray, np.ndarray]:
        """Remove and return every sample STRICTLY older than ``cutoff``
        (a sample exactly at the horizon is kept, matching the
        query-time retention boundary); the ring is repacked in place."""
        ts, vals = self.chronological()
        keep = ts >= cutoff
        if keep.all():
            return np.empty(0), np.empty(0)
        drained = ts[~keep].copy(), vals[~keep].copy()
        kept_ts, kept_vals = ts[keep].copy(), vals[keep].copy()
        n = len(kept_ts)
        self.ts[:n] = kept_ts
        self.values[:n] = kept_vals
        self.count = n
        self.head = n % len(self.ts)
        return drained


class _ColdTier:
    """Downsampled history past the hot ring's resolution horizon.

    Samples drained out of a hot ring land here as one mean-per-bin
    sample per ``downsample_resolution_sec`` — the newest (possibly
    still-filling) bin accumulates in the pending slot until a later
    bin's samples arrive, so a bin is finalized exactly once.  Memory
    stays bounded: one extra ring per series, never more.
    """

    __slots__ = ("ring", "pending_bin", "pending_sum", "pending_tsum",
                 "pending_cnt")

    def __init__(self, capacity: int):
        self.ring = _Ring(capacity)
        self.pending_bin: int | None = None
        self.pending_sum = 0.0
        self.pending_tsum = 0.0
        self.pending_cnt = 0

    def flush_pending(self) -> None:
        if self.pending_cnt:
            self.ring.append(self.pending_tsum / self.pending_cnt,
                             self.pending_sum / self.pending_cnt)
        self.pending_bin = None
        self.pending_sum = self.pending_tsum = 0.0
        self.pending_cnt = 0

    def absorb(self, ts: np.ndarray, values: np.ndarray,
               resolution_s: float) -> None:
        if len(ts) == 0:
            return
        bins = np.floor(ts / resolution_s).astype(np.int64)
        for b in np.unique(bins):          # ascending
            mask = bins == b
            if self.pending_bin is not None and b < self.pending_bin:
                # out-of-order stragglers: finalize directly rather
                # than reopening a flushed bin
                self.ring.append(float(ts[mask].mean()),
                                 float(values[mask].mean()))
                continue
            if self.pending_bin is not None and b > self.pending_bin:
                self.flush_pending()
            self.pending_bin = int(b)
            self.pending_sum += float(values[mask].sum())
            self.pending_tsum += float(ts[mask].sum())
            self.pending_cnt += int(mask.sum())

    def window(self, start: float, end: float
               ) -> tuple[np.ndarray, np.ndarray]:
        ts, vals = self.ring.window(start, end)
        if self.pending_cnt:
            pt = self.pending_tsum / self.pending_cnt
            if start <= pt <= end:
                ts = np.append(ts, pt)
                vals = np.append(vals,
                                 self.pending_sum / self.pending_cnt)
        return ts, vals


class AggregateResult:
    """Windowed aggregation over one series."""

    def __init__(self, ts: np.ndarray, values: np.ndarray):
        self.ts = ts
        self.values = values

    @property
    def count(self) -> int:
        return len(self.values)

    @property
    def empty(self) -> bool:
        return self.count == 0

    def avg(self) -> float:
        return float(self.values.mean()) if self.count else 0.0

    def latest(self) -> float:
        return float(self.values[np.argmax(self.ts)]) if self.count else 0.0

    def max(self) -> float:
        return float(self.values.max()) if self.count else 0.0

    def percentile(self, q: float) -> float:
        """q in (0,1]; lower-interpolation to match Go's sample quantiles."""
        if not self.count:
            return 0.0
        return float(np.quantile(self.values, q, method="lower"))

    def percentiles(self, qs: Iterable[float]) -> dict[float, float]:
        return {q: self.percentile(q) for q in qs}

    def duration_seconds(self) -> float:
        return float(self.ts.max() - self.ts.min()) if self.count > 1 else 0.0

    def first(self) -> float:
        """Value at the earliest timestamp in the window (the other end
        of a cumulative counter's windowed delta); 0.0 sentinel when
        empty."""
        return float(self.values[np.argmin(self.ts)]) if self.count else 0.0

    def downsample(self, resolution_s: float) -> "AggregateResult":
        """Mean-per-bin downsampling to one sample per ``resolution_s``
        (bin timestamp = mean of member timestamps).  Bounds the cost of
        long-window aggregation (the SLO engine's 1h slow window over a
        1s sample cadence) without a second storage tier.  Meant for
        gauges — averaging COUNTER samples inside a bin slightly skews
        windowed deltas, so counter paths query raw."""
        if resolution_s <= 0 or self.count == 0:
            return self
        bins = np.floor(self.ts / resolution_s).astype(np.int64)
        _, inverse = np.unique(bins, return_inverse=True)
        counts = np.bincount(inverse)
        ts = np.bincount(inverse, weights=self.ts) / counts
        values = np.bincount(inverse, weights=self.values) / counts
        order = np.argsort(ts)
        return AggregateResult(ts[order], values[order])


class MetricCache:
    """Thread-safe store of ring-buffered series + an immutable KV side table."""

    def __init__(self, capacity_per_series: int = 4096, clock=time.time,
                 retention_sec: float | None = None,
                 downsample_after_sec: float | None = None,
                 downsample_resolution_sec: float = 10.0):
        self.capacity = capacity_per_series
        #: query-time retention horizon: samples strictly older than
        #: ``now - retention_sec`` are never served (the ring already
        #: bounds memory; retention bounds what a WINDOW may claim to
        #: cover).  A sample exactly AT the horizon is still served.
        self.retention_sec = retention_sec
        #: long-horizon tier (ISSUE 9): samples aging past this horizon
        #: move out of the hot ring into a per-series cold ring at
        #: mean-per-``downsample_resolution_sec``-bin resolution, so an
        #: hours-long soak keeps a bounded TWO rings per series (full
        #: resolution recent, downsampled history) instead of either
        #: unbounded memory or silent eviction of the history the trend
        #: engine needs.  A sample exactly AT the horizon stays hot;
        #: one strictly older is downsampled.  None disables the tier
        #: (hot-ring wraparound evicts, the pre-existing behavior).
        self.downsample_after_sec = downsample_after_sec
        self.downsample_resolution_sec = downsample_resolution_sec
        self._series: dict[tuple, _Ring] = {}
        self._cold: dict[tuple, _ColdTier] = {}
        self._kv: dict[str, object] = {}
        self._lock = threading.Lock()
        self._clock = clock

    # -- samples --

    def append(self, metric: str, value: float,
               labels: Mapping[str, str] | None = None,
               ts: Optional[float] = None) -> None:
        key = _series_key(metric, labels)
        now = self._clock() if ts is None else ts
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = _Ring(self.capacity)
            if (self.downsample_after_sec is not None
                    and ring.count == len(ring.ts)):
                # the hot ring is full: this append overwrites the
                # oldest sample.  With the long-horizon tier on, a
                # wrap-evicted sample is CAPTURED (downsampled) instead
                # of silently lost — a hot ring smaller than the horizon
                # must not punch holes in the history
                evict_ts = float(ring.ts[ring.head])
                evict_val = float(ring.values[ring.head])
                tier = self._cold.get(key)
                if tier is None:
                    tier = self._cold[key] = _ColdTier(self.capacity)
                tier.absorb(np.asarray([evict_ts]),
                            np.asarray([evict_val]),
                            self.downsample_resolution_sec)
            ring.append(now, value)
            if self.downsample_after_sec is not None:
                # amortized: compact this series only once a full
                # downsample bin's worth has aged past the horizon
                # (compact() does the exact-cutoff sweep on demand)
                oldest = ring.oldest_ts()
                if oldest is not None and (
                        oldest < now - self.downsample_after_sec
                        - self.downsample_resolution_sec):
                    self._compact_series_locked(key, now)

    def append_many(self, samples: list[tuple[str, float, Mapping[str, str] | None]],
                    ts: Optional[float] = None) -> None:
        now = self._clock() if ts is None else ts
        for metric, value, labels in samples:
            self.append(metric, value, labels, ts=now)

    # koordlint: guarded-by(self._lock)
    def _compact_series_locked(self, key: tuple, now: float) -> None:
        ring = self._series.get(key)
        if ring is None or self.downsample_after_sec is None:
            return
        drained_ts, drained_vals = ring.drain_older(
            now - self.downsample_after_sec)
        if len(drained_ts) == 0:
            return
        tier = self._cold.get(key)
        if tier is None:
            tier = self._cold[key] = _ColdTier(self.capacity)
        tier.absorb(drained_ts, drained_vals,
                    self.downsample_resolution_sec)

    def compact(self, now: Optional[float] = None) -> None:
        """Move every sample older than ``downsample_after_sec`` into
        its series' downsampled cold tier right now (appends do this
        lazily per series); no-op when the tier is disabled."""
        if self.downsample_after_sec is None:
            return
        now = self._clock() if now is None else now
        with self._lock:
            for key in list(self._series):
                self._compact_series_locked(key, now)

    def query(self, metric: str, labels: Mapping[str, str] | None = None,
              start: float = 0.0, end: Optional[float] = None) -> AggregateResult:
        key = _series_key(metric, labels)
        end = self._clock() if end is None else end
        if self.retention_sec is not None:
            start = max(start, self._clock() - self.retention_sec)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                return AggregateResult(np.empty(0), np.empty(0))
            ts, vals = ring.window(start, end)
            tier = self._cold.get(key)
            if tier is not None:
                # downsampled history first (older), hot samples after —
                # aggregators don't require sorted input, but keeping
                # rough chronological order costs nothing
                cts, cvals = tier.window(start, end)
                if len(cts):
                    ts = np.concatenate([cts, ts])
                    vals = np.concatenate([cvals, vals])
        return AggregateResult(ts, vals)

    def series_labels(self, metric: str) -> list[dict[str, str]]:
        """All label-sets present for a metric (e.g. every pod_uid)."""
        with self._lock:
            return [
                dict(lbl) for m, lbl in self._series.keys() if m == metric
            ]

    def delete_series(self, metric: str, labels: Mapping[str, str]) -> None:
        with self._lock:
            key = _series_key(metric, labels)
            self._series.pop(key, None)
            self._cold.pop(key, None)

    def gc(self, keep_pod_uids: set[str]) -> int:
        """Drop series of pods that no longer exist; returns dropped count."""
        with self._lock:
            stale = [
                key for key in self._series
                if any(k == "pod_uid" and v not in keep_pod_uids for k, v in key[1])
            ]
            for key in stale:
                del self._series[key]
                self._cold.pop(key, None)
        return len(stale)

    # -- persistence (tsdb_storage.go:29 role) --
    #
    # The reference's metriccache is an embedded Prometheus TSDB persisted
    # on the node, so a koordlet restart keeps its aggregation windows; the
    # ring buffers must match that or the NodeMetric reporter publishes a
    # "p95 over the window" computed from seconds of post-restart data
    # while claiming the window's label, and suppress/evict run on cold
    # data until the window refills.

    def snapshot(self, path: str) -> None:
        """Atomically write every series (and JSON-serializable KV
        entries) to ``path`` (.npz).  Same tmp+``os.replace`` pattern as
        the prediction checkpoints (prediction_server.py)."""
        with self._lock:
            keys = [
                {"metric": m, "labels": dict(lbl)}
                for m, lbl in self._series
            ]
            rings = list(self._series.values())
            arrays = {
                "ts": (np.stack([r.ts for r in rings])
                       if rings else np.zeros((0, self.capacity))),
                "values": (np.stack([r.values for r in rings])
                           if rings else np.zeros((0, self.capacity))),
                "head": np.asarray([r.head for r in rings], np.int64),
                "count": np.asarray([r.count for r in rings], np.int64),
            }
            kv = {}
            for k, v in self._kv.items():
                try:
                    if json.loads(json.dumps(v)) != v:
                        # JSON round-trip changed the shape (int dict
                        # keys become strings, tuples become lists) — a
                        # restored value that differs from the stored one
                        # would break consumers until the next collect;
                        # skip it like the opaque objects below
                        continue
                except (TypeError, ValueError):
                    continue   # opaque objects (topology structs) rebuild
                kv[k] = v      # from collection after restart
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # unique tmp per call: the interval snapshot (tick thread) and the
        # stop() shutdown snapshot can run concurrently; a shared tmp name
        # would interleave writers and os.replace a corrupt file
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f, keys=np.asarray(json.dumps(keys)),
                    kv=np.asarray(json.dumps(kv)), **arrays)
            os.replace(tmp, path)
        except BaseException:
            # a failed write (full/readonly disk) must not strand a
            # uniquely-named tmp per incarnation in var_run_root
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def restore(self, path: str) -> bool:
        """Load a snapshot written by :meth:`snapshot`; False (and start
        fresh) when absent or corrupt — a bad snapshot must never brick
        agent startup.  A capacity change across restart keeps the newest
        ``capacity`` samples per series."""
        try:
            if not os.path.exists(path):
                return False
            with np.load(path, allow_pickle=False) as z:
                keys = json.loads(str(z["keys"]))
                kv = json.loads(str(z["kv"]))
                ts, values = z["ts"], z["values"]
                head, count = z["head"], z["count"]
            series: dict[tuple, _Ring] = {}
            for i, key in enumerate(keys):
                ring = _Ring(self.capacity)
                cnt, hd = int(count[i]), int(head[i])
                cap_stored = ts.shape[1]
                # chronological order: oldest sample first
                if cnt < cap_stored:
                    idx = np.arange(cnt)
                else:
                    idx = np.arange(hd, hd + cap_stored) % cap_stored
                idx = idx[-self.capacity:]
                n = len(idx)
                ring.ts[:n] = ts[i, idx]
                ring.values[:n] = values[i, idx]
                ring.count = n
                ring.head = n % self.capacity
                series[_series_key(key["metric"], key["labels"])] = ring
        except Exception:  # noqa: BLE001 — truncated/corrupt npz (zip
            # errors, bad JSON) => start fresh
            return False
        with self._lock:
            self._series = series
            for k, v in kv.items():
                self._kv.setdefault(k, v)
        return True

    # -- KV (device info, NUMA topology, etc.) --

    def set_kv(self, key: str, value: object) -> None:
        with self._lock:
            self._kv[key] = value

    def get_kv(self, key: str, default=None):
        with self._lock:
            return self._kv.get(key, default)
