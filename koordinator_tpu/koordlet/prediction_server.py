"""PredictServer: the agent's usage forecaster (reference:
``pkg/koordlet/prediction/predict_server.go`` — ``PredictServer`` :65,
``training()`` :139, ``doCheckpoint`` :307, ``restoreModels`` :358;
``peak_predictor.go`` cold-start + safety margin).

TPU-native redesign: instead of one Go histogram object per UID, ALL models
live in two :class:`~koordinator_tpu.prediction.histogram.HistogramBank`
matrices (cpu milli-cores, memory MiB). A training tick gathers the latest
samples for every tracked UID from the metric cache and scatter-adds them in
one jitted call; p95/p98 queries answer every model at once. Checkpointing
writes the banks + the uid->row map; restore reloads both.

Tracked UIDs: ``node``, ``sys``, every pod uid, and the four priority-band
aggregates (prod/mid/batch/free) the mid-resource plugin consumes.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.priority import PriorityClass, priority_class_of
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.prediction import histogram as hist

UID_NODE = "node"
UID_SYS = "sys"
BAND_UIDS = {
    PriorityClass.PROD: "band/prod",
    PriorityClass.MID: "band/mid",
    PriorityClass.BATCH: "band/batch",
    PriorityClass.FREE: "band/free",
}
MIB = 1 << 20

#: cold start: models need this much observation time before being trusted
COLD_START_SECONDS = 15 * 60
#: safety margin applied to peaks (peak_predictor.go DefaultSafetyMarginPercent)
SAFETY_MARGIN_PCT = 10


class PredictServer:
    def __init__(
        self,
        states: StatesInformer,
        cache: mc.MetricCache,
        checkpoint_dir: Optional[str] = None,
        capacity: int = 512,
        half_life_sec: float = 24 * 3600.0,
        checkpoint_interval_sec: float = 600.0,
        clock=time.time,
    ):
        self.states = states
        self.cache = cache
        self.checkpoint_dir = checkpoint_dir
        self.clock = clock
        self.capacity = capacity
        self.checkpoint_interval_sec = checkpoint_interval_sec
        self.cpu_buckets = hist.default_cpu_buckets()
        self.mem_buckets = hist.default_memory_buckets()
        self.cpu_bank = hist.HistogramBank.zeros(capacity, self.cpu_buckets,
                                                 half_life_sec)
        self.mem_bank = hist.HistogramBank.zeros(capacity, self.mem_buckets,
                                                 half_life_sec)
        self._rows: dict[str, int] = {}
        self._first_seen: dict[str, float] = {}
        self._free_rows: list[int] = list(range(capacity - 1, -1, -1))
        self._last_checkpoint = 0.0
        if checkpoint_dir:
            self.restore()

    # -- row management ------------------------------------------------------

    def _row_of(self, uid: str, now: float) -> Optional[int]:
        row = self._rows.get(uid)
        if row is not None:
            return row
        if not self._free_rows:
            return None  # bank full: drop new models (reference logs + skips)
        row = self._free_rows.pop()
        self._rows[uid] = row
        self._first_seen[uid] = now
        # clear any stale weights left by a previous occupant of this row
        self.cpu_bank = self.cpu_bank.replace(
            weights=self.cpu_bank.weights.at[row].set(0.0),
            total=self.cpu_bank.total.at[row].set(0.0),
        )
        self.mem_bank = self.mem_bank.replace(
            weights=self.mem_bank.weights.at[row].set(0.0),
            total=self.mem_bank.total.at[row].set(0.0),
        )
        return row

    def gc(self) -> int:
        """Release rows of pods that no longer exist."""
        live = {p.uid for p in self.states.get_all_pods()}
        keep = {UID_NODE, UID_SYS, *BAND_UIDS.values()}
        stale = [u for u in self._rows if u not in live and u not in keep]
        for uid in stale:
            self._free_rows.append(self._rows.pop(uid))
            self._first_seen.pop(uid, None)
        return len(stale)

    # -- training ------------------------------------------------------------

    def train_once(self) -> int:
        """One training tick: feed the latest sample of every tracked UID.
        Returns the number of samples ingested."""
        now = self.clock()
        window = 120.0
        uids: list[int] = []
        cpu_vals: list[float] = []
        mem_vals: list[float] = []
        cpu_mask: list[bool] = []
        mem_mask: list[bool] = []
        band_cpu: dict[str, float] = {u: 0.0 for u in BAND_UIDS.values()}
        band_mem: dict[str, float] = {u: 0.0 for u in BAND_UIDS.values()}

        def push(uid: str, cpu_milli, mem_mib):
            """None marks a missing half: the sample is masked out of that
            bank instead of polluting the histogram with a 0."""
            row = self._row_of(uid, now)
            if row is None:
                return
            uids.append(row)
            cpu_vals.append(0.0 if cpu_milli is None else cpu_milli)
            mem_vals.append(0.0 if mem_mib is None else mem_mib)
            cpu_mask.append(cpu_milli is not None)
            mem_mask.append(mem_mib is not None)

        node_cpu = self.cache.query(mc.NODE_CPU_USAGE, None, now - window, now)
        node_mem = self.cache.query(mc.NODE_MEMORY_USAGE, None, now - window, now)
        if not (node_cpu.empty and node_mem.empty):
            push(UID_NODE,
                 None if node_cpu.empty else node_cpu.latest() * 1000.0,
                 None if node_mem.empty else node_mem.latest() / MIB)
        sys_cpu = self.cache.query(mc.SYS_CPU_USAGE, None, now - window, now)
        sys_mem = self.cache.query(mc.SYS_MEMORY_USAGE, None, now - window, now)
        if not (sys_cpu.empty and sys_mem.empty):
            push(UID_SYS,
                 None if sys_cpu.empty else sys_cpu.latest() * 1000.0,
                 None if sys_mem.empty else sys_mem.latest() / MIB)

        for pod in self.states.get_all_pods():
            if not pod.is_running:
                continue
            labels = {"pod_uid": pod.uid}
            cpu = self.cache.query(mc.POD_CPU_USAGE, labels, now - window, now)
            mem = self.cache.query(mc.POD_MEMORY_USAGE, labels, now - window, now)
            if cpu.empty and mem.empty:
                continue
            cpu_milli = None if cpu.empty else cpu.latest() * 1000.0
            mem_mib = None if mem.empty else mem.latest() / MIB
            push(pod.uid, cpu_milli, mem_mib)
            band = BAND_UIDS.get(priority_class_of(pod.priority))
            if band:
                band_cpu[band] += cpu_milli or 0.0
                band_mem[band] += mem_mib or 0.0

        for band_uid in BAND_UIDS.values():
            if band_cpu[band_uid] > 0 or band_mem[band_uid] > 0:
                push(band_uid, band_cpu[band_uid], band_mem[band_uid])

        if not uids:
            return 0
        rows = jnp.asarray(np.asarray(uids, np.int32))
        t = jnp.float32(now)
        self.cpu_bank = hist.add_samples(
            self.cpu_bank, self.cpu_buckets, rows,
            jnp.asarray(np.asarray(cpu_vals, np.float32)), t,
            mask=jnp.asarray(np.asarray(cpu_mask, bool)),
        )
        self.mem_bank = hist.add_samples(
            self.mem_bank, self.mem_buckets, rows,
            jnp.asarray(np.asarray(mem_vals, np.float32)), t,
            mask=jnp.asarray(np.asarray(mem_mask, bool)),
        )
        if (self.checkpoint_dir
                and now - self._last_checkpoint >= self.checkpoint_interval_sec):
            self.checkpoint()
            self._last_checkpoint = now
        return len(uids)

    # -- prediction ----------------------------------------------------------

    def peak(self, uid: str, p: float = 0.95,
             safety_margin_pct: int = SAFETY_MARGIN_PCT
             ) -> Optional[tuple[int, int]]:
        """(cpu milli, mem MiB) predicted peak, or None (unknown/cold)."""
        row = self._rows.get(uid)
        if row is None:
            return None
        if self.clock() - self._first_seen.get(uid, 0.0) < COLD_START_SECONDS:
            return None
        cpu = float(hist.percentile(self.cpu_bank, self.cpu_buckets, p)[row])
        mem = float(hist.percentile(self.mem_bank, self.mem_buckets, p)[row])
        scale = 1.0 + safety_margin_pct / 100.0
        return int(cpu * scale), int(mem * scale)

    def prod_reclaimable(self) -> tuple[int, int]:
        """The mid-resource input (midresource plugin): what prod pods have
        *requested* but are very unlikely to use — sum(prod requests) minus
        the predicted prod-band peak (p98 + margin), clamped at 0."""
        peak = self.peak(BAND_UIDS[PriorityClass.PROD], p=0.98)
        if peak is None:
            return 0, 0
        req_cpu = req_mem = 0
        for pod in self.states.get_all_pods():
            if priority_class_of(pod.priority) is not PriorityClass.PROD:
                continue
            req_cpu += int(pod.requests.get("cpu", 0))
            req_mem += int(pod.requests.get("memory", 0)) // MIB
        return (max(0, req_cpu - peak[0]), max(0, req_mem - peak[1]))

    # -- checkpoint / restore -------------------------------------------------

    def checkpoint(self) -> None:
        assert self.checkpoint_dir
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        hist.save_bank(self.cpu_bank, os.path.join(self.checkpoint_dir, "cpu.npz"))
        hist.save_bank(self.mem_bank, os.path.join(self.checkpoint_dir, "mem.npz"))
        meta = {
            "rows": self._rows,
            "first_seen": self._first_seen,
        }
        tmp = os.path.join(self.checkpoint_dir, "meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.checkpoint_dir, "meta.json"))

    def restore(self) -> bool:
        try:
            cpu_path = os.path.join(self.checkpoint_dir, "cpu.npz")
            meta_path = os.path.join(self.checkpoint_dir, "meta.json")
            if not (os.path.exists(cpu_path) and os.path.exists(meta_path)):
                return False
            self.cpu_bank = hist.load_bank(cpu_path)
            self.mem_bank = hist.load_bank(
                os.path.join(self.checkpoint_dir, "mem.npz")
            )
            with open(meta_path) as f:
                meta = json.load(f)
            self._rows = {k: int(v) for k, v in meta["rows"].items()}
            self._first_seen = {
                k: float(v) for k, v in meta.get("first_seen", {}).items()
            }
            used = set(self._rows.values())
            self._free_rows = [
                r for r in range(self.capacity - 1, -1, -1) if r not in used
            ]
            return True
        except Exception:  # noqa: BLE001 — a corrupt checkpoint (truncated
            # npz raises BadZipFile/EOFError) must never brick agent startup;
            # start fresh instead.
            self.cpu_bank = hist.HistogramBank.zeros(
                self.capacity, self.cpu_buckets, float(self.cpu_bank.half_life)
            )
            self.mem_bank = hist.HistogramBank.zeros(
                self.capacity, self.mem_buckets, float(self.mem_bank.half_life)
            )
            self._rows = {}
            self._first_seen = {}
            self._free_rows = list(range(self.capacity - 1, -1, -1))
            return False
