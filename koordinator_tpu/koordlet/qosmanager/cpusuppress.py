"""BE CPU suppression (reference: ``qosmanager/plugins/cpusuppress/
cpu_suppress.go`` — ``calculateBESuppressCPU`` :136, ``suppressBECPU`` :246).

Every tick, the CPU room left for BestEffort is::

    be_allowable = capacity * suppress_threshold% - (node_used - be_used)

(everything in milli-cores; ``node_used - be_used`` is the LS+system share).
The result is applied either as a **cpuset** (shrink the number of CPUs the
BE tier may run on, NUMA-spread, avoiding LSR/LSE exclusive CPUs) or as a
**cfs quota** on the besteffort tier cgroup. Growth back up is rate-limited
(``max_increase_pct`` per tick) so a quiet moment doesn't instantly hand all
CPUs back — matching the reference's chattiness guard.
"""

from __future__ import annotations

import math
from typing import Optional

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.qosmanager.framework import StrategyContext
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdate
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import procfs

#: the BE tier always keeps at least this many CPUs runnable
BE_MIN_CPUS = 2
CFS_PERIOD_US = 100_000


def calculate_be_suppress_milli(
    capacity_milli: int,
    node_used_milli: int,
    be_used_milli: int,
    threshold_pct: int,
    max_increase_pct: int = 5,
    prev_allowable_milli: Optional[int] = None,
) -> int:
    """The suppress formula with rate-limited growth, all int milli-cores."""
    allowable = capacity_milli * threshold_pct // 100 - (
        node_used_milli - be_used_milli
    )
    allowable = min(allowable, capacity_milli)
    if prev_allowable_milli is not None and allowable > prev_allowable_milli:
        step = capacity_milli * max_increase_pct // 100
        allowable = min(allowable, prev_allowable_milli + max(step, 1000))
    # the BE minimum is the LAST word: a sub-floor prev (external
    # checkpoint, config change) must not let the rate limiter hold the
    # result under the guaranteed floor (found by the randomized sweep)
    # — but the floor itself can never exceed the machine (a 1-CPU node
    # cannot enforce a 2-CPU quota)
    return max(allowable, min(BE_MIN_CPUS * 1000, capacity_milli))


def select_be_cpuset(
    topology: procfs.CPUTopology,
    n_cpus: int,
    exclusive_cpus: frozenset[int] = frozenset(),
) -> list[int]:
    """Pick which CPUs the BE tier runs on: spread across NUMA nodes
    round-robin (suppress policy keeps BE pressure even), skipping
    LSR/LSE-exclusive CPUs unless nothing else is left."""
    nodes = topology.numa_nodes()
    per_node = {
        n: [c for c in topology.cpus_in_node(n) if c not in exclusive_cpus]
        for n in nodes
    }
    picked: list[int] = []
    while len(picked) < n_cpus and any(per_node.values()):
        for n in nodes:
            if per_node[n] and len(picked) < n_cpus:
                picked.append(per_node[n].pop(0))
    if len(picked) < n_cpus:  # fall back onto exclusive CPUs if we must
        rest = [c.cpu for c in topology.cpus if c.cpu not in picked]
        picked.extend(rest[: n_cpus - len(picked)])
    return sorted(picked)


class CPUSuppress:
    name = "cpusuppress"
    interval_seconds = 1.0
    feature_gate = "BECPUSuppress"

    def __init__(self, ctx: StrategyContext,
                 topology: Optional[procfs.CPUTopology] = None,
                 exclusive_cpus: frozenset[int] = frozenset()):
        self.ctx = ctx
        self._topology = topology
        self.exclusive_cpus = exclusive_cpus
        self._prev_allowable: Optional[int] = None

    def enabled(self) -> bool:
        return self.ctx.node_slo().resource_used_threshold_with_be.enable

    @property
    def topology(self) -> procfs.CPUTopology:
        if self._topology is None:
            self._topology = procfs.read_cpu_topology(self.ctx.cfg)
        return self._topology

    def _usages_milli(self) -> tuple[int, int]:
        now = self.ctx.clock()
        node = self.ctx.cache.query(mc.NODE_CPU_USAGE, None, now - 60, now)
        be = self.ctx.cache.query(mc.BE_CPU_USAGE, None, now - 60, now)
        return int(node.latest() * 1000), int(be.latest() * 1000)

    def update(self) -> None:
        strategy = self.ctx.node_slo().resource_used_threshold_with_be
        capacity = self.ctx.node_cpu_capacity_milli()
        if capacity <= 0:
            return
        node_used, be_used = self._usages_milli()
        allowable = calculate_be_suppress_milli(
            capacity, node_used, be_used,
            strategy.cpu_suppress_threshold_percent,
            prev_allowable_milli=self._prev_allowable,
        )
        self._prev_allowable = allowable
        from koordinator_tpu.metrics import be_suppress_cpu_cores

        be_suppress_cpu_cores.set(allowable / 1000.0)
        be_dir = self.ctx.cfg.kube_qos_dir("besteffort")
        if strategy.cpu_suppress_policy == "cfsQuota":
            quota = allowable * CFS_PERIOD_US // 1000
            self.ctx.executor.update(
                ResourceUpdate(cg.CPU_CFS_QUOTA, be_dir, str(quota))
            )
        else:  # cpuset policy
            n_cpus = max(BE_MIN_CPUS, math.ceil(allowable / 1000))
            n_cpus = min(n_cpus, self.topology.num_cpus)
            cpus = select_be_cpuset(self.topology, n_cpus, self.exclusive_cpus)
            value = procfs.format_cpu_list(cpus)
            # BE tier dir + every BE pod AND container dir (the kernel
            # rejects a pod-level shrink while container cpusets still hold
            # the wider set; leveled batch orders depth per direction).
            updates = [ResourceUpdate(cg.CPUSET_CPUS, be_dir, value)]
            for pod in self.ctx.be_pods():
                pod_dir = pod.cgroup_dir(self.ctx.cfg)
                updates.append(ResourceUpdate(cg.CPUSET_CPUS, pod_dir, value))
                for container in pod.containers:
                    crel = container.cgroup_dir or self.ctx.cfg.container_cgroup_dir(
                        pod.kube_qos, pod.uid, container.container_id
                    )
                    updates.append(ResourceUpdate(cg.CPUSET_CPUS, crel, value))
            self.ctx.executor.leveled_update_batch(updates)

    def be_real_limit_milli(self) -> int:
        """What BE may actually use right now (for cpuevict satisfaction)."""
        if self._prev_allowable is not None:
            return self._prev_allowable
        return self.ctx.node_cpu_capacity_milli()
