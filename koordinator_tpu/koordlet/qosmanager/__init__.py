"""QoS manager: the periodic strategy loops ("slo-agent") that enforce
node-side QoS (reference: ``pkg/koordlet/qosmanager/`` — plugin registry
``plugins/register.go:32-40``).

Plugins: cpusuppress, cpuevict, memoryevict, cpuburst, cgreconcile, blkio,
resctrl, sysreconcile — each a :class:`~.framework.QOSStrategy` driven by the
manager's tick.
"""

from koordinator_tpu.koordlet.qosmanager.framework import (
    Evictor, QOSManager, QOSStrategy, StrategyContext,
)
