"""Tier-level reconcilers (reference: ``qosmanager/plugins/cgreconcile/``,
``resctrl/``, ``blkio/``, ``sysreconcile/``).

- :class:`CgroupReconcile`: program kube-QoS-tier cgroup knobs (memory QoS
  watermarks/protection, priority) from the NodeSLO per-class strategies.
- :class:`ResctrlQOS`: LLC way masks + MBA percents for the LS/LSR/BE resctrl
  groups, and task binding of each tier's pids.
- :class:`BlkIOQOS`: per-tier IO weight / throttles.
- :class:`SysReconcile`: node sysctl knobs (min_free_kbytes factor,
  watermark_scale_factor).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from koordinator_tpu.api.crds import QoSStrategy
from koordinator_tpu.api.qos import QoSClass
from koordinator_tpu.koordlet.qosmanager.framework import StrategyContext
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdate
from koordinator_tpu.koordlet.system import cgroup as cg
from koordinator_tpu.koordlet.system import resctrl as rfs

#: kube QoS tier -> koord QoS strategies that apply to it
TIER_OF_CLASS = {
    QoSClass.LSE: "guaranteed",
    QoSClass.LSR: "guaranteed",
    QoSClass.LS: "burstable",
    QoSClass.BE: "besteffort",
}


class CgroupReconcile:
    name = "cgreconcile"
    interval_seconds = 10.0
    feature_gate = "CgroupReconcile"

    def __init__(self, ctx: StrategyContext):
        self.ctx = ctx

    def enabled(self) -> bool:
        slo = self.ctx.node_slo()
        return any(
            s.memory.enable
            for s in (slo.resource_qos_ls, slo.resource_qos_lsr, slo.resource_qos_be)
        )

    def _apply_memory_qos(self, rel: str, strategy: QoSStrategy,
                          request_bytes: int, limit_bytes: int) -> None:
        memory = strategy.memory
        if not memory.enable:
            return
        updates = []
        if memory.min_limit_percent > 0 and request_bytes > 0:
            updates.append(ResourceUpdate(
                cg.MEMORY_MIN, rel, str(request_bytes * memory.min_limit_percent // 100)
            ))
        if memory.low_limit_percent > 0 and request_bytes > 0:
            updates.append(ResourceUpdate(
                cg.MEMORY_LOW, rel, str(request_bytes * memory.low_limit_percent // 100)
            ))
        if memory.throttling_percent > 0 and limit_bytes > 0:
            updates.append(ResourceUpdate(
                cg.MEMORY_HIGH, rel, str(limit_bytes * memory.throttling_percent // 100)
            ))
        updates.append(ResourceUpdate(cg.MEMORY_WMARK_RATIO, rel, str(memory.wmark_ratio)))
        updates.append(ResourceUpdate(
            cg.MEMORY_WMARK_SCALE_FACTOR, rel, str(memory.wmark_scale_permill)
        ))
        updates.append(ResourceUpdate(
            cg.MEMORY_WMARK_MIN_ADJ, rel, str(memory.wmark_min_adj)
        ))
        if memory.priority_enable:
            updates.append(ResourceUpdate(cg.MEMORY_PRIORITY, rel, str(memory.priority)))
            updates.append(ResourceUpdate(
                cg.MEMORY_USE_PRIORITY_OOM, rel, str(memory.priority_enable)
            ))
        self.ctx.executor.update_batch(updates)

    def update(self) -> None:
        slo = self.ctx.node_slo()
        strategy_of = {
            QoSClass.LSE: slo.resource_qos_lsr,
            QoSClass.LSR: slo.resource_qos_lsr,
            QoSClass.LS: slo.resource_qos_ls,
            QoSClass.BE: slo.resource_qos_be,
        }
        for pod in self.ctx.states.get_all_pods():
            if not pod.is_running:
                continue
            strategy = strategy_of.get(pod.qos_class)
            if strategy is None:
                continue
            self._apply_memory_qos(
                pod.cgroup_dir(self.ctx.cfg), strategy,
                int(pod.requests.get("memory", 0)),
                int(pod.limits.get("memory", 0)),
            )


class ResctrlQOS:
    name = "resctrl"
    interval_seconds = 10.0
    feature_gate = "RdtResctrl"

    def __init__(self, ctx: StrategyContext,
                 fs: Optional[rfs.ResctrlFS] = None,
                 tier_pids: Optional[Callable[[str], list[int]]] = None):
        self.ctx = ctx
        self.fs = fs or rfs.ResctrlFS(ctx.cfg)
        #: group name -> pids, injected (reads cgroup.procs of the tier in prod)
        self.tier_pids = tier_pids

    def enabled(self) -> bool:
        return self.fs.available()

    def update(self) -> None:
        slo = self.ctx.node_slo()
        per_group = {
            rfs.GROUP_LS: slo.resource_qos_ls.resctrl,
            rfs.GROUP_LSR: slo.resource_qos_lsr.resctrl,
            rfs.GROUP_BE: slo.resource_qos_be.resctrl,
        }
        for group, strategy in per_group.items():
            # CAT range [start, end] percent of ways -> positioned mask, so
            # disjoint ranges give disjoint way sets (real LLC isolation).
            span = max(1, strategy.cat_range_end_percent - strategy.cat_range_start_percent)
            self.fs.apply_qos_policy(
                group, span, strategy.mba_percent,
                l3_start_percent=strategy.cat_range_start_percent,
            )
            if self.tier_pids is not None:
                self.fs.add_tasks(group, self.tier_pids(group))


class BlkIOQOS:
    name = "blkio"
    interval_seconds = 10.0
    feature_gate = "BlkIOReconcile"

    def __init__(self, ctx: StrategyContext):
        self.ctx = ctx

    def enabled(self) -> bool:
        slo = self.ctx.node_slo()
        return any(
            s.blkio.enable
            for s in (slo.resource_qos_ls, slo.resource_qos_lsr, slo.resource_qos_be)
        )

    def update(self) -> None:
        slo = self.ctx.node_slo()
        tiers = {
            "burstable": slo.resource_qos_ls.blkio,
            "besteffort": slo.resource_qos_be.blkio,
        }
        for tier, blkio in tiers.items():
            if not blkio.enable:
                continue
            rel = self.ctx.cfg.kube_qos_dir(tier)
            self.ctx.executor.update(
                ResourceUpdate(cg.BLKIO_WEIGHT, rel, str(blkio.weight))
            )


class SysReconcile:
    name = "sysreconcile"
    interval_seconds = 30.0
    feature_gate = "SystemConfig"

    def __init__(self, ctx: StrategyContext):
        self.ctx = ctx
        self._baseline_min_free: Optional[int] = None

    def enabled(self) -> bool:
        return True

    def update(self) -> None:
        strategy = self.ctx.node_slo().system_strategy
        vm = self.ctx.cfg.proc_path("sys", "vm")
        targets = {
            "watermark_scale_factor": strategy.watermark_scale_factor,
        }
        if strategy.min_free_kbytes_factor != 100:
            try:
                if self._baseline_min_free is None:
                    # scale from the boot-time value once, not compounding
                    # the already-scaled knob on every tick
                    with open(os.path.join(vm, "min_free_kbytes")) as f:
                        self._baseline_min_free = int(f.read().strip())
                targets["min_free_kbytes"] = (
                    self._baseline_min_free * strategy.min_free_kbytes_factor // 100
                )
            except (OSError, ValueError):
                pass
        for knob, value in targets.items():
            path = os.path.join(vm, knob)
            try:
                with open(path) as f:
                    if f.read().strip() == str(value):
                        continue
                with open(path, "w") as f:
                    f.write(str(value))
                if self.ctx.auditor:
                    self.ctx.auditor.log("sysctl", "update", knob, {"value": str(value)})
            except OSError:
                continue
