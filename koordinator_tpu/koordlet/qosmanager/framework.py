"""QoS strategy framework (reference: ``qosmanager/framework/strategy.go:21``
QOSStrategy interface, ``helpers/`` Evictor).

A :class:`QOSStrategy` exposes ``enabled()`` + ``update()``; the
:class:`QOSManager` ticks every enabled strategy at its own interval.
:class:`Evictor` centralizes BE pod eviction with an injected kill handler
(the reference POSTs an eviction to the apiserver; the bridge provides that)
and audit logging.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Protocol

from koordinator_tpu.features import KOORDLET_GATES

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdateExecutor
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


class StrategyContext:
    """Shared dependencies handed to every strategy."""

    def __init__(
        self,
        states: StatesInformer,
        cache: mc.MetricCache,
        executor: ResourceUpdateExecutor,
        cfg: Optional[SystemConfig] = None,
        auditor: Optional[Auditor] = None,
        clock=time.time,
    ):
        self.states = states
        self.cache = cache
        self.executor = executor
        self.cfg = cfg or get_config()
        self.auditor = auditor
        self.clock = clock

    def node_slo(self):
        """Current NodeSLO (api.crds.NodeSLO) or defaults."""
        from koordinator_tpu.api.crds import NodeSLO

        return self.states.get_node_slo() or NodeSLO()

    def node_cpu_capacity_milli(self) -> int:
        node = self.states.get_node()
        if node is None:
            return 0
        return int(node.allocatable.get("cpu", 0))

    def node_memory_capacity(self) -> int:
        node = self.states.get_node()
        if node is None:
            return 0
        return int(node.allocatable.get("memory", 0))

    def be_pods(self, sort_for_eviction: bool = False,
                sort_by: str = "cpu") -> list[PodMeta]:
        """Running BE pods; eviction order = (priority asc, usage desc) —
        lowest priority first, then the biggest consumer of the pressured
        resource (sort_by: "cpu" | "memory")."""
        pods = [
            p for p in self.states.get_all_pods()
            if p.qos_class.is_best_effort and p.is_running
        ]
        if sort_for_eviction:
            now = self.clock()
            metric = mc.POD_MEMORY_USAGE if sort_by == "memory" else mc.POD_CPU_USAGE

            def usage(p: PodMeta) -> float:
                return self.cache.query(
                    metric, {"pod_uid": p.uid}, now - 60, now
                ).latest()

            pods.sort(key=lambda p: (p.priority, -usage(p)))
        return pods


class QOSStrategy(Protocol):
    name: str
    interval_seconds: float
    #: KOORDLET_GATES gate controlling the strategy ("" = ungated)
    feature_gate: str

    def enabled(self) -> bool: ...

    def update(self) -> None: ...


class Evictor:
    """BE pod eviction helper (qosmanager/helpers/evictor).

    An eviction is asynchronous — the pod stays in the informer state until
    the control plane deletes it — so a cooldown suppresses re-evicting the
    same pod every tick while the first eviction is in flight.
    """

    def __init__(self, ctx: StrategyContext,
                 kill_handler: Optional[Callable[[PodMeta, str], bool]] = None,
                 cooldown_seconds: float = 300.0):
        self.ctx = ctx
        self.kill_handler = kill_handler
        self.cooldown_seconds = cooldown_seconds
        self.evicted: list[tuple[str, str]] = []  # (pod uid, reason)
        self._in_flight: dict[str, float] = {}    # pod uid -> evict time

    def _prune(self, now: float) -> None:
        horizon = now - 2 * self.cooldown_seconds
        for uid in [u for u, t in self._in_flight.items() if t < horizon]:
            del self._in_flight[uid]
        if len(self.evicted) > 1000:
            del self.evicted[:-1000]

    def evict(self, pod: PodMeta, reason: str) -> bool:
        now = self.ctx.clock()
        self._prune(now)
        since = self._in_flight.get(pod.uid)
        if since is not None and now - since < self.cooldown_seconds:
            return False
        ok = True
        if self.kill_handler is not None:
            # None (a fire-and-forget handler with no opinion) counts as
            # success: the reference accounts released capacity from the
            # pods it SELECTS (cpu_evict.go:356 calculateMilliRelease*),
            # not from the eviction API's result, so a bare callback must
            # not zero the released tally (which would over-evict past
            # the lower-percent target).  Any other return is truth-
            # tested, so False, 0, and numpy False all mean failure.
            result = self.kill_handler(pod, reason)
            ok = result is None or bool(result)
        if ok:
            from koordinator_tpu.metrics import pod_eviction_total

            pod_eviction_total.inc(labels={"reason": reason})
            self._in_flight[pod.uid] = now
            self.evicted.append((pod.uid, reason))
            if self.ctx.auditor:
                self.ctx.auditor.log(
                    "eviction", "evict", pod.uid,
                    {"pod": f"{pod.namespace}/{pod.name}", "reason": reason},
                )
        return ok


class QOSManager:
    """Ticks every enabled strategy at its interval (qosmanager/qos_manager.go)."""

    def __init__(self, ctx: StrategyContext, strategies: list[QOSStrategy]):
        self.ctx = ctx
        self.strategies = strategies
        self._last_run: dict[str, float] = {}

    def tick(self) -> list[str]:
        """Run strategies whose interval elapsed; returns names that ran."""
        now = self.ctx.clock()
        ran = []
        for strategy in self.strategies:
            last = self._last_run.get(strategy.name, 0.0)
            if now - last < strategy.interval_seconds:
                continue
            gate = getattr(strategy, "feature_gate", "")
            if gate and not KOORDLET_GATES.enabled(gate):
                continue
            try:
                if strategy.enabled():
                    strategy.update()
                    ran.append(strategy.name)
            except (OSError, ValueError):
                continue
            finally:
                self._last_run[strategy.name] = now
        return ran
