"""BE eviction strategies (reference: ``qosmanager/plugins/cpuevict/`` and
``memoryevict/``).

- :class:`CPUEvict`: when BE *satisfaction* (real limit / request) stays under
  the lower bound for a full window AND BE is actually CPU-hungry
  (usage/limit above the usage threshold), evict BE pods — lowest priority,
  biggest consumer first — until enough request is released to bring
  satisfaction back to the upper bound.
- :class:`MemoryEvict`: when node memory utilization crosses the threshold,
  evict BE pods until projected utilization reaches the lower target
  (default threshold - 2, matching the reference's fallback).
"""

from __future__ import annotations

from typing import Callable, Optional

from koordinator_tpu.api import extension as ext
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.qosmanager.framework import Evictor, StrategyContext

CPU_EVICT_USAGE_THRESHOLD_PCT = 90


class CPUEvict:
    name = "cpuevict"
    interval_seconds = 1.0
    feature_gate = "BECPUEvict"

    def __init__(self, ctx: StrategyContext, evictor: Evictor,
                 be_real_limit_milli: Callable[[], int]):
        self.ctx = ctx
        self.evictor = evictor
        self.be_real_limit_milli = be_real_limit_milli
        self._low_since: Optional[float] = None

    def enabled(self) -> bool:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        return s.enable and s.cpu_evict_be_satisfaction_lower_percent > 0

    def _be_request_milli(self) -> int:
        return sum(
            int(p.requests.get(ext.RESOURCE_BATCH_CPU, p.requests.get("cpu", 0)))
            for p in self.ctx.be_pods()
        )

    def update(self) -> None:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        now = self.ctx.clock()
        be_request = self._be_request_milli()
        if be_request <= 0:
            self._low_since = None
            return
        real_limit = self.be_real_limit_milli()
        satisfaction_pct = real_limit * 100 // be_request
        be_usage = int(
            self.ctx.cache.query(mc.BE_CPU_USAGE, None, now - 60, now).latest() * 1000
        )
        hungry = real_limit > 0 and be_usage * 100 // real_limit >= (
            s.cpu_evict_be_usage_threshold_percent or CPU_EVICT_USAGE_THRESHOLD_PCT
        )
        if satisfaction_pct >= s.cpu_evict_be_satisfaction_lower_percent or not hungry:
            self._low_since = None
            return
        if self._low_since is None:
            self._low_since = now
            return
        if now - self._low_since < s.cpu_evict_time_window_seconds:
            return
        # Release enough request to reach the upper satisfaction bound:
        # (real_limit / (be_request - released)) >= upper%
        upper = max(
            s.cpu_evict_be_satisfaction_upper_percent,
            s.cpu_evict_be_satisfaction_lower_percent,
        )
        target_request = real_limit * 100 // max(upper, 1)
        to_release = be_request - target_request
        released = 0
        for pod in self.ctx.be_pods(sort_for_eviction=True):
            if released >= to_release:
                break
            req = int(
                pod.requests.get(ext.RESOURCE_BATCH_CPU, pod.requests.get("cpu", 0))
            )
            if self.evictor.evict(pod, "evictPodCPUPressure"):
                released += req
        self._low_since = None


class AllocatableEvict:
    """Evict BE pods when their batch-resource REQUESTS outgrow the node's
    batch ALLOCATABLE (reference cpu_evict.go:356 evictByAllocatable /
    memory_evict.go's allocatable policy; CPUAllocatableEvict and
    MemoryAllocatableEvict gates).

    The colocation model shrinks batch allocatable as LS load rises; when
    already-admitted batch requests exceed ``threshold%`` of the (now
    smaller) allocatable, pods are evicted lowest-priority /
    biggest-request first until requests fall to ``lower%``.  This is a
    REQUEST-vs-MODEL check, not a usage check — it fires even when the
    node is physically idle, because the promised overcommit is gone.
    """

    interval_seconds = 1.0

    def __init__(self, ctx: StrategyContext, evictor: Evictor,
                 resource: str = "cpu"):
        assert resource in ("cpu", "memory")
        self.ctx = ctx
        self.evictor = evictor
        self.resource = resource
        self.name = f"{resource}allocatableevict"
        self.feature_gate = ("CPUAllocatableEvict" if resource == "cpu"
                             else "MemoryAllocatableEvict")
        self._batch_resource = (ext.RESOURCE_BATCH_CPU if resource == "cpu"
                                else ext.RESOURCE_BATCH_MEMORY)

    def _thresholds(self) -> tuple[int, int]:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        if self.resource == "cpu":
            return (s.cpu_evict_by_allocatable_threshold_percent,
                    s.cpu_evict_by_allocatable_lower_percent)
        return (s.memory_evict_by_allocatable_threshold_percent,
                s.memory_evict_by_allocatable_lower_percent)

    def enabled(self) -> bool:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        return s.enable and self._thresholds()[0] >= 0

    def update(self) -> None:
        threshold, lower = self._thresholds()
        if threshold < 0:
            return
        if lower < 0:
            lower = max(threshold - 2, 0)
        node = self.ctx.states.get_node()
        if node is None:
            return
        allocatable = int(node.allocatable.get(self._batch_resource, 0))
        if allocatable <= 0:
            return
        requested = sum(
            int(p.requests.get(self._batch_resource, 0))
            for p in self.ctx.be_pods()
        )
        if requested * 100 <= allocatable * threshold:
            return
        target = allocatable * lower // 100
        to_release = requested - target
        released = 0
        for pod in self.ctx.be_pods(sort_for_eviction=True,
                                    sort_by=self.resource):
            if released >= to_release:
                break
            req = int(pod.requests.get(self._batch_resource, 0))
            if req <= 0:
                continue
            if self.evictor.evict(
                    pod, f"evictPodByNode{self.resource.capitalize()}"
                         f"Allocatable"):
                released += req


class MemoryEvict:
    name = "memoryevict"
    interval_seconds = 1.0
    feature_gate = "BEMemoryEvict"

    def __init__(self, ctx: StrategyContext, evictor: Evictor):
        self.ctx = ctx
        self.evictor = evictor

    def enabled(self) -> bool:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        return s.enable and s.memory_evict_threshold_percent > 0

    def update(self) -> None:
        s = self.ctx.node_slo().resource_used_threshold_with_be
        capacity = self.ctx.node_memory_capacity()
        if capacity <= 0:
            return
        now = self.ctx.clock()
        node_used = int(
            self.ctx.cache.query(mc.NODE_MEMORY_USAGE, None, now - 60, now).latest()
        )
        usage_pct = node_used * 100 // capacity
        if usage_pct < s.memory_evict_threshold_percent:
            return
        lower_pct = s.memory_evict_lower_percent or max(
            s.memory_evict_threshold_percent - 2, 0
        )
        to_release = node_used - capacity * lower_pct // 100
        released = 0
        for pod in self.ctx.be_pods(sort_for_eviction=True, sort_by="memory"):
            if released >= to_release:
                break
            pod_mem = int(
                self.ctx.cache.query(
                    mc.POD_MEMORY_USAGE, {"pod_uid": pod.uid}, now - 60, now
                ).latest()
            )
            if pod_mem <= 0:
                # no sample yet: credit the declared request so a missing
                # metric can't turn one needed eviction into evict-everything
                pod_mem = int(pod.requests.get(
                    ext.RESOURCE_BATCH_MEMORY, pod.requests.get("memory", 0)
                ))
            if self.evictor.evict(pod, "evictPodMemoryPressure"):
                released += pod_mem
