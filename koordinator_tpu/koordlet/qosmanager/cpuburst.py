"""CPU burst (reference: ``qosmanager/plugins/cpuburst/cpu_burst.go``).

Two mechanisms per the CPUBurstStrategy policy:

- **cpu.cfs_burst_us** (kernel CFS burst): burstable slack =
  ``limit * cpu_burst_percent% * period``; lets a container briefly exceed
  quota using banked idle time.
- **cfs quota burst**: when a container is being throttled and the node share
  pool is calm (usage below ``share_pool_threshold_percent``), scale its cfs
  quota up (x1.2 per tick, capped at ``limit * cfs_quota_burst_percent%``);
  scale back toward the base quota once the node heats up or the burst period
  expires.

Policies: none | cpuBurstOnly | cfsQuotaBurstOnly | auto (both).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.qosmanager.framework import StrategyContext
from koordinator_tpu.koordlet.resourceexecutor import ResourceUpdate
from koordinator_tpu.koordlet.statesinformer import PodMeta
from koordinator_tpu.koordlet.system import cgroup as cg

CFS_PERIOD_US = 100_000
QUOTA_SCALE_UP_RATIO = 1.2


@dataclasses.dataclass
class _BurstState:
    base_quota_us: int
    current_quota_us: int
    burst_since: Optional[float] = None


class CPUBurst:
    name = "cpuburst"
    interval_seconds = 1.0
    feature_gate = "CPUBurst"

    def __init__(self, ctx: StrategyContext):
        self.ctx = ctx
        self._state: dict[str, _BurstState] = {}

    def enabled(self) -> bool:
        return self.ctx.node_slo().cpu_burst_strategy.policy != "none"

    def _pod_cpu_limit_milli(self, pod: PodMeta) -> int:
        return int(pod.limits.get("cpu", 0))

    def _node_calm(self, threshold_pct: int) -> bool:
        capacity = self.ctx.node_cpu_capacity_milli()
        if capacity <= 0:
            return False
        now = self.ctx.clock()
        used = int(
            self.ctx.cache.query(mc.NODE_CPU_USAGE, None, now - 60, now).latest()
            * 1000
        )
        return used * 100 // capacity < threshold_pct

    def update(self) -> None:
        strategy = self.ctx.node_slo().cpu_burst_strategy
        do_burst = strategy.policy in ("cpuBurstOnly", "auto")
        do_quota = strategy.policy in ("cfsQuotaBurstOnly", "auto")
        now = self.ctx.clock()
        calm = self._node_calm(strategy.share_pool_threshold_percent)
        live: set[str] = set()

        for pod in self.ctx.states.get_all_pods():
            if pod.qos_class.is_best_effort or not pod.is_running:
                continue  # burst is for LS/LSR pods with CPU limits
            limit_milli = self._pod_cpu_limit_milli(pod)
            if limit_milli <= 0:
                continue
            live.add(pod.uid)
            rel = pod.cgroup_dir(self.ctx.cfg)
            if do_burst:
                burst_us = (
                    limit_milli * strategy.cpu_burst_percent // 100
                    * CFS_PERIOD_US // 1000
                )
                self.ctx.executor.update(
                    ResourceUpdate(cg.CPU_CFS_BURST, rel, str(burst_us))
                )
            if do_quota:
                self._reconcile_quota(pod, rel, limit_milli, strategy, calm, now)

        for uid in [u for u in self._state if u not in live]:
            del self._state[uid]

    def _reconcile_quota(self, pod: PodMeta, rel: str, limit_milli: int,
                         strategy, calm: bool, now: float) -> None:
        base_quota = limit_milli * CFS_PERIOD_US // 1000
        max_quota = base_quota * strategy.cfs_quota_burst_percent // 100
        state = self._state.get(pod.uid)
        if state is None:
            state = self._state[pod.uid] = _BurstState(base_quota, base_quota)

        throttled = self.ctx.cache.query(
            mc.CONTAINER_CPU_THROTTLED, {"pod_uid": pod.uid}, now - 60, now
        ).latest()

        expired = (
            strategy.cfs_quota_burst_period_seconds >= 0
            and state.burst_since is not None
            and now - state.burst_since > strategy.cfs_quota_burst_period_seconds
        )
        if throttled > 0 and calm and not expired:
            new_quota = min(int(state.current_quota_us * QUOTA_SCALE_UP_RATIO),
                            max_quota)
            if state.burst_since is None:
                state.burst_since = now
        elif not calm or expired:
            # scale back down toward base once the node heats up
            new_quota = max(int(state.current_quota_us / QUOTA_SCALE_UP_RATIO),
                            base_quota)
            if new_quota == base_quota:
                state.burst_since = None
        else:
            new_quota = state.current_quota_us
        if new_quota != state.current_quota_us:
            from koordinator_tpu import metrics

            metrics.cpu_burst_total.inc(labels={
                "direction": "up" if new_quota > state.current_quota_us
                else "down"})
            state.current_quota_us = new_quota
            self.ctx.executor.update(
                ResourceUpdate(cg.CPU_CFS_QUOTA, rel, str(new_quota))
            )
