"""Relocatable host-filesystem roots (reference: ``util/system/config.go``,
``common_linux.go`` path helpers, and ``util_test_tool.go`` test redirection).

A single process-global :class:`SystemConfig` holds the mount points of every
kernel interface the agent touches. Production uses the real roots; tests
install a config rooted in a tempdir and write fake kernel files.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path

#: kubepods cgroup sub-trees per QoS class, cgroup-v1 layout names.
KUBE_ROOT_NAME = "kubepods"
KUBE_BURSTABLE_NAME = "burstable"
KUBE_BESTEFFORT_NAME = "besteffort"


@dataclasses.dataclass
class SystemConfig:
    """Mount points + layout knobs for the host kernel interfaces."""

    cgroup_root: str = "/sys/fs/cgroup"
    proc_root: str = "/proc"
    sys_root: str = "/sys"
    resctrl_root: str = "/sys/fs/resctrl"
    var_run_root: str = "/var/run/koordinator"
    use_cgroup_v2: bool = False
    #: systemd-style slice names (kubepods.slice) vs cgroupfs (kubepods)
    cgroup_driver_systemd: bool = False

    # ---- cgroup path layout -------------------------------------------------

    def _kube_component(self, name: str) -> str:
        if not self.cgroup_driver_systemd:
            return name
        if name == KUBE_ROOT_NAME:
            return "kubepods.slice"
        return f"kubepods-{name}.slice"

    def kube_qos_dir(self, qos: str) -> str:
        """Relative cgroup dir for a kubelet QoS tier.

        qos in {"guaranteed", "burstable", "besteffort"}; guaranteed pods live
        directly under the kubepods root (kubelet convention).
        """
        root = self._kube_component(KUBE_ROOT_NAME)
        if qos == "guaranteed":
            return root
        return os.path.join(root, self._kube_component(qos))

    def pod_cgroup_dir(self, qos: str, pod_uid: str) -> str:
        """Relative cgroup dir of one pod sandbox."""
        if self.cgroup_driver_systemd:
            prefix = {
                "guaranteed": "kubepods",
                "burstable": "kubepods-burstable",
                "besteffort": "kubepods-besteffort",
            }[qos]
            leaf = f"{prefix}-pod{pod_uid.replace('-', '_')}.slice"
        else:
            leaf = f"pod{pod_uid}"
        return os.path.join(self.kube_qos_dir(qos), leaf)

    def container_cgroup_dir(self, qos: str, pod_uid: str, container_id: str) -> str:
        """Relative cgroup dir of one container (containerd cri layout)."""
        pod_dir = self.pod_cgroup_dir(qos, pod_uid)
        if self.cgroup_driver_systemd:
            return os.path.join(pod_dir, f"cri-containerd-{container_id}.scope")
        return os.path.join(pod_dir, container_id)

    def cgroup_abs_path(self, subsystem: str, rel_dir: str, filename: str = "") -> str:
        """Absolute path of a cgroup file. On v2 the subsystem level vanishes
        (unified hierarchy); on v1 it is the first path component."""
        if self.use_cgroup_v2:
            parts = [self.cgroup_root, rel_dir]
        else:
            parts = [self.cgroup_root, subsystem, rel_dir]
        if filename:
            parts.append(filename)
        return os.path.join(*parts)

    # ---- procfs / sysfs -----------------------------------------------------

    def proc_path(self, *parts: str) -> str:
        return os.path.join(self.proc_root, *parts)

    def sys_path(self, *parts: str) -> str:
        return os.path.join(self.sys_root, *parts)


_CONFIG = SystemConfig()


def get_config() -> SystemConfig:
    return _CONFIG


def set_config(cfg: SystemConfig) -> SystemConfig:
    """Install a new process-global config; returns the previous one."""
    global _CONFIG
    prev, _CONFIG = _CONFIG, cfg
    return prev


def make_test_config(root: str | Path, use_cgroup_v2: bool = False) -> SystemConfig:
    """A config fully rooted under ``root`` (the FileTestUtil equivalent)."""
    root = str(root)
    return SystemConfig(
        cgroup_root=os.path.join(root, "cgroup"),
        proc_root=os.path.join(root, "proc"),
        sys_root=os.path.join(root, "sys"),
        resctrl_root=os.path.join(root, "resctrl"),
        var_run_root=os.path.join(root, "var-run"),
        use_cgroup_v2=use_cgroup_v2,
    )
