"""PSI (pressure stall information) parsing (reference: ``util/system/psi.go``).

PSI files look like::

    some avg10=0.00 avg60=0.00 avg300=0.00 total=123456
    full avg10=0.00 avg60=0.00 avg300=0.00 total=12345

cpu.pressure has no ``full`` line on older kernels; parsing tolerates that.
"""

from __future__ import annotations

import dataclasses

from koordinator_tpu.koordlet.system import cgroup
from koordinator_tpu.koordlet.system.config import SystemConfig, get_config


@dataclasses.dataclass(frozen=True)
class PSILine:
    avg10: float = 0.0
    avg60: float = 0.0
    avg300: float = 0.0
    total_us: int = 0


@dataclasses.dataclass(frozen=True)
class PSIStats:
    some: PSILine = PSILine()
    full: PSILine = PSILine()
    full_supported: bool = False


def parse_psi(content: str) -> PSIStats:
    some, full, has_full = PSILine(), PSILine(), False
    for line in content.splitlines():
        parts = line.split()
        if not parts:
            continue
        kv = dict(p.split("=", 1) for p in parts[1:] if "=" in p)
        try:
            parsed = PSILine(
                avg10=float(kv.get("avg10", 0)),
                avg60=float(kv.get("avg60", 0)),
                avg300=float(kv.get("avg300", 0)),
                total_us=int(kv.get("total", 0)),
            )
        except ValueError:
            continue
        if parts[0] == "some":
            some = parsed
        elif parts[0] == "full":
            full, has_full = parsed, True
    return PSIStats(some=some, full=full, full_supported=has_full)


@dataclasses.dataclass(frozen=True)
class PSIByResource:
    cpu: PSIStats
    mem: PSIStats
    io: PSIStats


def read_psi(rel_dir: str, cfg: SystemConfig | None = None) -> PSIByResource:
    """Read all three pressure files of one cgroup dir."""
    cfg = cfg or get_config()

    def one(res) -> PSIStats:
        try:
            return parse_psi(cgroup.cgroup_read(res, rel_dir, cfg))
        except OSError:
            return PSIStats()

    return PSIByResource(
        cpu=one(cgroup.CPU_PRESSURE),
        mem=one(cgroup.MEMORY_PRESSURE),
        io=one(cgroup.IO_PRESSURE),
    )
