"""procfs/sysfs readers (reference: ``util/system/common_linux.go``,
``lscpu.go``, ``meminfo.go``, ``stat.go``): node CPU/memory usage and the
CPU/NUMA topology the NUMA-aware scheduler consumes.
"""

from __future__ import annotations

import dataclasses
import os

from koordinator_tpu.koordlet.system.config import SystemConfig, get_config

JIFFIES_PER_SEC = 100  # USER_HZ


@dataclasses.dataclass(frozen=True)
class CPUStat:
    """Aggregate jiffies from the first line of /proc/stat."""

    user: int = 0
    nice: int = 0
    system: int = 0
    idle: int = 0
    iowait: int = 0
    irq: int = 0
    softirq: int = 0
    steal: int = 0

    @property
    def used_jiffies(self) -> int:
        # usage = everything but idle/iowait (reference GetCPUStatUsageTicks).
        return (
            self.user + self.nice + self.system + self.irq + self.softirq + self.steal
        )

    @property
    def total_jiffies(self) -> int:
        return self.used_jiffies + self.idle + self.iowait


def parse_proc_stat(content: str) -> CPUStat:
    for line in content.splitlines():
        parts = line.split()
        if parts and parts[0] == "cpu":
            vals = [int(x) for x in parts[1:9]] + [0] * 8
            return CPUStat(*vals[:8])
    return CPUStat()


def parse_proc_stat_percpu(content: str) -> dict[int, CPUStat]:
    """Per-CPU rows ("cpu0", "cpu1", ...) of /proc/stat (PerCPUMetric)."""
    out: dict[int, CPUStat] = {}
    for line in content.splitlines():
        parts = line.split()
        if (parts and parts[0].startswith("cpu")
                and parts[0] != "cpu" and parts[0][3:].isdigit()):
            vals = [int(x) for x in parts[1:9]] + [0] * 8
            out[int(parts[0][3:])] = CPUStat(*vals[:8])
    return out


def read_cpu_stat(cfg: SystemConfig | None = None) -> CPUStat:
    cfg = cfg or get_config()
    with open(cfg.proc_path("stat")) as f:
        return parse_proc_stat(f.read())


@dataclasses.dataclass(frozen=True)
class MemInfo:
    """Bytes, from /proc/meminfo (kB fields scaled)."""

    total: int = 0
    free: int = 0
    available: int = 0
    buffers: int = 0
    cached: int = 0

    @property
    def used_no_cache(self) -> int:
        """MemTotal - MemAvailable: the reference's node memory usage."""
        return max(0, self.total - self.available)


def parse_meminfo(content: str) -> MemInfo:
    kv: dict[str, int] = {}
    for line in content.splitlines():
        parts = line.replace(":", " ").split()
        if len(parts) >= 2 and parts[1].isdigit():
            kv[parts[0]] = int(parts[1]) * 1024
    return MemInfo(
        total=kv.get("MemTotal", 0),
        free=kv.get("MemFree", 0),
        available=kv.get("MemAvailable", kv.get("MemFree", 0)),
        buffers=kv.get("Buffers", 0),
        cached=kv.get("Cached", 0),
    )


def read_meminfo(cfg: SystemConfig | None = None) -> MemInfo:
    cfg = cfg or get_config()
    with open(cfg.proc_path("meminfo")) as f:
        return parse_meminfo(f.read())


@dataclasses.dataclass
class DiskStat:
    """One device line of /proc/diskstats (sectors are 512-byte units)."""

    device: str
    reads_completed: int
    sectors_read: int
    writes_completed: int
    sectors_written: int
    io_in_progress: int
    io_ticks_ms: int

    @property
    def read_bytes(self) -> int:
        return self.sectors_read * 512

    @property
    def written_bytes(self) -> int:
        return self.sectors_written * 512


def parse_diskstats(content: str) -> dict[str, DiskStat]:
    """Whole-disk rows of /proc/diskstats (partitions like sda1 are skipped
    with the usual heuristic: trailing digit after a letter-name, except
    nvme0n1-style whole disks)."""
    out: dict[str, DiskStat] = {}
    for line in content.splitlines():
        parts = line.split()
        if len(parts) < 14:
            continue
        name = parts[2]
        if name[-1].isdigit() and not name.startswith(("nvme", "loop", "md")):
            continue  # partition (sda1); nvme whole disks end in digits
        if name.startswith("nvme") and "p" in name[4:]:
            continue  # nvme0n1p1 partition
        out[name] = DiskStat(
            device=name,
            reads_completed=int(parts[3]),
            sectors_read=int(parts[5]),
            writes_completed=int(parts[7]),
            sectors_written=int(parts[9]),
            io_in_progress=int(parts[11]),
            io_ticks_ms=int(parts[12]),
        )
    return out


def read_diskstats(cfg: SystemConfig | None = None) -> dict[str, DiskStat]:
    cfg = cfg or get_config()
    with open(cfg.proc_path("diskstats")) as f:
        return parse_diskstats(f.read())


# ---- cpuset list format -----------------------------------------------------


def parse_cpu_list(spec: str, limit: int | None = None) -> list[int]:
    """'0-3,8,10-11' -> [0,1,2,3,8,10,11] (util/cpuset parity).

    ``limit`` bounds the materialized size for callers parsing EXTERNAL
    data (annotations): a corrupt '0-4000000000' raises ValueError before
    expanding instead of exhausting memory."""
    cpus: list[int] = []
    for part in spec.strip().split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo_s, hi_s = part.split("-", 1)
            lo, hi = int(lo_s), int(hi_s)
            if limit is not None and (hi < lo or hi - lo + 1 > limit):
                raise ValueError(f"cpu range too wide: {part}")
            cpus.extend(range(lo, hi + 1))
        else:
            cpus.append(int(part))
        if limit is not None and len(cpus) > limit:
            raise ValueError("cpu list too large")
    return sorted(set(cpus))


def format_cpu_list(cpus: list[int]) -> str:
    """Inverse of :func:`parse_cpu_list`, producing compact ranges."""
    cpus = sorted(set(cpus))
    if not cpus:
        return ""
    runs: list[tuple[int, int]] = []
    start = prev = cpus[0]
    for c in cpus[1:]:
        if c == prev + 1:
            prev = c
            continue
        runs.append((start, prev))
        start = prev = c
    runs.append((start, prev))
    return ",".join(f"{a}-{b}" if a != b else f"{a}" for a, b in runs)


# ---- CPU/NUMA topology ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    cpu: int
    core: int
    socket: int
    node: int  # NUMA node


@dataclasses.dataclass(frozen=True)
class CPUTopology:
    cpus: tuple[CPUInfo, ...]

    @property
    def num_cpus(self) -> int:
        return len(self.cpus)

    def numa_nodes(self) -> list[int]:
        return sorted({c.node for c in self.cpus})

    def cpus_in_node(self, node: int) -> list[int]:
        return [c.cpu for c in self.cpus if c.node == node]

    def siblings(self, cpu: int) -> list[int]:
        info = next(c for c in self.cpus if c.cpu == cpu)
        return [
            c.cpu
            for c in self.cpus
            if c.core == info.core and c.socket == info.socket
        ]


def read_cpu_topology(cfg: SystemConfig | None = None) -> CPUTopology:
    """Build topology from /sys/devices/system/cpu (lscpu.go equivalent)."""
    cfg = cfg or get_config()
    base = cfg.sys_path("devices", "system", "cpu")
    try:
        with open(os.path.join(base, "online")) as f:
            online = parse_cpu_list(f.read())
    except OSError:
        # No global `online` file (some containers/sysfs mounts omit
        # it): fall back to enumerating cpuN directories, honoring each
        # cpu's own online file — absent means online (kernel semantics:
        # cpu0 commonly has none), "0" means offlined (e.g. disabled SMT
        # siblings) and must stay out of the topology.
        def cpu_online(cpu: int) -> bool:
            try:
                with open(os.path.join(base, f"cpu{cpu}", "online")) as f:
                    return f.read().strip() != "0"
            except OSError:
                return True

        # a missing BASE directory is a misconfigured sys root and must
        # stay loud (the pre-fallback behavior) — only the per-file
        # absence is the benign container case
        online = sorted(
            cpu for cpu in (
                int(e[3:]) for e in os.listdir(base)
                if e.startswith("cpu") and e[3:].isdigit()
            ) if cpu_online(cpu)
        )

    def read_int(path: str, default: int = 0) -> int:
        try:
            with open(path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return default

    infos = []
    for cpu in online:
        topo = os.path.join(base, f"cpu{cpu}", "topology")
        core = read_int(os.path.join(topo, "core_id"), cpu)
        socket = read_int(os.path.join(topo, "physical_package_id"), 0)
        node = 0
        cpu_dir = os.path.join(base, f"cpu{cpu}")
        try:
            for entry in os.listdir(cpu_dir):
                if entry.startswith("node") and entry[4:].isdigit():
                    node = int(entry[4:])
                    break
        except OSError:
            pass
        infos.append(CPUInfo(cpu=cpu, core=core, socket=socket, node=node))
    return CPUTopology(cpus=tuple(infos))


# ---- kidled cold pages ------------------------------------------------------


def parse_idle_page_stats(content: str) -> dict[str, int]:
    """Parse memory.idle_page_stats (kidled_util.go): returns the csei/dsei...
    bucket sums plus 'cold' = pages idle beyond the highest tracked age."""
    out: dict[str, int] = {}
    cold = 0
    for line in content.splitlines():
        parts = line.split()
        if not parts or parts[0].startswith("#"):
            continue
        key = parts[0]
        try:
            vals = [int(x) for x in parts[1:]]
        except ValueError:
            continue
        out[key] = sum(vals)
        if vals and not key.startswith("scan"):
            cold += vals[-1]  # oldest idle-age bucket
    out["cold"] = cold
    return out


def kidled_supported(cfg: SystemConfig | None = None) -> bool:
    cfg = cfg or get_config()
    return os.path.exists(cfg.sys_path("kernel", "mm", "kidled", "scan_period_in_seconds"))
