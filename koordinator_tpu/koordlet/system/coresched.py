"""Core scheduling (SMT sibling isolation) via prctl (reference:
``util/system/core_sched_linux.go`` — PR_SCHED_CORE operations).

Pods in the same group share a core-sched cookie so they may share SMT
siblings; different cookies never co-run on a physical core — the CoreSched
runtime hook uses this to stop BE pods from stealing LS siblings.

The prctl path needs a 5.14+ kernel; everything is gated on
:func:`supported` and degrades to a no-op recorder usable in tests.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os

PR_SCHED_CORE = 62
PR_SCHED_CORE_GET = 0
PR_SCHED_CORE_CREATE = 1
PR_SCHED_CORE_SHARE_TO = 2
PR_SCHED_CORE_SHARE_FROM = 3

PIDTYPE_PID = 0
PIDTYPE_TGID = 1
PIDTYPE_PGID = 2


class CoreSched:
    """Thin prctl wrapper; inject a fake ``prctl`` callable for tests."""

    def __init__(self, prctl=None):
        if prctl is None:
            prctl = self._load_prctl()
        self._prctl = prctl

    @staticmethod
    def _load_prctl():
        try:
            libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)

            def prctl(option, arg2, arg3, arg4, arg5):
                res = libc.prctl(
                    ctypes.c_int(option),
                    ctypes.c_ulong(arg2),
                    ctypes.c_ulong(arg3),
                    ctypes.c_ulong(arg4),
                    ctypes.c_ulong(arg5),
                )
                if res != 0:
                    raise OSError(ctypes.get_errno(), os.strerror(ctypes.get_errno()))
                return res

            return prctl
        except Exception:  # pragma: no cover - no libc
            return None

    def supported(self) -> bool:
        """Probe PR_SCHED_CORE_GET on self (EINVAL => kernel too old)."""
        if self._prctl is None:
            return False
        try:
            cookie = ctypes.c_ulonglong(0)
            self._prctl(
                PR_SCHED_CORE, PR_SCHED_CORE_GET, os.getpid(), PIDTYPE_PID,
                ctypes.addressof(cookie),
            )
            return True
        except OSError:
            return False
        except Exception:
            return False

    def get(self, pid: int) -> int:
        cookie = ctypes.c_ulonglong(0)
        self._prctl(
            PR_SCHED_CORE, PR_SCHED_CORE_GET, pid, PIDTYPE_PID,
            ctypes.addressof(cookie),
        )
        return cookie.value

    def create(self, pid: int, pid_type: int = PIDTYPE_TGID) -> None:
        """Assign a fresh cookie to pid (and its thread group)."""
        self._prctl(PR_SCHED_CORE, PR_SCHED_CORE_CREATE, pid, pid_type, 0)

    def share_to(self, pid: int) -> None:
        """Push the calling task's cookie onto pid."""
        self._prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_TO, pid, PIDTYPE_PID, 0)

    def share_from(self, pid: int) -> None:
        """Pull pid's cookie onto the calling task."""
        self._prctl(PR_SCHED_CORE, PR_SCHED_CORE_SHARE_FROM, pid, PIDTYPE_PID, 0)

    def assign_group(self, leader_pid: int, member_pids: list[int]) -> list[int]:
        """Give leader a fresh cookie, then propagate it to members.
        Returns pids that failed.

        The share_from/share_to dance necessarily adopts the group's cookie
        on the calling task and there is no prctl to restore a zero cookie,
        so the dance runs in a short-lived forked child — the agent's own
        cookie (and its SMT co-runnability) is never touched.
        """
        if self._prctl is None:
            return [leader_pid, *member_pids]
        if not hasattr(os, "fork"):  # pragma: no cover - non-POSIX
            return self._assign_group_inline(leader_pid, member_pids)
        read_fd, write_fd = os.pipe()
        pid = os.fork()
        if pid == 0:  # child
            os.close(read_fd)
            try:
                failed = self._assign_group_inline(leader_pid, member_pids)
                # "ok:" sentinel distinguishes an empty failure list from a
                # child that died before reporting.
                os.write(write_fd, ("ok:" + ",".join(map(str, failed))).encode())
            finally:
                os._exit(0)
        os.close(write_fd)
        try:
            data = os.read(read_fd, 65536).decode()
        finally:
            os.close(read_fd)
            os.waitpid(pid, 0)
        if not data.startswith("ok:"):
            return [leader_pid, *member_pids]
        return [int(x) for x in data[3:].split(",") if x]

    def _assign_group_inline(self, leader_pid: int, member_pids: list[int]) -> list[int]:
        failed: list[int] = []
        try:
            self.create(leader_pid)
            self.share_from(leader_pid)
        except OSError:
            return [leader_pid, *member_pids]
        for pid in member_pids:
            try:
                self.share_to(pid)
            except OSError:
                failed.append(pid)
        return failed


class FakeCoreSched(CoreSched):
    """Records cookies in-memory; used by tests and non-Linux dev hosts."""

    def __init__(self):
        super().__init__(prctl=lambda *a: 0)
        self.cookies: dict[int, int] = {}
        self._next = 1

    def supported(self) -> bool:
        return True

    def get(self, pid: int) -> int:
        return self.cookies.get(pid, 0)

    def create(self, pid: int, pid_type: int = PIDTYPE_TGID) -> None:
        self.cookies[pid] = self._next
        self._next += 1

    def share_from(self, pid: int) -> None:
        self.cookies[os.getpid()] = self.cookies.get(pid, 0)

    def share_to(self, pid: int) -> None:
        self.cookies[pid] = self.cookies.get(os.getpid(), 0)

    def assign_group(self, leader_pid: int, member_pids: list[int]) -> list[int]:
        # Model the forked-child semantics: group gets cookies, the agent's
        # own entry is untouched.
        saved = self.cookies.get(os.getpid())
        failed = self._assign_group_inline(leader_pid, member_pids)
        if saved is None:
            self.cookies.pop(os.getpid(), None)
        else:
            self.cookies[os.getpid()] = saved
        return failed
