"""L0 host/kernel abstraction (reference: ``pkg/koordlet/util/system/``).

Everything here is path-relocatable: all kernel filesystems (cgroupfs, procfs,
sysfs, resctrl) are resolved through :class:`~.config.SystemConfig`, so tests
point the whole layer at a tempdir exactly like the reference's
``util_test_tool.go NewFileTestUtil``.
"""

from koordinator_tpu.koordlet.system.config import SystemConfig, set_config, get_config
from koordinator_tpu.koordlet.system.cgroup import (
    CgroupResource,
    CgroupVersion,
    known_resources,
    resource_by_name,
)
