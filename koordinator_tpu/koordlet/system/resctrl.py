"""resctrl (Intel RDT / AMD QoS) filesystem layer (reference:
``util/system/resctrl_linux.go``): LLC cache-way masks (CAT) and memory
bandwidth percentages (MBA) per control group, plus task binding.

Layout under the resctrl root::

    /sys/fs/resctrl/
        schemata                # root group
        tasks
        LS/ schemata tasks      # koordinator QoS groups: LS / LSR / BE
        BE/ schemata tasks
        info/L3/cbm_mask        # e.g. "fffff" => 20 cache ways
        info/MB/min_bandwidth

Schemata lines look like ``L3:0=fffff;1=fffff`` / ``MB:0=100;1=100``.
"""

from __future__ import annotations

import dataclasses
import os

from koordinator_tpu.koordlet.system.config import SystemConfig, get_config

#: resctrl group names used by the QoS manager (resctrl qos plugin).
GROUP_LS = "LS"
GROUP_LSR = "LSR"
GROUP_BE = "BE"
ALL_GROUPS = (GROUP_LS, GROUP_LSR, GROUP_BE)


@dataclasses.dataclass(frozen=True)
class Schemata:
    """Per-cache-domain L3 masks and MB percents."""

    l3: dict[int, int] = dataclasses.field(default_factory=dict)   # domain -> way bitmask
    mb: dict[int, int] = dataclasses.field(default_factory=dict)   # domain -> percent

    def render(self) -> str:
        lines = []
        if self.l3:
            lines.append(
                "L3:" + ";".join(f"{d}={m:x}" for d, m in sorted(self.l3.items()))
            )
        if self.mb:
            lines.append(
                "MB:" + ";".join(f"{d}={p}" for d, p in sorted(self.mb.items()))
            )
        return "\n".join(lines) + ("\n" if lines else "")


def parse_schemata(content: str) -> Schemata:
    l3: dict[int, int] = {}
    mb: dict[int, int] = {}
    for line in content.splitlines():
        line = line.strip()
        if ":" not in line:
            continue
        kind, rest = line.split(":", 1)
        for entry in rest.split(";"):
            if "=" not in entry:
                continue
            dom, val = entry.split("=", 1)
            try:
                if kind.strip() == "L3":
                    l3[int(dom)] = int(val, 16)
                elif kind.strip() == "MB":
                    mb[int(dom)] = int(val)
            except ValueError:
                continue
    return Schemata(l3=l3, mb=mb)


def percent_to_way_mask(percent: int, num_ways: int) -> int:
    """A contiguous low mask covering >= percent of the cache ways (>=1 way).

    Mirrors CalculateCatL3MaskValue: ways = ceil(num_ways * percent / 100).
    """
    ways = max(1, -(-num_ways * max(0, min(100, percent)) // 100))
    return (1 << ways) - 1


def range_to_way_mask(start_pct: int, end_pct: int, num_ways: int) -> int:
    """Positioned contiguous mask for a CAT percent range [start, end]:
    ways floor(start%) .. ceil(end%)-1. Disjoint ranges (BE [0,30],
    LS [30,100]) yield non-overlapping masks — the point of the range model.
    """
    start_pct = max(0, min(100, start_pct))
    end_pct = max(start_pct, min(100, end_pct))
    # round-half-up both bounds so adjacent ranges meet exactly at the same
    # way boundary (floor/ceil mixing would overlap by one way).
    lo = (num_ways * start_pct + 50) // 100
    hi = (num_ways * end_pct + 50) // 100
    hi = min(hi, num_ways)
    if hi <= lo:  # always at least one way
        hi = min(num_ways, lo + 1)
        lo = hi - 1
    return ((1 << (hi - lo)) - 1) << lo


class ResctrlFS:
    """Handle over the resctrl mount."""

    def __init__(self, cfg: SystemConfig | None = None):
        self.cfg = cfg or get_config()
        self.root = self.cfg.resctrl_root

    def available(self) -> bool:
        return os.path.isfile(os.path.join(self.root, "schemata"))

    def cbm_mask(self) -> int:
        """Full L3 way mask from info/L3/cbm_mask (e.g. 0xfffff)."""
        with open(os.path.join(self.root, "info", "L3", "cbm_mask")) as f:
            return int(f.read().strip(), 16)

    def num_cache_ways(self) -> int:
        return self.cbm_mask().bit_count()

    def cache_domains(self) -> list[int]:
        """Domains present in the root schemata's L3 line."""
        return sorted(self.read_schemata("").l3.keys())

    def group_dir(self, group: str) -> str:
        return os.path.join(self.root, group) if group else self.root

    def ensure_group(self, group: str) -> None:
        os.makedirs(self.group_dir(group), exist_ok=True)

    def read_schemata(self, group: str) -> Schemata:
        with open(os.path.join(self.group_dir(group), "schemata")) as f:
            return parse_schemata(f.read())

    def write_schemata(self, group: str, schemata: Schemata) -> None:
        self.ensure_group(group)
        with open(os.path.join(self.group_dir(group), "schemata"), "w") as f:
            f.write(schemata.render())

    def read_tasks(self, group: str) -> list[int]:
        path = os.path.join(self.group_dir(group), "tasks")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [int(x) for x in f.read().split() if x.strip().isdigit()]

    def add_tasks(self, group: str, pids: list[int]) -> list[int]:
        """Bind pids to a group; returns pids that failed (exited races are
        expected and non-fatal, mirroring the reference's tolerance)."""
        self.ensure_group(group)
        failed = []
        path = os.path.join(self.group_dir(group), "tasks")
        for pid in pids:
            try:
                with open(path, "a") as f:
                    f.write(f"{pid}\n")
            except OSError:
                failed.append(pid)
        return failed

    def apply_qos_policy(
        self, group: str, l3_percent: int, mb_percent: int,
        l3_start_percent: int = 0,
    ) -> Schemata:
        """Program one QoS group from percentage policy (resctrl qos plugin
        semantics): L3 range [start, start+percent] -> positioned way mask
        per domain, MB percent verbatim."""
        ways = self.num_cache_ways()
        mask = range_to_way_mask(
            l3_start_percent, l3_start_percent + l3_percent, ways
        )
        domains = self.cache_domains()
        schemata = Schemata(
            l3={d: mask for d in domains},
            mb={d: max(1, min(100, mb_percent)) for d in domains},
        )
        self.write_schemata(group, schemata)
        return schemata
